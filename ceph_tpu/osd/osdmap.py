"""OSDMap: epoch-versioned cluster map + batched PG->OSD placement.

ref: src/osd/OSDMap.{h,cc} (OSDMap, OSDMap::Incremental). The reference
maps one PG per call (pg_to_up_acting_osds); here the same pipeline —
pps, CRUSH, nonexistent-removal, upmap, up-filter, primary affinity,
pg_temp — runs over an entire seed array at once, with the CRUSH step on
the accelerator and the sparse overrides (upmap/pg_temp, typically a few
thousand entries) as host-side scatters.

Round 6 adds two serving layers above the pipeline so the data path
stops re-entering the mapper per op:

- an EPOCH-KEYED memo cache for small (scalar) lookups — Objecter op
  targeting, mon `osd map`/repair, OSD lazy PG instantiation. Keyed
  (pool, seed), valid for exactly one epoch: any mutation bumps
  ``epoch`` and the next lookup drops the memo wholesale. Code paths
  that mutate placement state WITHOUT bumping the epoch (only
  ``calc_pg_upmaps`` mid-iteration) must bypass it (see
  ``_pipeline_from_crush``) and bump the epoch before returning.
- an attached :class:`~ceph_tpu.osd.osdmap_mapping.OSDMapMapping`
  full-cluster table (``attach_mapping``) serving BULK lookups — OSD
  advance-map, mon sweeps, the balancer — maintained across epochs by
  delta remap instead of full recomputation.

The split ``pg_to_crush_osds`` (pure CRUSH output) and
``_pipeline_from_crush`` (everything after CRUSH) exists because the
two halves invalidate differently: up/down/exists flips, primary
affinity and the override dicts never change CRUSH output, so their
delta remap replays only the cheap numpy pipeline over cached raw rows.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.crush import hash as chash
from ceph_tpu.crush.mapper import Mapper
from ceph_tpu.crush.types import ITEM_NONE, WEIGHT_ONE, CrushMap
from ceph_tpu.osd.types import ObjectLocator, PGPool, pg_t

MAX_PRIMARY_AFFINITY = 0x10000  # ref: CEPH_OSD_MAX_PRIMARY_AFFINITY
DEFAULT_PRIMARY_AFFINITY = 0x10000

# osd_state flags (ref: src/osd/OSDMap.h CEPH_OSD_EXISTS / CEPH_OSD_UP;
# NEARFULL/FULL mirror the per-OSD fullness state the mon derives from
# reported statfs against mon_osd_nearfull_ratio / mon_osd_full_ratio).
STATE_EXISTS = 1
STATE_UP = 2
STATE_NEARFULL = 4
STATE_FULL = 8

# cluster-wide osdmap service flags (ref: src/include/rados.h
# CEPH_OSDMAP_PAUSERD..NOIN — the `ceph osd set <flag>` surface).
# pauserd/pausewr park the respective client op classes; FULL parks
# (or -ENOSPCs, with FULL_TRY) all writes; noout/nodown/noup/noin
# suppress the corresponding mon state transition.
FLAG_PAUSERD = 1 << 0
FLAG_PAUSEWR = 1 << 1
FLAG_FULL = 1 << 2
FLAG_NOOUT = 1 << 3
FLAG_NODOWN = 1 << 4
FLAG_NOUP = 1 << 5
FLAG_NOIN = 1 << 6

FLAG_NAMES = {
    "pauserd": FLAG_PAUSERD, "pausewr": FLAG_PAUSEWR,
    "full": FLAG_FULL, "noout": FLAG_NOOUT, "nodown": FLAG_NODOWN,
    "noup": FLAG_NOUP, "noin": FLAG_NOIN,
}


def flag_names(flags: int) -> str:
    """'noout,full'-style rendering (ref: OSDMap::get_flag_string)."""
    return ",".join(n for n, bit in FLAG_NAMES.items() if flags & bit)


_EMPTY_ROWS = np.empty(0, dtype=np.int64)

# mapping-engine counters (round 6): cache traffic and delta-remap
# volume, exported via prometheus/asok like the crush_mapper set
from ceph_tpu.utils.perf_counters import PerfCountersBuilder as _PCB

PERF = (_PCB("osdmap")
        .add_u64_counter("mapping_cache_hits",
                         "pg lookups served from the epoch cache/table")
        .add_u64_counter("mapping_cache_misses",
                         "pg lookups that entered the mapping pipeline")
        .add_u64_counter("remap_pgs",
                         "PGs delta-remapped by OSDMapMapping.update")
        .add_u64_counter("remap_full_sweeps",
                         "full-pool sweeps by OSDMapMapping.update")
        .add_u64_counter("remap_sharded_sweeps",
                         "full-pool sweeps served by the mesh-sharded "
                         "sweep (crush.sharded_sweep)")
        .create_perf_counters())

_PG_CACHE_MAX_BATCH = 16       # memo-cache only scalar-ish lookups;
                               # bulk callers go to the table/pipeline
_PG_CACHE_MAX_ENTRIES = 1 << 20


def _index_overrides(folded: np.ndarray, pgs) -> dict[int, np.ndarray]:
    """seed -> matching row indices, one O(N log E) pass instead of an
    O(N) scan per override entry."""
    seeds = np.unique(np.array([pg.seed for pg in pgs], dtype=folded.dtype))
    if not seeds.size:
        return {}
    hit = np.flatnonzero(np.isin(folded, seeds))
    out: dict[int, np.ndarray] = {}
    for s in seeds:
        out[int(s)] = hit[folded[hit] == s]
    return out


def _shift_left(rows: np.ndarray) -> np.ndarray:
    """Stable left-compaction of non-NONE entries (replicated up-sets)."""
    w = rows.shape[1]
    keys = np.where(rows == ITEM_NONE, w, 0) + np.arange(w)[None, :]
    order = np.argsort(keys, axis=1, kind="stable")
    return np.take_along_axis(rows, order, axis=1)


@dataclass
class Incremental:
    """A delta between epochs (ref: OSDMap::Incremental — same role,
    dict-shaped instead of encoded)."""

    epoch: int = 0
    new_max_osd: int | None = None
    new_pools: dict[int, PGPool] = field(default_factory=dict)
    old_pools: list[int] = field(default_factory=list)
    new_up: list[int] = field(default_factory=list)
    new_down: list[int] = field(default_factory=list)
    new_weight: dict[int, int] = field(default_factory=dict)
    new_primary_affinity: dict[int, int] = field(default_factory=dict)
    new_pg_temp: dict[pg_t, list[int]] = field(default_factory=dict)
    new_primary_temp: dict[pg_t, int] = field(default_factory=dict)
    new_pg_upmap: dict[pg_t, tuple] = field(default_factory=dict)
    old_pg_upmap: list[pg_t] = field(default_factory=list)
    new_pg_upmap_items: dict[pg_t, list] = field(default_factory=dict)
    old_pg_upmap_items: list[pg_t] = field(default_factory=list)
    new_crush: CrushMap | None = None
    # daemon addresses published at boot (ref: OSDMap::Incremental
    # new_up_client/new_hb_back_up): osd -> (host, port, hb_port)
    new_addrs: dict[int, tuple] = field(default_factory=dict)
    # absolute state overrides (ref: Incremental::new_state xor — here
    # absolute values; used by `osd new` to create EXISTS+down slots)
    new_state: dict[int, int] = field(default_factory=dict)
    # client entity -> absolute expiry (unix); ref: Incremental::
    # new_blocklist — fences evicted/zombie clients at the OSDs
    new_blocklist: dict[str, float] = field(default_factory=dict)
    old_blocklist: list[str] = field(default_factory=list)
    # ref: Incremental::new_up_thru — the mon grants 'osd X was up
    # through epoch E' when a primary asks before activating; peering
    # uses it to decide whether a past interval may have gone active
    new_up_thru: dict[int, int] = field(default_factory=dict)
    # absolute cluster service-flag value (ref: Incremental::new_flags;
    # -1/None = unchanged). Absolute, not xor: the mon serializes flag
    # edits under its proposal lock, and an absolute value survives a
    # replayed incremental.
    new_flags: int | None = None
    # per-entity op QoS profiles (`ceph osd client-profile set/rm`):
    # entity -> (reservation, weight, limit). Rides the map so every
    # OSD's scheduler converges on the same committed table.
    new_client_profiles: dict[str, tuple] = field(default_factory=dict)
    old_client_profiles: list[str] = field(default_factory=list)


class OSDMap:
    """The authoritative placement state at one epoch."""

    def __init__(self, crush: CrushMap, max_osd: int | None = None):
        self.epoch = 1
        self.crush = crush
        self.max_osd = max_osd if max_osd is not None else crush.max_devices
        n = self.max_osd
        self.osd_state = np.full(n, STATE_EXISTS | STATE_UP, dtype=np.int32)
        self.osd_weight = np.full(n, WEIGHT_ONE, dtype=np.int64)
        self.osd_primary_affinity = np.full(n, DEFAULT_PRIMARY_AFFINITY,
                                            dtype=np.int64)
        self.pools: dict[int, PGPool] = {}
        self.pg_temp: dict[pg_t, list[int]] = {}
        self.primary_temp: dict[pg_t, int] = {}
        self.pg_upmap: dict[pg_t, tuple] = {}
        self.pg_upmap_items: dict[pg_t, list] = {}
        # osd -> (host, port, hb_port); ref: OSDMap osd_addrs
        self.osd_addrs: dict[int, tuple] = {}
        # osd -> highest epoch the mon has granted 'alive through'
        # (ref: osd_info_t::up_thru); peering's maybe-went-active test
        self.up_thru: dict[int, int] = {}
        # client entity name -> absolute expiry time (unix). ref:
        # OSDMap blocklist: the cluster-level fence behind MDS client
        # eviction (and rbd exclusive-lock breaking upstream) — OSDs
        # refuse ops from blocklisted entities, so a zombie client
        # whose caps were revoked cannot mutate data after the grant
        # moved on, no matter when it resumes.
        self.blocklist: dict[str, float] = {}
        # cluster-wide service flags (ref: OSDMap::flags — pauserd,
        # pausewr, full, noout, nodown, noup, noin)
        self.flags = 0
        # entity -> (reservation, weight, limit): the committed
        # `osd client-profile` table the OSD schedulers resolve
        # against (never read by placement)
        self.client_profiles: dict[str, tuple] = {}
        self._mappers: dict[int | None, Mapper] = {}
        # bumped whenever the crush TREE changes (not reweights):
        # OSDMapMapping keys its topology-fallback detection on it
        self.crush_version = 1
        # epoch-keyed scalar memo + optional full-cluster table (see
        # module docstring); counters are instance-level so tests can
        # assert on one map, and mirrored into the process-wide PERF
        self._mapping = None
        # optional device mesh (round 10): bulk sweeps route through
        # crush.sharded_sweep — set via attach_mesh, re-attached by an
        # OSDMapMapping(mesh=...) on every update
        self._mesh = None
        self._mesh_min_batch = None
        self._pg_cache: dict[tuple[int, int], tuple] = {}
        self._pg_cache_epoch = self.epoch
        self.mapping_cache_hits = 0
        self.mapping_cache_misses = 0

    def test_flag(self, bit: int) -> bool:
        return bool(self.flags & bit)

    def is_blocklisted(self, name: str, now: float | None = None) -> bool:
        exp = self.blocklist.get(name)
        if exp is None:
            return False
        if now is None:
            import time
            now = time.time()
        return now < exp

    # -- state predicates (array-capable) ---------------------------------
    def exists(self, osd):
        safe = np.clip(osd, 0, self.max_osd - 1)
        ok = (self.osd_state[safe] & STATE_EXISTS) != 0
        return ok & (np.asarray(osd) >= 0) & (np.asarray(osd) < self.max_osd)

    def is_up(self, osd):
        safe = np.clip(osd, 0, self.max_osd - 1)
        return (self.osd_state[safe] & STATE_UP) != 0

    def is_out(self, osd) -> bool:
        return self.osd_weight[osd] == 0

    def is_nearfull(self, osd) -> bool:
        return bool(self.osd_state[osd] & STATE_NEARFULL)

    def is_full(self, osd) -> bool:
        return bool(self.osd_state[osd] & STATE_FULL)

    # -- mutation (each bumps the epoch; ref: OSDMap::apply_incremental) --
    def _dirty(self, crush_changed: bool = False) -> None:
        self.epoch += 1
        if crush_changed:
            self._mappers.clear()
            self.crush_version += 1

    def set_max_osd(self, n: int) -> None:
        grow = n - self.max_osd
        if grow > 0:
            self.osd_state = np.concatenate(
                [self.osd_state, np.zeros(grow, dtype=np.int32)])
            self.osd_weight = np.concatenate(
                [self.osd_weight, np.zeros(grow, dtype=np.int64)])
            self.osd_primary_affinity = np.concatenate(
                [self.osd_primary_affinity,
                 np.full(grow, DEFAULT_PRIMARY_AFFINITY, dtype=np.int64)])
        else:
            self.osd_state = self.osd_state[:n].copy()
            self.osd_weight = self.osd_weight[:n].copy()
            self.osd_primary_affinity = self.osd_primary_affinity[:n].copy()
        self.max_osd = n
        self.crush.max_devices = max(self.crush.max_devices, n)
        self._dirty(crush_changed=True)

    def create_osd(self, osd: int, weight: int = WEIGHT_ONE) -> None:
        if osd >= self.max_osd:
            self.set_max_osd(osd + 1)
        self.osd_state[osd] = STATE_EXISTS | STATE_UP
        self.osd_weight[osd] = weight
        self._dirty()

    def mark_up(self, osd: int) -> None:
        self.osd_state[osd] |= STATE_UP
        self._dirty()

    def mark_down(self, osd: int) -> None:
        self.osd_state[osd] &= ~STATE_UP
        self._dirty()

    def mark_out(self, osd: int) -> None:
        self.set_weight(osd, 0)

    def mark_in(self, osd: int) -> None:
        self.set_weight(osd, WEIGHT_ONE)

    def set_weight(self, osd: int, weight: int) -> None:
        """The in/out reweight (16.16), consumed by CRUSH's is_out check."""
        self.osd_weight[osd] = weight
        for mp in self._mappers.values():
            mp.set_device_weights(self._device_weights())
        self._dirty()

    def set_primary_affinity(self, osd: int, aff: int) -> None:
        self.osd_primary_affinity[osd] = aff
        self._dirty()

    def insert_crush_item(self, osd: int, weight: int,
                          bucket_id: int) -> None:
        """create + link an OSD into the CRUSH tree (the `ceph osd crush
        add` path: CrushWrapper::insert_item)."""
        from ceph_tpu.crush import builder
        if osd >= self.max_osd:
            self.set_max_osd(osd + 1)
            self.epoch -= 1
        self.osd_state[osd] = STATE_EXISTS | STATE_UP
        self.osd_weight[osd] = WEIGHT_ONE
        builder.insert_item(self.crush, osd, weight, bucket_id)
        self.crush.max_devices = max(self.crush.max_devices, self.max_osd)
        self._dirty(crush_changed=True)

    def remove_crush_item(self, osd: int) -> None:
        """unlink + mark gone (ref: CrushWrapper::remove_item +
        OSDMap rm)."""
        from ceph_tpu.crush import builder
        builder.remove_item(self.crush, osd)
        self.osd_state[osd] = 0
        self.osd_weight[osd] = 0
        self._dirty(crush_changed=True)

    def set_crush(self, crush: CrushMap) -> None:
        self.crush = crush
        if crush.max_devices > self.max_osd:
            self.set_max_osd(crush.max_devices)
        self._dirty(crush_changed=True)

    def add_pool(self, pool: PGPool) -> PGPool:
        self.pools[pool.id] = pool
        self._dirty()
        return pool

    def apply_incremental(self, inc: Incremental) -> None:
        """ref: OSDMap::apply_incremental."""
        if inc.epoch and inc.epoch != self.epoch + 1:
            raise ValueError(
                f"incremental epoch {inc.epoch} != {self.epoch + 1}")
        if inc.new_crush is not None:
            self.crush = inc.new_crush
            self._mappers.clear()
            self.crush_version += 1
        if inc.new_max_osd is not None:
            self.set_max_osd(inc.new_max_osd)
            self.epoch -= 1  # counted once below
        for pid in inc.old_pools:
            self.pools.pop(pid, None)
        self.pools.update(inc.new_pools)
        for o, st in inc.new_state.items():
            self.osd_state[o] = st
        for o in inc.new_up:
            self.osd_state[o] |= STATE_EXISTS | STATE_UP
        for o in inc.new_down:
            self.osd_state[o] &= ~STATE_UP
        for o, w in inc.new_weight.items():
            self.osd_weight[o] = w
        for o, a in inc.new_primary_affinity.items():
            self.osd_primary_affinity[o] = a
        for pg, osds in inc.new_pg_temp.items():
            if osds:
                self.pg_temp[pg] = list(osds)
            else:
                self.pg_temp.pop(pg, None)
        for pg, p in inc.new_primary_temp.items():
            if p >= 0:
                self.primary_temp[pg] = p
            else:
                self.primary_temp.pop(pg, None)
        self.pg_upmap.update(inc.new_pg_upmap)
        for pg in inc.old_pg_upmap:
            self.pg_upmap.pop(pg, None)
        self.pg_upmap_items.update(inc.new_pg_upmap_items)
        for pg in inc.old_pg_upmap_items:
            self.pg_upmap_items.pop(pg, None)
        self.osd_addrs.update(inc.new_addrs)
        self.up_thru.update(inc.new_up_thru)
        if inc.new_flags is not None and inc.new_flags >= 0:
            self.flags = inc.new_flags
        self.blocklist.update(inc.new_blocklist)
        for name in inc.old_blocklist:
            self.blocklist.pop(name, None)
        self.client_profiles.update(inc.new_client_profiles)
        for name in inc.old_client_profiles:
            self.client_profiles.pop(name, None)
        for mp in self._mappers.values():
            mp.set_device_weights(self._device_weights())
        self.epoch += 1

    # -- mapper -----------------------------------------------------------
    def _device_weights(self) -> np.ndarray:
        w = np.zeros(max(self.crush.max_devices, self.max_osd),
                     dtype=np.int64)
        w[:self.max_osd] = self.osd_weight
        return w

    def _choose_args_key(self, pool_id: int) -> int | None:
        """Weight-set selection: a pool-keyed entry wins, else the
        compat/default set (-1), else none (ref: CrushWrapper::
        choose_args_get_with_fallback)."""
        if pool_id in self.crush.choose_args:
            return pool_id
        if -1 in self.crush.choose_args:
            return -1
        return None

    def attach_mesh(self, mesh, mesh_min_batch: int | None = None):
        """Route bulk mapping sweeps over a device mesh (round 10):
        existing and future Mappers of this map get the mesh attached
        (crush.sharded_sweep serves batches >= mesh_min_batch)."""
        self._mesh = mesh
        self._mesh_min_batch = mesh_min_batch
        for mp in self._mappers.values():
            mp.attach_mesh(mesh, mesh_min_batch)

    def mapper(self, choose_args_key: int | None = None) -> Mapper:
        mp = self._mappers.get(choose_args_key)
        if mp is None:
            mp = Mapper(self.crush,
                        device_weights=self._device_weights(),
                        choose_args=choose_args_key,
                        mesh=self._mesh,
                        mesh_min_batch=self._mesh_min_batch)
            self._mappers[choose_args_key] = mp
        return mp

    def serving_mapper(self, pool_id: int) -> Mapper:
        """THE Mapper pg_to_crush_osds uses for this pool — the single
        authoritative selection site, so callers reading post-sweep
        state (last_map_path for the remap_sharded_sweeps counter and
        crush_sweep span tags) cannot drift from the sweep itself."""
        return self.mapper(self._choose_args_key(pool_id))

    # -- object -> PG ------------------------------------------------------
    def object_locator_to_pg(self, name: str, loc: ObjectLocator) -> pg_t:
        """ref: OSDMap::object_locator_to_pg (raw pg; fold with
        pool.raw_pg_to_pg)."""
        pool = self.pools[loc.pool]
        if loc.hash >= 0:
            ps = loc.hash
        else:
            ps = pool.hash_key(loc.key or name, loc.nspace)
        return pg_t(loc.pool, ps)

    # -- PG -> OSDs, batched ----------------------------------------------
    def pg_to_crush_osds(self, pool_id: int,
                         seeds) -> tuple[np.ndarray, np.ndarray]:
        """PURE CRUSH output (no nonexistent-removal) + pps. This is
        the half of the pipeline that only weight/topology changes can
        invalidate — OSDMapMapping caches it per pool so up/down flips
        and override edits replay just ``_pipeline_from_crush``."""
        raw, pps, _paths = self.pg_to_crush_osds_path(pool_id, seeds)
        return raw, pps

    def pg_to_crush_osds_path(self, pool_id: int, seeds) -> tuple[
            np.ndarray, np.ndarray, tuple[str | None, str | None]]:
        """``pg_to_crush_osds`` plus this sweep's PER-CALL engine
        evidence ``(expected, actual)``: ``expected`` is the serving
        Mapper's pre-run plan (``mapping_path``), ``actual`` the
        engine the call really executed on (``map_pgs_path`` — not the
        racy ``last_map_path`` slot). OSDMapMapping feeds both to the
        daemon's device-runtime monitor so a silent kernel-path
        degradation is a counted per-daemon fact (round 14)."""
        pool = self.pools[pool_id]
        seeds = np.asarray(seeds, dtype=np.uint32)
        pps = pool.raw_pg_to_pps(seeds, xp=np)
        mp = self.serving_mapper(pool.id)
        expected = mp.expected_path(pool.crush_rule, pool.size)
        out, actual = mp.map_pgs_path(pool.crush_rule, pps, pool.size)
        return np.asarray(out), pps, (expected, actual)

    def pg_to_raw_osds(self, pool_id: int,
                       seeds) -> tuple[np.ndarray, np.ndarray]:
        """CRUSH output with nonexistent devices removed
        (ref: OSDMap::pg_to_raw_osds)."""
        pool = self.pools[pool_id]
        raw, pps = self.pg_to_crush_osds(pool_id, seeds)
        return self._remove_nonexistent(pool, raw), pps

    def _remove_nonexistent(self, pool: PGPool, raw: np.ndarray) -> np.ndarray:
        """ref: OSDMap::_remove_nonexistent_osds."""
        bad = (raw != ITEM_NONE) & ~self.exists(raw)
        raw = np.where(bad, ITEM_NONE, raw)
        if pool.can_shift_osds():
            raw = _shift_left(raw)
        return raw

    def _apply_upmap(self, pool: PGPool, seeds: np.ndarray,
                     raw: np.ndarray) -> np.ndarray:
        """Sparse explicit overrides (ref: OSDMap::_apply_upmap)."""
        if not self.pg_upmap and not self.pg_upmap_items:
            return raw
        folded = pool.raw_pg_to_pg(seeds, xp=np)
        rows_of = _index_overrides(
            folded, [pg for pg in self.pg_upmap if pg.pool == pool.id] +
            [pg for pg in self.pg_upmap_items if pg.pool == pool.id])
        # A REJECTED pg_upmap entry settles the PG (the scalar walk
        # returns early); a valid one is applied and then falls through
        # to pg_upmap_items. Only in-range zero-weight targets reject.
        settled: set[int] = set()
        for pg, target in self.pg_upmap.items():
            if pg.pool != pool.id:
                continue
            rows = rows_of.get(pg.seed, _EMPTY_ROWS)
            if not rows.size:
                continue
            if any(o != ITEM_NONE and 0 <= o < self.max_osd and
                   self.osd_weight[o] == 0 for o in target):
                settled.add(pg.seed)
                continue  # reject mappings onto marked-out osds
            row = np.full(raw.shape[1], ITEM_NONE, dtype=raw.dtype)
            row[:min(len(target), raw.shape[1])] = \
                list(target)[:raw.shape[1]]
            raw[rows] = row
        for pg, pairs in self.pg_upmap_items.items():
            if pg.pool != pool.id or pg.seed in settled:
                continue
            rows = rows_of.get(pg.seed, _EMPTY_ROWS)
            for ri in rows:
                row = raw[ri]
                for frm, to in pairs:
                    if to in row:
                        continue
                    if to < 0 or to >= self.max_osd or \
                            self.osd_weight[to] == 0:
                        continue
                    pos = np.flatnonzero(row == frm)
                    if pos.size:
                        row[pos[0]] = to
        return raw

    def _raw_to_up(self, pool: PGPool, raw: np.ndarray) -> np.ndarray:
        """Drop down/gone devices (ref: OSDMap::_raw_to_up_osds)."""
        ok = (raw != ITEM_NONE) & self.exists(raw) & self.is_up(
            np.clip(raw, 0, self.max_osd - 1))
        up = np.where(ok, raw, ITEM_NONE)
        if pool.can_shift_osds():
            up = _shift_left(up)
        return up

    @staticmethod
    def _pick_primary(osds: np.ndarray) -> np.ndarray:
        """First non-NONE entry per row, -1 if none
        (ref: OSDMap::_pick_primary)."""
        valid = osds != ITEM_NONE
        has = valid.any(axis=1)
        pos = np.argmax(valid, axis=1)
        return np.where(has, np.take_along_axis(
            osds, pos[:, None], axis=1)[:, 0], -1)

    def _apply_primary_affinity(self, pps: np.ndarray, up: np.ndarray,
                                primary: np.ndarray) -> np.ndarray:
        """ref: OSDMap::_apply_primary_affinity — hash-gated pass-over of
        low-affinity primaries, vectorized over (pg, slot)."""
        if (self.osd_primary_affinity == DEFAULT_PRIMARY_AFFINITY).all():
            return primary
        valid = up != ITEM_NONE
        safe = np.clip(up, 0, self.max_osd - 1)
        aff = self.osd_primary_affinity[safe]
        h = chash.hash32_2(pps[:, None].astype(np.uint32),
                           up.astype(np.uint32), xp=np).astype(np.int64) >> 16
        accept = valid & ((aff >= MAX_PRIMARY_AFFINITY) | (h < aff))
        any_acc = accept.any(axis=1)
        pos = np.argmax(accept, axis=1)
        cand = np.take_along_axis(up, pos[:, None], axis=1)[:, 0]
        return np.where(any_acc, cand, primary)

    def _get_temp_osds(self, pool: PGPool, seeds: np.ndarray,
                       up: np.ndarray, up_primary: np.ndarray):
        """ref: OSDMap::_get_temp_osds."""
        acting = up.copy()
        acting_primary = up_primary.copy()
        if not self.pg_temp and not self.primary_temp:
            return acting, acting_primary
        folded = pool.raw_pg_to_pg(seeds, xp=np)
        rows_of = _index_overrides(
            folded, [pg for pg in self.pg_temp if pg.pool == pool.id] +
            [pg for pg in self.primary_temp if pg.pool == pool.id])
        for pg, osds in self.pg_temp.items():
            if pg.pool != pool.id:
                continue
            rows = rows_of.get(pg.seed, _EMPTY_ROWS)
            if not rows.size:
                continue
            kept = [o for o in osds if o == ITEM_NONE or bool(
                self.exists(np.asarray(o)))]
            if not any(o != ITEM_NONE for o in kept):
                continue
            row = np.full(acting.shape[1], ITEM_NONE, dtype=acting.dtype)
            row[:min(len(kept), len(row))] = kept[:len(row)]
            acting[rows] = row
            prim = next((o for o in kept if o != ITEM_NONE), -1)
            acting_primary[rows] = prim
        for pg, p in self.primary_temp.items():
            if pg.pool != pool.id:
                continue
            acting_primary[rows_of.get(pg.seed, _EMPTY_ROWS)] = p
        return acting, acting_primary

    def _pipeline_from_crush(self, pool: PGPool, seeds: np.ndarray,
                             craw: np.ndarray, pps: np.ndarray):
        """Everything AFTER the CRUSH step (ref: the tail of
        OSDMap::_pg_to_up_acting_osds): nonexistent-removal -> upmap ->
        up-filter -> primary pick/affinity -> pg_temp/primary_temp.
        ``craw`` is never mutated, so a caller may replay this over
        cached raw rows (OSDMapMapping delta remap, the balancer's
        candidate probes)."""
        raw = self._remove_nonexistent(pool, craw)   # returns a copy
        raw = self._apply_upmap(pool, seeds, raw)
        up = self._raw_to_up(pool, raw)
        up_primary = self._pick_primary(up)
        up_primary = self._apply_primary_affinity(pps, up, up_primary)
        acting, acting_primary = self._get_temp_osds(pool, seeds, up,
                                                     up_primary)
        return up, up_primary, acting, acting_primary

    def _pg_to_up_acting_uncached(self, pool: PGPool, seeds: np.ndarray):
        craw, pps = self.pg_to_crush_osds(pool.id, seeds)
        return self._pipeline_from_crush(pool, seeds, craw, pps)

    def attach_mapping(self, mapping) -> None:
        """Attach an OSDMapMapping whose table (when at this map's
        epoch) serves pg_to_up_acting_osds directly — bulk and scalar
        — without re-entering the mapper."""
        self._mapping = mapping

    def pg_to_up_acting_osds(self, pool_id: int, seeds):
        """The full pipeline (ref: OSDMap::_pg_to_up_acting_osds).

        seeds: (N,) actual pg seeds in [0, pg_num). Returns
        (up (N,size), up_primary (N,), acting, acting_primary).

        Served, in order of preference, from (1) the attached
        OSDMapMapping table when it is at this epoch, (2) the
        epoch-keyed scalar memo for small batches, (3) the pipeline.
        The cache NEVER serves across ``apply_incremental``/any epoch
        bump — the memo is keyed to one epoch and dropped wholesale.
        """
        pool = self.pools[pool_id]
        seeds = np.atleast_1d(np.asarray(seeds, dtype=np.uint32))
        mp = self._mapping
        if mp is not None and mp.serves(self, pool_id):
            self.mapping_cache_hits += len(seeds)
            PERF.inc("mapping_cache_hits", len(seeds))
            return mp.lookup(pool_id, seeds)
        if not len(seeds) or len(seeds) > _PG_CACHE_MAX_BATCH:
            if len(seeds):
                self.mapping_cache_misses += len(seeds)
                PERF.inc("mapping_cache_misses", len(seeds))
            return self._pg_to_up_acting_uncached(pool, seeds)
        if self._pg_cache_epoch != self.epoch:
            self._pg_cache.clear()
            self._pg_cache_epoch = self.epoch
        missing = [int(s) for s in seeds
                   if (pool_id, int(s)) not in self._pg_cache]
        if missing:
            if len(self._pg_cache) > _PG_CACHE_MAX_ENTRIES:
                self._pg_cache.clear()
                # the flush evicted this batch's hit seeds too
                missing = [int(s) for s in seeds]
            self.mapping_cache_misses += len(missing)
            PERF.inc("mapping_cache_misses", len(missing))
            u, upp, a, actp = self._pg_to_up_acting_uncached(
                pool, np.asarray(missing, dtype=np.uint32))
            for i, s in enumerate(missing):
                self._pg_cache[(pool_id, s)] = (
                    tuple(int(o) for o in u[i]), int(upp[i]),
                    tuple(int(o) for o in a[i]), int(actp[i]))
        nhit = len(seeds) - len(missing)
        if nhit:
            self.mapping_cache_hits += nhit
            PERF.inc("mapping_cache_hits", nhit)
        width = max(len(self._pg_cache[(pool_id, int(s))][0])
                    for s in seeds)
        up = np.full((len(seeds), width), ITEM_NONE, dtype=np.int32)
        acting = np.full((len(seeds), width), ITEM_NONE, dtype=np.int32)
        up_primary = np.empty(len(seeds), dtype=np.int64)
        acting_primary = np.empty(len(seeds), dtype=np.int64)
        for i, s in enumerate(seeds):
            cu, cupp, ca, cactp = self._pg_cache[(pool_id, int(s))]
            up[i, :len(cu)] = cu
            acting[i, :len(ca)] = ca
            up_primary[i] = cupp
            acting_primary[i] = cactp
        return up, up_primary, acting, acting_primary

    def pg_to_acting_osds(self, pool_id: int, seeds):
        _, _, acting, acting_primary = self.pg_to_up_acting_osds(pool_id,
                                                                 seeds)
        return acting, acting_primary

    def pg_to_acting_primary(self, pool_id: int, seed: int):
        """Scalar (acting list, acting_primary) for one PG — the
        data-path op-targeting shape (Objecter _calc_target, mon
        repair/`osd map`). Served from the epoch-keyed cache, so
        steady-state client ops never re-enter the mapper.

        The acting list is POSITION-LOSSY: ITEM_NONE holes are
        filtered out, so for EC pools list index is NOT shard id —
        callers needing shard positions must use
        ``pg_to_up_acting_osds`` (which keeps the placeholders)."""
        _, _, acting, actp = self.pg_to_up_acting_osds(
            pool_id, [int(seed)])
        return [int(o) for o in acting[0] if o != ITEM_NONE], \
            int(actp[0])

    def map_pool(self, pool_id: int):
        """All PGs of a pool in one call -> (up, up_primary, acting,
        acting_primary), shape (pg_num, ...)."""
        pool = self.pools[pool_id]
        return self.pg_to_up_acting_osds(
            pool_id, np.arange(pool.pg_num, dtype=np.uint32))

    # -- utilization ------------------------------------------------------
    def pool_utilization(self, pool_id: int) -> np.ndarray:
        """PG count per OSD for one pool (the CrushTester aggregate,
        ref: src/crush/CrushTester.cc test aggregation)."""
        up, _, _, _ = self.map_pool(pool_id)
        flat = up[up != ITEM_NONE]
        return np.bincount(flat, minlength=self.max_osd)

    # -- upmap balancer ----------------------------------------------------
    def _crush_parents(self) -> dict[int, int]:
        parents: dict[int, int] = {}
        for b in self.crush.buckets.values():
            for child in b.items:
                parents[child] = b.id
        return parents

    def _failure_domain_of(self, parents: dict[int, int], osd: int,
                           fd_type: int) -> int:
        """Ancestor bucket of `osd` at fd_type (the chooseleaf domain);
        the osd itself when fd_type is 0/absent."""
        if fd_type <= 0:
            return osd
        node = osd
        while node in parents:
            node = parents[node]
            b = self.crush.buckets.get(node)
            if b is not None and b.type == fd_type:
                return node
        return osd

    def _rule_failure_domain(self, ruleno: int) -> int:
        """The separation type the rule's choose steps enforce."""
        from ceph_tpu.crush.types import (
            OP_CHOOSELEAF_FIRSTN, OP_CHOOSELEAF_INDEP, OP_CHOOSE_FIRSTN,
            OP_CHOOSE_INDEP)
        fd = 0
        for s in self.crush.rules[ruleno].steps:
            if s.op in (OP_CHOOSELEAF_FIRSTN, OP_CHOOSELEAF_INDEP,
                        OP_CHOOSE_FIRSTN, OP_CHOOSE_INDEP):
                fd = max(fd, s.arg2)
        return fd

    def calc_pg_upmaps(self, pool_ids=None, max_deviation: int = 5,
                       max_iterations: int = 200,
                       inc: "Incremental | None" = None) -> int:
        """Generate pg_upmap_items flattening the PG distribution.

        ref: src/osd/OSDMap.cc OSDMap::calc_pg_upmaps — the mgr
        balancer's upmap mode. Same shape as upstream: compute per-OSD
        deviation from the weight-proportional target, then repeatedly
        move one PG shard from the most-overfull OSD to an underfull one
        via a pg_upmap_items pair, preferring to DROP an existing upmap
        entry that feeds the overfull OSD before adding new ones. Every
        candidate move is validated by remapping the PG through the full
        pipeline (no duplicate OSDs, no holes, failure-domain separation
        preserved — upstream delegates that to crush->try_remap_rule).

        Batched twist: placement is computed once per pool with the
        vectorized mapper; counts update incrementally per move.

        Returns the number of upmap changes recorded (and applied to this
        map; pass ``inc`` to also record them Incremental-style).
        """
        pools = {pid: self.pools[pid]
                 for pid in (pool_ids or self.pools)}
        if not pools:
            return 0
        parents = self._crush_parents()

        # per-osd weight share: crush weight x reweight (out osds get 0).
        # A device's crush weight lives in its parent bucket's weights
        # slot (ref: crush_bucket.weights), not on the device itself.
        crush_w = np.zeros(self.max_osd, dtype=np.float64)
        for b in self.crush.buckets.values():
            for child, w in zip(b.items, b.weights):
                if 0 <= child < self.max_osd:
                    crush_w[child] = w / WEIGHT_ONE
        base_w = np.zeros(self.max_osd, dtype=np.float64)
        for o in range(self.max_osd):
            if not self.exists(np.asarray(o)) or self.osd_weight[o] == 0:
                continue
            base_w[o] = crush_w[o] * (self.osd_weight[o] / WEIGHT_ONE)

        # Initial placement + per-pg bookkeeping. The balancer iterates
        # on the MAPPING TABLE, not the mapper (round 6): the pure
        # CRUSH output per pool is computed ONCE (or served from an
        # attached OSDMapMapping) — pg_upmap_items edits never change
        # CRUSH output, so every candidate-move probe below replays
        # only the numpy post-CRUSH pipeline over the cached raw row
        # instead of dispatching a one-lane device program (this was
        # the whole seconds_per_iteration at 10k OSDs).
        up_by_pool: dict[int, np.ndarray] = {}
        craw_by_pool: dict[int, np.ndarray] = {}
        pps_by_pool: dict[int, np.ndarray] = {}
        counts = np.zeros(self.max_osd, dtype=np.int64)
        for pid in pools:
            pool = pools[pid]
            seeds = np.arange(pool.pg_num, dtype=np.uint32)
            mtab = self._mapping
            if mtab is not None and mtab.serves(self, pid) and \
                    mtab.crush_raw(pid) is not None:
                craw = mtab.crush_raw(pid)
                pps = pool.raw_pg_to_pps(seeds, xp=np)
            else:
                craw, pps = self.pg_to_crush_osds(pid, seeds)
            craw_by_pool[pid] = craw
            pps_by_pool[pid] = pps
            up, _, _, _ = self._pipeline_from_crush(pool, seeds, craw,
                                                    pps)
            up_by_pool[pid] = up
            flat = up[up != ITEM_NONE]
            counts += np.bincount(flat, minlength=self.max_osd)
        total = int(counts.sum())
        if total == 0 or base_w.sum() == 0:
            return 0
        target = base_w / base_w.sum() * total

        def deviation():
            dev = counts - target
            dev[base_w == 0] = 0            # out osds: not balanceable
            return dev

        def remap_pg(pid, seed):
            # post-CRUSH pipeline only — reads the MUTATED upmap dicts
            # against the cached raw row, bit-identical to a full
            # pg_to_up_acting_osds call (and deliberately NOT the memo
            # cache: the epoch has not been bumped yet)
            sarr = np.asarray([seed], dtype=np.uint32)
            up, _, _, _ = self._pipeline_from_crush(
                pools[pid], sarr, craw_by_pool[pid][seed:seed + 1],
                pps_by_pool[pid][seed:seed + 1])
            return up[0]

        changes = 0
        for _ in range(max_iterations):
            dev = deviation()
            over = int(np.argmax(dev))
            # both tails count (upstream fills underfull OSDs from the
            # most-loaded ones even when no OSD exceeds +max_deviation)
            if dev[over] <= max_deviation and \
                    dev.min() >= -max_deviation:
                break
            under_order = np.argsort(dev)
            moved = False
            # candidate PGs currently holding a shard on `over`
            for pid, up in up_by_pool.items():
                pool = pools[pid]
                fd_type = self._rule_failure_domain(pool.crush_rule)
                rows = np.flatnonzero((up == over).any(axis=1))
                for row in rows:
                    pg = pg_t(pid, int(row))
                    if pg in self.pg_upmap:
                        continue    # full override settles the PG; items
                    pairs = self.pg_upmap_items.get(pg, [])
                    # prefer reverting an existing remap feeding `over`
                    reverted = [p for p in pairs if p[1] != over]
                    if len(reverted) != len(pairs):
                        if reverted:
                            self.pg_upmap_items[pg] = reverted
                        else:
                            self.pg_upmap_items.pop(pg, None)
                        new_row = remap_pg(pid, row)
                        if (inc is not None):
                            if reverted:
                                inc.new_pg_upmap_items[pg] = reverted
                            else:
                                inc.old_pg_upmap_items.append(pg)
                    else:
                        # cheap pre-filters (dup/up/failure-domain) reject
                        # most candidates in O(1); the full pipeline then
                        # confirms — in the common case exactly one
                        # pipeline call per accepted move.
                        new_row = None
                        row_domains = {
                            self._failure_domain_of(parents, int(o),
                                                    fd_type)
                            for o in up[row] if o != ITEM_NONE and
                            o != over}
                        cur = set(int(o) for o in up[row]
                                  if o != ITEM_NONE)
                        for u in under_order:
                            u = int(u)
                            if base_w[u] == 0:
                                continue
                            if dev[u] >= dev[over] - 1:
                                break   # ascending: no target improves max
                            if u in cur or not bool(
                                    self.is_up(np.asarray(u))):
                                continue
                            if self._failure_domain_of(
                                    parents, u, fd_type) in row_domains:
                                continue
                            self.pg_upmap_items[pg] = pairs + [(over, u)]
                            cand = remap_pg(pid, row)
                            vals = cand[cand != ITEM_NONE]
                            if (cand != ITEM_NONE).all() and \
                                    len(set(vals.tolist())) == len(vals) \
                                    and u in vals and over not in vals:
                                new_row = cand
                                if inc is not None:
                                    inc.new_pg_upmap_items[pg] = \
                                        pairs + [(over, u)]
                                break
                            # pipeline disagreed: roll back
                            if pairs:
                                self.pg_upmap_items[pg] = pairs
                            else:
                                self.pg_upmap_items.pop(pg, None)
                        if new_row is None:
                            continue
                    # bookkeeping: update counts with the actual delta
                    old_row = up[row]
                    for o in old_row[old_row != ITEM_NONE]:
                        counts[o] -= 1
                    for o in new_row[new_row != ITEM_NONE]:
                        counts[o] += 1
                    up_by_pool[pid][row] = new_row
                    changes += 1
                    moved = True
                    break
                if moved:
                    break
            if not moved:
                break
        if changes:
            self._dirty()
        return changes
