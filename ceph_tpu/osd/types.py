"""Placement-group types and pool metadata.

ref: src/osd/osd_types.{h,cc} (pg_t, spg_t, pg_pool_t, object_locator_t)
rebuilt as array-friendly dataclasses: every seed-indexed computation also
accepts arrays so the whole pool maps in one shot.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.crush import hash as chash
from ceph_tpu.osd.str_hash import (
    CEPH_STR_HASH_RJENKINS, str_hash, str_hash_batch,
)

POOL_TYPE_REPLICATED = 1  # ref: pg_pool_t::TYPE_REPLICATED
POOL_TYPE_ERASURE = 3     # ref: pg_pool_t::TYPE_ERASURE

FLAG_HASHPSPOOL = 1 << 2  # ref: pg_pool_t::FLAG_HASHPSPOOL
# pool fullness flags (ref: pg_pool_t::FLAG_FULL / FLAG_FULL_QUOTA):
# FULL is the operator/mon "no more writes to this pool" bit;
# FULL_QUOTA is set by the mon's quota sweep when the pool's aggregate
# usage crosses quota_bytes/quota_objects (writes -EDQUOT / park) and
# cleared by the same sweep once usage drops or the quota is raised.
FLAG_POOL_FULL = 1 << 1
FLAG_POOL_FULL_QUOTA = 1 << 10

# last_backfill watermark bounds (ref: hobject_t::get_max / is_max —
# pg_info_t.last_backfill). Backfill scans the collection in plain
# string-sorted object-name order; "" (MIN) sorts before every name and
# MAX_OID after every name this framework can generate (object names
# are JSON-safe strings; U+FFFF is a noncharacter that never appears in
# them). last_backfill == MAX_OID means "fully backfilled" — the normal
# state of every complete replica.
MIN_OID = ""
MAX_OID = "\uffff"


def ceph_stable_mod(x, b, bmask, xp=np):
    """ref: src/include/ceph_hash.h ceph_stable_mod — the split-aware mod
    that keeps objects stable while pg_num grows toward a power of two."""
    if xp is None:  # plain ints
        return x & bmask if (x & bmask) < b else x & (bmask >> 1)
    x = xp.asarray(x)
    return xp.where((x & bmask) < b, x & bmask, x & (bmask >> 1))


def calc_mask(n: int) -> int:
    """pg_num -> pg_num_mask (ref: pg_pool_t::calc_pg_masks)."""
    if n <= 0:
        return 0
    return (1 << (n - 1).bit_length()) - 1


@dataclass(frozen=True)
class pg_t:
    """ref: osd_types.h struct pg_t (pool id + placement seed)."""

    pool: int
    seed: int

    def __str__(self) -> str:
        return f"{self.pool}.{self.seed:x}"

    @classmethod
    def parse(cls, s: str) -> "pg_t":
        pool, _, seed = s.partition(".")
        return cls(int(pool), int(seed, 16))


@dataclass(frozen=True)
class spg_t:
    """Shard-qualified PG for EC pools (ref: osd_types.h struct spg_t)."""

    pgid: pg_t
    shard: int = -1  # NO_SHARD

    def __str__(self) -> str:
        if self.shard < 0:
            return str(self.pgid)
        return f"{self.pgid}s{self.shard}"


@dataclass(frozen=True)
class ObjectLocator:
    """ref: osd_types.h object_locator_t."""

    pool: int
    key: str = ""
    nspace: str = ""
    hash: int = -1  # explicit hash position overrides name hashing


@dataclass
class PGPool:
    """ref: osd_types.h pg_pool_t — the subset placement consumes."""

    id: int
    pg_num: int = 64
    pgp_num: int | None = None
    type: int = POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    crush_rule: int = 0
    flags: int = FLAG_HASHPSPOOL
    object_hash: int = CEPH_STR_HASH_RJENKINS
    erasure_code_profile: str = ""
    name: str = ""
    pg_temp_primaries_first: bool = False
    extra: dict = field(default_factory=dict)
    # pool quotas (ref: pg_pool_t::quota_max_bytes/quota_max_objects;
    # `ceph osd pool set-quota`): 0 = unlimited. The mon compares the
    # pool's aggregate pg stats against these on tick and toggles
    # FLAG_POOL_FULL_QUOTA in the next incremental.
    quota_bytes: int = 0
    quota_objects: int = 0
    # PG merge barrier (ref: pg_pool_t::pg_num_pending): a pg_num
    # DECREASE commits in two phases — first pg_num_pending (+ the
    # pgp_num fold, so sources migrate onto their fold targets), then
    # pg_num itself once every source PG has quiesced and reported
    # ready-to-merge. 0 = no merge pending. Placement NEVER reads this
    # field — clients keep folding by pg_num until the decrease lands.
    pg_num_pending: int = 0
    # pool-level op QoS (ref: the mClock pool profile options
    # osd_mclock_scheduler_* per-pool overrides; `ceph osd pool set
    # qos_reservation|qos_weight|qos_limit`): every client queue in
    # this pool without a per-entity `osd client-profile` inherits
    # these dmClock parameters. 0 = unset (fall through to the
    # osd_qos_default_* knobs). reservation/limit are ops/s.
    qos_reservation: float = 0.0
    qos_weight: float = 0.0
    qos_limit: float = 0.0

    def __post_init__(self) -> None:
        if self.pgp_num is None:
            self.pgp_num = self.pg_num

    def is_full(self) -> bool:
        """Writes to this pool must park/fail (ref: pg_pool_t::has_flag
        FLAG_FULL|FLAG_FULL_QUOTA checks in Objecter::target_should_be_paused)."""
        return bool(self.flags & (FLAG_POOL_FULL | FLAG_POOL_FULL_QUOTA))

    def is_merge_source(self, seed: int) -> bool:
        """Is this PG folded away by the pending pg_num decrease?
        (ref: pg_t::is_merge_source)"""
        return bool(self.pg_num_pending) and seed >= self.pg_num_pending

    def merge_target(self, seed: int) -> int:
        """The parent a merge-source seed folds into at pg_num_pending
        (ref: pg_t::get_parent under the stable-mod fold)."""
        assert self.pg_num_pending
        return int(ceph_stable_mod(seed, self.pg_num_pending,
                                   calc_mask(self.pg_num_pending),
                                   xp=None))

    # -- masks ------------------------------------------------------------
    @property
    def pg_num_mask(self) -> int:
        return calc_mask(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return calc_mask(self.pgp_num)

    def is_replicated(self) -> bool:
        return self.type == POOL_TYPE_REPLICATED

    def is_erasure(self) -> bool:
        return self.type == POOL_TYPE_ERASURE

    def can_shift_osds(self) -> bool:
        """Replicated sets compact over holes; EC sets are positional
        (ref: pg_pool_t::can_shift_osds)."""
        return self.is_replicated()

    # -- seed math (array-capable) ----------------------------------------
    def raw_pg_to_pg(self, seeds, xp=np):
        """Fold raw seeds onto actual pg_num (ref: pg_pool_t::raw_pg_to_pg)."""
        return ceph_stable_mod(seeds, self.pg_num, self.pg_num_mask, xp=xp)

    def raw_pg_to_pps(self, seeds, xp=np):
        """Placement seed fed to CRUSH (ref: pg_pool_t::raw_pg_to_pps).

        HASHPSPOOL mixes the pool id through rjenkins so co-sized pools
        don't stack their PGs on the same OSDs; legacy adds the pool id.
        """
        folded = ceph_stable_mod(seeds, self.pgp_num, self.pgp_num_mask,
                                 xp=xp)
        if self.flags & FLAG_HASHPSPOOL:
            if xp is None:
                return int(chash.hash32_2(np.uint32(folded),
                                          np.uint32(self.id), xp=np))
            return chash.hash32_2(folded, xp.full_like(
                xp.asarray(folded), self.id), xp=xp).astype(xp.uint32)
        return folded + self.id

    def hash_key(self, key: str | bytes, nspace: str | bytes = "") -> int:
        """ref: pg_pool_t::hash_key — 0x1f-joined nspace+key."""
        kb = key.encode() if isinstance(key, str) else key
        nb = nspace.encode() if isinstance(nspace, str) else nspace
        data = nb + b"\x1f" + kb if nb else kb
        return str_hash(self.object_hash, data)

    def hash_keys(self, padded, lengths, xp=np):
        """Batched hash_key over pre-packed (nspace-joined) name bytes."""
        return str_hash_batch(self.object_hash, padded, lengths, xp=xp)
