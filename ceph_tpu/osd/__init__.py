"""OSDMap-lite: object->PG->OSD placement (ref: src/osd/OSDMap.{h,cc}).

The data path's pure placement math, re-built batch-first: every query
takes an array of PG seeds and returns arrays of OSD sets, so the whole
cluster's placement can be computed in one device program.
"""

from ceph_tpu.osd.str_hash import (  # noqa: F401
    CEPH_STR_HASH_LINUX, CEPH_STR_HASH_RJENKINS,
)
from ceph_tpu.osd.types import (  # noqa: F401
    PGPool, ObjectLocator, pg_t, spg_t,
    POOL_TYPE_REPLICATED, POOL_TYPE_ERASURE,
    FLAG_HASHPSPOOL, ceph_stable_mod,
)
from ceph_tpu.osd.osdmap import OSDMap  # noqa: F401
from ceph_tpu.osd.str_hash import str_hash, str_hash_batch  # noqa: F401
