"""The OSD daemon: boot, map handling, op dispatch, heartbeats, stats.

ref: src/osd/OSD.{h,cc} — the daemon that owns one ObjectStore, two
messengers (client/cluster + heartbeat), a MonClient, and the PG table.
Boot mirrors OSD::init/_send_boot (authenticate, subscribe to maps,
announce addresses, wait to be marked up); map handling mirrors
OSD::handle_osd_map + consume_map (advance every PG, instantiate new
ones — here the whole pool's placement is computed in ONE batched
mapper call instead of per-PG crush lookups); failure detection mirrors
the osd_heartbeat_grace machinery with MOSDFailure reports.
"""

from __future__ import annotations

import asyncio
import json

import numpy as np

from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.mon.client import MonClient
from ceph_tpu.mon.messages import (MOSDBoot, MOSDFailure,
                                   MOSDMarkMeDown, MPGStats)
from ceph_tpu.msg import Dispatcher, EntityAddr, Keyring, Messenger, Policy
from ceph_tpu.os_.objectstore import MemStore, ObjectStore
from ceph_tpu.osd.ec_pg import ECPG
from ceph_tpu.osd.messages import (
    MBackfillReserve, MOSDBackoff, MOSDECSubOpRead, MOSDECSubOpReadReply,
    MOSDECSubOpWrite,
    MOSDECSubOpWriteReply, MOSDMapPing, MOSDOp, MOSDPGBackfill,
    MOSDPGBackfillReply, MOSDPGInfo, MOSDPGPull,
    MOSDPGPush, MOSDPGPushReply, MOSDPGQuery, MOSDPGRepair, MOSDPGScan,
    MOSDPGScanReply, MOSDPing, MOSDRepOp,
    MOSDRepOpReply, MOSDRepScrub, MOSDRepScrubMap, MPGCleanNotice,
    MUTATING_OPS, PING,
    PING_REPLY,
)
from ceph_tpu.osd.pg import PG
from ceph_tpu.osd.recovery import AsyncReserver
from ceph_tpu.osd.scheduler import (OpScheduler, QoSProfile,
                                    SchedulerThrottle, _Grant,
                                    size_scaled_cost)
from ceph_tpu.osd.types import MAX_OID, pg_t
from ceph_tpu.utils.devmon import engine_name as _engine_name
from ceph_tpu.utils.logging import get_logger
from ceph_tpu.utils.op_tracker import OpTracker
from ceph_tpu.utils.perf_counters import PerfCountersBuilder
from ceph_tpu.utils.throttle import MessageThrottle

log = get_logger("osd")


def _boot_crush_mesh(cfg: dict):
    """Mesh provenance (round 15, ROADMAP #1d first slice): the
    ``osd_crush_mesh`` knob decides where this daemon's device mesh
    comes from, so mesh-sharded full-pool sweeps stop requiring
    hand-wiring. ``auto`` builds the local default mesh over every
    visible device when more than one is visible (one device keeps
    the plain path — the sharded sweep needs >1 anyway); ``off``
    (the default) never attaches one. Returns a Mesh or None; any
    backend probe failure degrades to None — mesh attachment is an
    optimization, never a boot dependency."""
    if str(cfg.get("osd_crush_mesh", "off")) != "auto":
        return None
    try:
        import jax
        devices = jax.devices()
        if len(devices) > 1:
            from ceph_tpu.parallel import make_mesh
            return make_mesh(devices)
    except Exception as e:
        log.dout(0, "osd_crush_mesh=auto: mesh probe failed "
                    f"({type(e).__name__}: {str(e)[:120]}) — "
                    "keeping the single-device path")
    return None


# process-wide overload-protection counters (exported via `perf dump`
# + the mgr prometheus module, like osd_recovery's)
OVERLOAD_PERF = (
    PerfCountersBuilder("osd_overload")
    .add_u64_counter("backoffs_sent", "MOSDBackoff BLOCKs sent")
    .add_u64_counter("backoffs_released", "MOSDBackoff UNBLOCKs sent")
    .add_u64_counter("failsafe_rejections",
                     "writes rejected -ENOSPC by the local failsafe")
    .add_u64_counter("throttle_queued",
                     "client ops that waited at the admission throttle")
    .create_perf_counters())


class OSD(Dispatcher):
    def __init__(self, whoami: int, monmap, store: ObjectStore | None = None,
                 keyring: Keyring | None = None,
                 config: dict | None = None):
        self.whoami = whoami
        self.monmap = monmap
        self.store = store or MemStore()
        cfg = config or {}
        self.hb_interval = cfg.get("osd_heartbeat_interval", 0.25)
        self.hb_grace = cfg.get("osd_heartbeat_grace", 1.5)
        self.stats_interval = cfg.get("osd_stats_interval", 0.5)
        self.scrub_interval = cfg.get("osd_scrub_interval", 0.0)
        self.config = cfg
        name = f"osd.{whoami}"
        self.msgr = Messenger(name, keyring=keyring)
        self.msgr.set_policy("osd", Policy.lossless_peer())
        self.msgr.add_dispatcher(self)
        self.hb_msgr = Messenger(name, keyring=keyring)
        self.hb_msgr.add_dispatcher(_HBDispatcher(self))
        self.monc = MonClient(name, monmap, keyring=keyring,
                              messenger=self.msgr)
        # maintain the full-cluster mapping table per epoch: the
        # advance-map sweep in _on_osdmap reads every pool's placement
        # anyway, so the (delta-updated) table replaces those mapper
        # runs rather than adding work
        self.monc.track_mapping = True
        # mesh provenance (round 15): the registered osd_crush_mesh
        # knob attaches the boot-time mesh to the tracked table, which
        # re-attaches it to every map it updates against — sharded
        # sweeps without hand-wiring (ROADMAP #1d)
        self.monc.mapping_mesh = _boot_crush_mesh(cfg)
        self.monc.map_callbacks.append(self._on_osdmap)
        self.osdmap = None
        self.pgs: dict[str, PG] = {}
        self._tid = 0
        # pool id -> snapids whose removed_snaps trim already ran here
        self._snaps_trimmed: dict[int, set[int]] = {}
        self._hb_last_rx: dict[int, float] = {}
        self._hb_reported: dict[int, float] = {}
        self._hb_task: asyncio.Task | None = None
        self._stats_task: asyncio.Task | None = None
        self._scrub_task: asyncio.Task | None = None
        self._stopped = False
        self.up = False
        self._statfs_reported = 0   # last capacity sent monward
        # ref: OSD op tracking + admin socket
        self.op_tracker = OpTracker(
            history_size=cfg.get("osd_op_history_size"),
            slow_op_warn_s=cfg.get("osd_op_complaint_time"))
        # distributed tracing (ref: src/common/tracer.cc in the OSD):
        # spans for sampled ops — queue/execute/repop/objectstore
        # phases — shipped monward on the stats piggyback
        from ceph_tpu.utils.tracing import Tracer
        self.tracer = Tracer(name, cfg)
        # bulk mapping sweeps in the tracked table emit crush_sweep
        # spans (n_pgs/path/n_devices) through the daemon's tracer, so
        # advance-map sweep cost is drill-downable in `trace show`
        self.monc.mapping_tracer = self.tracer
        # device-runtime observability (round 14): this daemon's
        # kernel-path health monitor (per-daemon counter family,
        # register=False like osd_ec_agg — it reaches /metrics only
        # through the report session) wired into the tracked table's
        # sweep sites; the PROCESS monitor gets this daemon's tracer
        # so jit compiles emit `jit_compile` spans that ship monward
        # on the existing stats piggyback
        from ceph_tpu.utils.devmon import DeviceRuntimeMonitor, devmon
        self.devmon = DeviceRuntimeMonitor(
            name="devmon", register=False, config=cfg)
        self.monc.mapping_devmon = self.devmon
        devmon().attach_tracer(self.tracer)
        self._proc_devmon = devmon()
        # per-op-class latency histograms (ref: the OSD's
        # l_osd_op_r/w_latency counters, as real TYPE_HISTOGRAM log2
        # buckets in MICROSECONDS — the prometheus module renders them
        # as le-bucketed series)
        self.perf = (
            PerfCountersBuilder(name)
            .add_histogram("op_r_latency_hist",
                           "read op latency, microseconds "
                           "(log2 buckets)")
            .add_histogram("op_w_latency_hist",
                           "write op latency, microseconds "
                           "(log2 buckets)")
            # round 12: the telemetry plane's rate-queryable op
            # counter plus the objectstore commit/apply time-avgs
            # behind `ceph osd perf` (ref: l_osd_op +
            # os_commit_latency/os_apply_latency in osd_stat_t)
            .add_u64_counter("ops", "client ops completed")
            .add_time_avg("commit_latency",
                          "primary-side objectstore txn commit "
                          "seconds (time-avg)")
            .add_time_avg("apply_latency",
                          "replica-side objectstore txn apply "
                          "seconds (time-avg)")
            .create_perf_counters())
        # daemon -> mgr report session (round 12, ref: MgrClient):
        # the mgrmap subscription finds the active mgr; the reporter
        # ships this daemon's counter schema + value deltas there
        from ceph_tpu.mgr.client import MgrReporter
        self._mgr_reporter = MgrReporter(
            name, self.msgr, lambda: self.monc.mgrmap,
            lambda: [self.perf, self.ec_agg.perf,
                     self.ec_read_agg.perf,
                     *([self.ec_resident.perf]
                       if self.ec_resident is not None else []),
                     # round 20: a BlueStore-backed OSD ships the
                     # shared-blob family (read LIVE off self.store,
                     # so a revive-remount swaps the new instance in)
                     *([self.store.perf]
                       if hasattr(self.store, "perf") else []),
                     self.devmon.perf, self._proc_devmon.perf], cfg)
        self._mgr_report_task: asyncio.Task | None = None
        self._slow_reported = 0     # last slow-op count sent monward
        self._device_reported: dict = {}   # last device_health sent
        self.asok = None
        self._asok_dir = cfg.get("admin_socket_dir")
        # backfill reservations (ref: AsyncReserver /
        # osd_max_backfills): local slots bound how many PGs this OSD
        # backfills AS PRIMARY, remote slots how many it accepts AS
        # TARGET
        max_backfills = cfg.get("osd_max_backfills", 1)
        self.local_reserver = AsyncReserver(max_backfills)
        self.remote_reserver = AsyncReserver(max_backfills)
        # op QoS scheduler (ref: mClockScheduler): the admission path's
        # dmClock-analog — client ops, recovery grants and scrub
        # rounds all dequeue through it (osd_op_queue=fifo reverts to
        # the pre-scheduler FIFO admission loop)
        self.scheduler = OpScheduler(cfg)
        # EC encode aggregator (round 13): concurrent stripe encodes
        # from every ECPG on this OSD coalesce into one padded batched
        # kernel launch per flush window (osd_ec_agg knobs, read LIVE)
        from ceph_tpu.osd.ec_aggregator import ECAggregator
        self.ec_agg = ECAggregator(cfg)
        # EC decode/repair aggregator (round 19): the read-side twin —
        # degraded reads and recovery rebuilds from every ECPG coalesce
        # into one padded decode launch per flush window
        # (osd_ec_read_agg knobs, read LIVE); repair decodes charge the
        # scheduler's `recovery` class so a degraded-read storm can't
        # bypass QoS cost tags
        from ceph_tpu.osd.ec_read_aggregator import ECReadAggregator
        self.ec_read_agg = ECReadAggregator(cfg,
                                            scheduler=self.scheduler)
        # hot-shard residency (round 19): gathered shard batches pin
        # device-side under osd_ec_resident_bytes, version-keyed so
        # writes invalidate by construction (None when disabled —
        # ec_pg probes with getattr)
        self.ec_resident = None
        if int(cfg.get("osd_ec_resident_bytes", 0)) > 0:
            from ceph_tpu.ec.jax_plugin import DeviceShardCache
            self.ec_resident = DeviceShardCache(cfg)
        # recovery QoS: PR 2's side token bucket folded in as the
        # scheduler's `recovery` class (SchedulerThrottle keeps the
        # acquire/release shape every PG call site uses)
        self.recovery_throttle = SchedulerThrottle(
            self.scheduler,
            max_active=cfg.get("osd_recovery_max_active", 8),
            bytes_per_s=cfg.get("osd_recovery_max_bytes", 0),
            config=cfg)
        # client-op admission throttle (ref: OSD client_messenger
        # policy throttles, osd_client_message_cap /
        # osd_client_message_size_cap): ops past the caps queue at
        # admission instead of dispatching, draining as in-flight
        # ops complete (dequeue ORDER is the scheduler's)
        self.client_throttle = MessageThrottle(
            max_ops=int(cfg.get("osd_client_message_cap", 256)),
            max_bytes=int(cfg.get("osd_client_message_size_cap",
                                  500 << 20)))
        self._admit_task: asyncio.Task | None = None
        # per-peer heartbeat round-trip EWMA (µs source for the mon's
        # gray-failure slow-score; ref: the osd_perf commit/apply
        # latencies the reference reports per OSD)
        self._peer_rtt: dict[int, float] = {}
        # oldest UNANSWERED ping send-time per peer (round 18): a
        # frozen-but-connected peer (SIGSTOP) answers nothing, so its
        # RTT EWMA goes stale-LOW — the pending age is the live lower
        # bound on its real round trip and inflates the reported
        # latency until a reply lands
        self._hb_ping_pending: dict[int, float] = {}
        # central-config application state (baselines for `config rm`)
        self._mon_cfg_state: dict = {}
        # proc-backend children set this so mon config also mirrors
        # into the per-process global Config "mon" layer
        self.mirror_global_config = False
        # used-bytes sweep cache: (stamp, used)
        self._used_cache: tuple[float, int] | None = None
        # graceful shutdown in progress: suppresses the
        # wrongly-marked-down re-boot when OUR mark-me-down commits
        self._prepared_to_stop = False

    def store_used_bytes(self) -> int:
        """Local statfs (ref: ObjectStore::statfs): total object bytes
        in the store. O(objects) sweep, cached for half a second —
        callers are the stats loop, the failsafe at op admission and
        backfill_toofull."""
        now = asyncio.get_event_loop().time()
        if self._used_cache is not None and \
                now - self._used_cache[0] < 0.5:
            return self._used_cache[1]
        used = 0
        try:
            for cid in self.store.list_collections():
                for oid in self.store.list_objects(cid):
                    try:
                        used += self.store.stat(cid, oid)
                    except Exception:
                        pass
        except Exception:
            return 0
        self._used_cache = (now, used)
        return used

    def _mapping_status(self) -> dict:
        """Mapping-engine counters for asok ``status``: the epoch
        cache and delta-remap traffic (osdmap), the kernel/pack
        counters (crush_mapper), and this daemon's tracked table."""
        from ceph_tpu.utils.perf_counters import PerfCountersCollection
        coll = PerfCountersCollection.instance()
        out = {}
        for name in ("osdmap", "crush_mapper"):
            pc = coll.get(name)
            if pc is not None:
                out[name] = pc.dump()
        if self.osdmap is not None:
            out["cache_hits"] = self.osdmap.mapping_cache_hits
            out["cache_misses"] = self.osdmap.mapping_cache_misses
        mt = self.monc.mapping_table
        if mt is not None:
            out["table_epoch"] = mt.epoch
        return out

    def _device_status(self) -> dict:
        """The asok ``device`` block / `device-runtime status`
        payload: this daemon's kernel-path health beside the process
        monitor's compile/transfer side (one daemon per process in
        production, so together they ARE the daemon's device view)."""
        from ceph_tpu.utils import crash as _crash
        return {"daemon": self.devmon.dump(),
                "process": self._proc_devmon.dump(),
                "recent_crashes": _crash.recent_crashes()}

    def failsafe_full(self) -> bool:
        """The stale-map-proof last line of defense (ref: OSD
        osd_failsafe_full_ratio check in OSD::check_full_status):
        writes are rejected -ENOSPC at op admission against LOCAL
        statfs — even a client whose map predates the mon's FULL flag
        cannot push this store over the edge, and the reject happens
        before any transaction touches the store (never partially
        applied)."""
        cap = int(self.config.get("osd_capacity_bytes", 0))
        if cap <= 0:
            return False
        ratio = float(self.config.get("osd_failsafe_full_ratio", 0.97))
        return self.store_used_bytes() >= cap * ratio

    def backfill_toofull(self) -> bool:
        """Reject incoming backfill reservations past the full ratio
        (ref: OSDService::check_backfill_full -> backfill_toofull).
        Only meaningful when a capacity is configured — the stores
        this framework runs on have no intrinsic size."""
        cap = int(self.config.get("osd_capacity_bytes", 0))
        if cap <= 0:
            return False
        ratio = float(self.config.get("osd_backfill_full_ratio", 0.85))
        return self.store_used_bytes() >= cap * ratio

    # -- service facade used by PG ----------------------------------------
    def next_tid(self) -> int:
        self._tid += 1
        return self._tid

    def osd_is_up(self, osd: int) -> bool:
        if self.osdmap is None or osd >= self.osdmap.max_osd:
            return False
        return bool(self.osdmap.is_up(np.asarray(osd)))

    def osd_addr(self, osd: int) -> EntityAddr | None:
        ent = self.osdmap.osd_addrs.get(osd) if self.osdmap else None
        return EntityAddr(ent[0], ent[1]) if ent else None

    def osd_hb_addr(self, osd: int) -> EntityAddr | None:
        ent = self.osdmap.osd_addrs.get(osd) if self.osdmap else None
        return EntityAddr(ent[0], ent[2]) if ent and ent[2] else None

    async def send_osd(self, osd: int, msg) -> None:
        addr = self.osd_addr(osd)
        if addr is None:
            raise ConnectionError(f"osd.{osd} has no address")
        await asyncio.wait_for(
            self.msgr.send_message(msg, addr, f"osd.{osd}"),
            timeout=2.0)

    def request_repeer(self, pg: PG, delay: float = 0.5) -> None:
        async def later():
            await asyncio.sleep(delay)
            if pg.state == "peering" and pg.is_primary() and \
                    not self._stopped:
                pg.advance(pg.up, pg.acting, pg.primary, pg.epoch)
        asyncio.ensure_future(later())

    # -- lifecycle ---------------------------------------------------------
    async def _send_boot(self) -> None:
        await self.monc.send_report(MOSDBoot(
            osd=self.whoami, addr_host=self.msgr.addr.host,
            addr_port=self.msgr.addr.port,
            hb_port=self.hb_msgr.addr.port,
            boot_epoch=self.osdmap.epoch if self.osdmap else 0))

    def _apply_config_map(self, cfgmap: dict) -> None:
        """Apply a mon-published central config map (round 18): the
        wire analog of the in-process shared-dict live push, so a
        separate-process OSD follows `config set` without a restart."""
        from ceph_tpu.utils.config import apply_mon_config
        changed = apply_mon_config(
            f"osd.{self.whoami}", cfgmap, self.config,
            self._mon_cfg_state,
            mirror_global=self.mirror_global_config)
        if changed:
            log.dout(10, f"osd.{self.whoami} applied mon config "
                         f"{sorted(changed)}")

    async def boot(self, host: str = "127.0.0.1") -> None:
        """ref: OSD::init + _send_boot."""
        await self.msgr.bind(host, 0)
        await self.hb_msgr.bind(host, 0)
        await self.monc.subscribe("osdmap", 0)
        # monmap following (runtime mon add/rm) + committed-keyring
        # following (auth rotation/revocation reach the daemon)
        await self.monc.subscribe("monmap", 0)
        # mgrmap following: the active mgr's address for the
        # perf-counter report session (re-opened on failover)
        await self.monc.subscribe("mgrmap", 0)
        if self.msgr.keyring is not None:
            await self.monc.subscribe("keyring", 0)
        # central config db (round 18): live knob flips reach this
        # daemon over the wire — the only path a separate-process
        # child has to the shared-dict semantics of the in-proc
        # backend (`config set osd ...` applies without a restart)
        self.monc.config_callbacks.append(self._apply_config_map)
        await self.monc.subscribe("config", 0)
        await self.monc.wait_for_osdmap()
        await self._send_boot()
        # wait until the map shows us up
        deadline = asyncio.get_event_loop().time() + 10.0
        while not self.up:
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(f"osd.{self.whoami} boot timed out")
            await self.monc.subscribe(
                "osdmap", (self.osdmap.epoch + 1) if self.osdmap else 0)
            await asyncio.sleep(0.05)
        if self._asok_dir:
            from ceph_tpu.utils.admin_socket import AdminSocket
            self.asok = AdminSocket(
                f"{self._asok_dir}/osd.{self.whoami}.asok")
            self.asok.register(
                "status", lambda: {
                    "whoami": self.whoami, "up": self.up,
                    "epoch": self.osdmap.epoch if self.osdmap else 0,
                    "num_pgs": len(self.pgs),
                    "pgs": {p: pg.state
                            for p, pg in self.pgs.items()},
                    "client_throttle": self.client_throttle.dump(),
                    "qos": self.scheduler.dump(),
                    "fullness": {
                        "used_bytes": self.store_used_bytes(),
                        "capacity_bytes": int(self.config.get(
                            "osd_capacity_bytes", 0)),
                        "failsafe_full": self.failsafe_full(),
                        "backfill_toofull": self.backfill_toofull()},
                    "mapping": self._mapping_status(),
                    "ec_agg": self.ec_agg.dump(),
                    "ec_read_agg": self.ec_read_agg.dump(),
                    "ec_resident": (self.ec_resident.dump()
                                    if self.ec_resident is not None
                                    else {"enabled": False}),
                    "device": self._device_status(),
                    "mgr_session": self._mgr_reporter.dump()},
                "osd state summary")
            self.asok.register(
                "device-runtime status",
                lambda: self._device_status(),
                "device-runtime observability: engine, kernel-path "
                "launches/mismatches, jit compile count/time, "
                "transfer bytes (daemon + process views)")
            self.asok.register(
                "dump_ops_in_flight",
                self.op_tracker.dump_ops_in_flight,
                "in-flight client ops")
            self.asok.register(
                "dump_historic_ops",
                self.op_tracker.dump_historic_ops,
                "recently completed ops")
            self.asok.register(
                "ops", self.op_tracker.dump_ops_in_flight,
                "in-flight client ops (alias of dump_ops_in_flight)")
            self.asok.register(
                "dump_slow_ops", self.op_tracker.dump_slow_ops,
                "in-flight ops older than the complaint threshold")
            self.asok.register(
                "dump_qos", lambda: {
                    "scheduler": self.scheduler.dump(),
                    "recovery_throttle": self.recovery_throttle.dump(),
                    "peer_rtt_us": {str(o): int(r * 1e6)
                                    for o, r in
                                    sorted(self._peer_rtt.items())}},
                "op QoS scheduler queues, the folded-in recovery "
                "throttle, and per-peer heartbeat RTTs")
            self.asok.register(
                "dump_tracing", self.tracer.dump,
                "completed trace spans (bounded buffer + slow ring) "
                "and the tracer's sampling/retention state")
            self.asok.register(
                "config show", lambda: dict(self.config),
                "daemon configuration")
            self.asok.register(
                "dump_backoffs", lambda: {
                    p: pg.dump_backoffs()
                    for p, pg in self.pgs.items()
                    if pg.backoffs},
                "asserted client backoffs per pg")
            self.asok.register(
                "backfill status", lambda: {
                    "local_reservations": self.local_reserver.dump(),
                    "remote_reservations": self.remote_reserver.dump(),
                    "throttle": self.recovery_throttle.dump(),
                    "pgs": {p: {"state": pg.state,
                                "last_backfill": pg.last_backfill,
                                **pg.backfill_stats,
                                "targets": {
                                    str(o): wm for o, wm in
                                    pg.backfill_targets.items()}}
                            for p, pg in self.pgs.items()
                            if pg.backfill_targets or
                            pg.last_backfill != MAX_OID}},
                "backfill reservations, throttle and per-pg progress")
            await self.asok.start()
        # crash capture (round 14): every long-lived loop carries the
        # top-level exception hook — a loop that dies with a real
        # exception ships a bounded MCrashReport monward instead of
        # leaving a silently half-alive daemon
        from ceph_tpu.utils import crash as _crash
        _name = f"osd.{self.whoami}"
        self._hb_task = _crash.watch(
            asyncio.ensure_future(self._hb_loop()), _name, self.monc,
            where="hb_loop")
        self._stats_task = _crash.watch(
            asyncio.ensure_future(self._stats_loop()), _name,
            self.monc, where="stats_loop")
        self._admit_task = _crash.watch(
            asyncio.ensure_future(self._admit_loop()), _name,
            self.monc, where="admit_loop")
        self._mgr_report_task = _crash.watch(
            asyncio.ensure_future(self._mgr_reporter.loop()), _name,
            self.monc, where="mgr_report_loop")
        if self.scrub_interval > 0:
            self._scrub_task = _crash.watch(
                asyncio.ensure_future(self._scrub_loop()), _name,
                self.monc, where="scrub_loop")
        # clog the boot (ref: OSD::init's "osd.N ... boot" clog line)
        asyncio.ensure_future(self.monc.clog(
            "INF", f"osd.{self.whoami} booted at {self.msgr.addr}"))
        log.dout(1, f"osd.{self.whoami} booted at {self.msgr.addr}")

    async def stop(self, mark_down: bool = False) -> None:
        """``mark_down=True`` is the graceful path (ref: OSD::shutdown
        -> MOSDMarkMeDown): tell the mon we are going so the down
        commits in the next incremental instead of after a full
        heartbeat-grace of client timeouts. The Thrasher kill path
        stays ungraceful by design — it models a crash."""
        if mark_down and self.up and not self._stopped and \
                self.osdmap is not None:
            self._prepared_to_stop = True
            try:
                await self.monc.send_report(MOSDMarkMeDown(
                    osd=self.whoami, epoch=self.osdmap.epoch))
                # the committed map is the ack: our subscription is
                # still live, _on_osdmap flips self.up
                deadline = asyncio.get_event_loop().time() + 3.0
                while self.up and \
                        asyncio.get_event_loop().time() < deadline:
                    await self.monc.subscribe(
                        "osdmap", self.osdmap.epoch + 1)
                    await asyncio.sleep(0.05)
            except Exception as e:
                log.dout(1, f"osd.{self.whoami} mark-me-down failed "
                            f"({e}); stopping anyway")
        self._stopped = True
        cancelled = []
        for task in (self._hb_task, self._stats_task,
                     self._scrub_task, self._admit_task,
                     self._mgr_report_task):
            if task:
                task.cancel()
                cancelled.append(task)
        for pg in self.pgs.values():
            if pg._worker:
                pg._worker.cancel()
                cancelled.append(pg._worker)
            if pg._peering_task:
                pg._peering_task.cancel()
            if pg._backfill_task:
                pg._backfill_task.cancel()
        # let the cancelled workers unwind so their in-flight ops'
        # finally blocks release their throttle slots NOW, then drain
        # every queued-but-never-executed op — a kill mid-admission
        # must not strand MessageThrottle tokens (the Thrasher-exposed
        # leak: queued costs were only released on primaryship loss,
        # never on daemon stop). RE-cancel survivors: pre-3.12
        # asyncio.wait_for can swallow a cancellation that races the
        # inner future's completion, leaving a worker looping back to
        # its queue with the cancel consumed — one more cancel() ends
        # it (seen under the QoS storm's 64-writer flood).
        pending = set(cancelled)
        for _ in range(8):
            if not pending:
                break
            done, pending = await asyncio.wait(pending, timeout=0.5)
            for task in pending:
                task.cancel()
        self.scheduler.drain(release=self._release_admission)
        self.ec_agg.drain()
        self.ec_read_agg.drain()
        if self.ec_resident is not None:
            self.ec_resident.clear()
        for pg in self.pgs.values():
            pg._drain_op_queue()
        if self.asok:
            await self.asok.stop()
        await self.msgr.shutdown()
        await self.hb_msgr.shutdown()

    # -- map handling ------------------------------------------------------
    async def _on_osdmap(self, osdmap) -> None:
        """ref: OSD::handle_osd_map + consume_map."""
        self.osdmap = osdmap
        was_up = self.up
        self.up = self.osd_is_up(self.whoami)
        if was_up and not self.up and not self._stopped and \
                not self._prepared_to_stop:
            # wrongly marked down (ref: OSD::_committed_osd_maps "I was
            # wrongly marked down" -> re-boot): announce ourselves again
            log.dout(1, f"osd.{self.whoami} marked down but alive; "
                        f"re-booting")
            asyncio.ensure_future(self._send_boot())
        by_pool: dict[int, list[PG]] = {}
        for pg in self.pgs.values():
            by_pool.setdefault(pg.pool.id, []).append(pg)
        # pg merging (ref: PG::merge_from on a committed pg_num
        # decrease — the inverse of the split below): every local PG
        # whose seed fell off its pool's new pg_num folds its objects
        # AND log into the stable-mod parent BEFORE anything peers at
        # the new map. Like the split, this is store-derived and runs
        # on every holder of source data — including an OSD that BOOTS
        # after the decrease with stale source collections on disk
        # (the down-during-merge case), which would otherwise strand
        # the folded history. ONE store scan per map advance (not per
        # pool): leftovers are empty on every epoch that didn't merge.
        stale = self._stale_merge_collections(osdmap)
        for pool in osdmap.pools.values():
            if stale.get(pool.id) or any(
                    pg.pgid.seed >= pool.pg_num
                    for pg in by_pool.get(pool.id, [])):
                self._fold_merged_pgs(pool, by_pool,
                                      stale.get(pool.id, []))
            seeds = np.arange(pool.pg_num, dtype=np.uint32)
            up, upp, acting, actp = osdmap.pg_to_up_acting_osds(
                pool.id, seeds)
            mine = np.flatnonzero(
                (acting == self.whoami).any(axis=1) |
                (up == self.whoami).any(axis=1) |
                (actp == self.whoami) | (upp == self.whoami))
            cls = ECPG if pool.is_erasure() else PG
            for s in mine:
                pgid = pg_t(pool.id, int(s))
                if str(pgid) not in self.pgs:
                    pg = self.pgs[str(pgid)] = cls(self, pool, pgid)
                    by_pool.setdefault(pool.id, []).append(pg)
            # pg splitting (ref: OSD::consume_map split tracking): a
            # grown pg_num re-folds object names; every local PG moves
            # its re-folded objects AND log entries into the child
            # BEFORE anything peers at the new map. Runs AFTER child
            # instantiation so split_objects can update the children's
            # in-memory logs (a child instance constructed above loaded
            # its pre-split — possibly empty — persisted log). Besides
            # the in-memory pg_num transition, the (idempotent,
            # store-derived) split runs once per PG instance: an OSD
            # that BOOTS after the increase builds its PGs from the new
            # map and would otherwise never observe a delta, stranding
            # re-folded objects in the parent collection.
            for pg in list(by_pool.get(pool.id, [])):
                if pool.pg_num > pg.pool.pg_num or \
                        not getattr(pg, "_split_checked", False):
                    touched = pg.split_objects(osdmap, pool)
                    pg._split_checked = True
                    # a batched pg_num+pgp_num consume can move a child
                    # away before it ever instantiates here: create the
                    # instance for any child we hold data for, so it
                    # becomes a STRAY that announces itself to the new
                    # primary instead of silently stranding the data
                    for child_cid in touched:
                        if child_cid not in self.pgs:
                            cpg = self.pgs[child_cid] = cls(
                                self, pool, pg_t.parse(child_cid))
                            by_pool[pool.id].append(cpg)
            for pg in by_pool.get(pool.id, []):
                row = pg.pgid.seed
                pg.pool = pool
                # EC sets are positional: holes stay as -1 markers
                pg.advance(
                    [int(o) if o != ITEM_NONE else -1
                     for o in up[row]],
                    [int(o) if o != ITEM_NONE else -1
                     for o in acting[row]],
                    int(actp[row]), osdmap.epoch)
        # drop PGs whose pool vanished
        for pgid_s in [p for p, pg in self.pgs.items()
                       if pg.pool.id not in osdmap.pools]:
            self.pgs.pop(pgid_s)
        self._kick_snap_trim(osdmap, by_pool)

    def _kick_snap_trim(self, osdmap, by_pool: dict) -> None:
        """Consume the pool removed_snaps deletion queue riding the
        osdmap (ref: OSDMap pg_pool_t::removed_snaps + the PG snap
        trimmer wakeup in PeeringState::activate): every snapid newly
        observed as removed gets a background trim pass on each local
        primary PG of the pool. Tracking is in-memory only — a restart
        replays the whole queue, which is safe because trimming is
        idempotent (clones covering nothing are already gone)."""
        for pool in osdmap.pools.values():
            removed = pool.extra.get("removed_snaps") or []
            fresh = [s for s in removed
                     if s not in self._snaps_trimmed.get(pool.id, set())]
            if not fresh:
                continue
            self._snaps_trimmed.setdefault(pool.id, set()).update(fresh)
            pgs = [pg for pg in by_pool.get(pool.id, [])
                   if pg.is_primary() and not pool.is_erasure()]
            if not pgs:
                continue
            batch = int(self.config.get("osd_snap_trim_batch", 16))
            sleep = float(self.config.get("osd_snap_trim_sleep", 0.0))

            async def trim(pgs=pgs, fresh=fresh, batch=batch,
                           sleep=sleep):
                for sid in fresh:
                    for pg in pgs:
                        try:
                            n = await pg.snap_trim_removed(
                                sid, batch, sleep)
                        except Exception as e:   # trim is best-effort
                            log.dout(1, f"snap trim pg {pg.pgid} "
                                        f"snap {sid}: {e!r}")
                            continue
                        if n:
                            log.dout(10, f"snap trim pg {pg.pgid}: "
                                         f"snap {sid}, {n} objects")
            asyncio.ensure_future(trim())

    def _stale_merge_collections(self, osdmap) -> dict[int, list]:
        """ONE pass over the store: pool id -> [(seed, cid)] of
        on-disk collections whose seed fell off the pool's pg_num
        (merge leftovers from a decrease this OSD slept through)."""
        out: dict[int, list] = {}
        for cid in self.store.list_collections():
            pid_s, _, seed_s = cid.partition(".")
            try:
                pid, seed = int(pid_s), int(seed_s, 16)
            except ValueError:
                continue
            pool = osdmap.pools.get(pid)
            if pool is not None and seed >= pool.pg_num:
                out.setdefault(pid, []).append((seed, cid))
        return out

    def _fold_merged_pgs(self, pool, by_pool: dict,
                         stale: list) -> None:
        """Fold every local merge-leftover of ``pool`` (instance or
        stale on-disk collection with seed >= the committed pg_num)
        into its stable-mod parent. The parent is instantiated when
        absent — it may not even be in our acting set (we become a
        STRAY holding merged data, and the existing notify machinery
        announces it to the real primary)."""
        import numpy as np
        cls = ECPG if pool.is_erasure() else PG
        pool_pgs = by_pool.setdefault(pool.id, [])
        leftovers = [pg for pg in pool_pgs
                     if pg.pgid.seed >= pool.pg_num]
        # stale on-disk collections without an instance (booted after
        # the merge committed)
        have = {pg.cid for pg in pool_pgs}
        for seed, cid in stale:
            if cid not in have:
                leftovers.append(cls(self, pool, pg_t(pool.id, seed)))
        for src in leftovers:
            parent_seed = int(pool.raw_pg_to_pg(
                np.asarray([src.pgid.seed]), xp=np)[0])
            parent_cid = str(pg_t(pool.id, parent_seed))
            parent = self.pgs.get(parent_cid)
            if parent is None:
                parent = self.pgs[parent_cid] = cls(
                    self, pool, pg_t.parse(parent_cid))
                pool_pgs.append(parent)
            parent.pool = pool
            parent.merge_from(src)
            self.pgs.pop(src.cid, None)
            if src in pool_pgs:
                pool_pgs.remove(src)

    # -- dispatch ----------------------------------------------------------
    def _pg_for(self, pgid_s: str, create: bool = False) -> PG | None:
        pg = self.pgs.get(pgid_s)
        if pg is None and create and self.osdmap is not None:
            pgid = pg_t.parse(pgid_s)
            pool = self.osdmap.pools.get(pgid.pool)
            if pool is None or pgid.seed >= pool.pg_num:
                # merged-away seed: a stale client (or peer) still
                # folding by the old pg_num must NOT resurrect the
                # source PG — the -11 reply below sends it for a
                # fresh map, which retargets the merged parent
                return None
            cls = ECPG if pool.is_erasure() else PG
            pg = self.pgs[pgid_s] = cls(self, pool, pgid)
            up, upp, acting, actp = self.osdmap.pg_to_up_acting_osds(
                pgid.pool, [pgid.seed])
            pg.advance([int(o) if o != ITEM_NONE else -1
                        for o in up[0]],
                       [int(o) if o != ITEM_NONE else -1
                        for o in acting[0]],
                       int(actp[0]), self.osdmap.epoch)
        return pg

    async def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MOSDMapPing):
            # epoch-barrier probe: report the map we actually serve
            # ops against (ref: the OSD side of epoch barriers)
            from ceph_tpu.osd.messages import MOSDMapPingReply
            await msg.conn.send_message(MOSDMapPingReply(
                tid=msg.tid,
                epoch=self.osdmap.epoch if self.osdmap else 0,
                from_osd=self.whoami))
            return True
        if isinstance(msg, MOSDOp):
            if self.osdmap is not None and \
                    self.osdmap.is_blocklisted(msg.src):
                # cluster-level fence (ref: OSD::ms_handle_fast_connect
                # blocklist check): an evicted/zombie client's ops are
                # refused with EBLOCKLISTED no matter when it resumes
                from ceph_tpu.osd.messages import MOSDOpReply
                await msg.conn.send_message(MOSDOpReply(
                    tid=msg.tid, attempt=getattr(msg, "attempt", 0),
                    result=-108, epoch=self.osdmap.epoch, data=b"",
                    extra=""))
                return True
            if self._op_cap_denied(msg):
                # per-op cap enforcement (PR 7's auth slice deepened):
                # the handshake-authenticated entity's `osd` caps are
                # checked HERE, on the same admission path the
                # scheduler owns — an `osd r`-only entity's write is
                # refused -EPERM before it touches any queue. Capless
                # entities stay unrestricted (legacy boot keys), like
                # the mon-side slice.
                from ceph_tpu.osd.messages import MOSDOpReply
                await msg.conn.send_message(MOSDOpReply(
                    tid=msg.tid, attempt=getattr(msg, "attempt", 0),
                    result=-1, epoch=self.osdmap.epoch
                    if self.osdmap else 0, data=b"", extra=""))
                return True
            pg = self._pg_for(str(pg_t(msg.pool, msg.seed)))
            if pg is None or not pg.is_primary():
                # wrong target: client's map is stale; it will resend
                from ceph_tpu.osd.messages import MOSDOpReply
                await msg.conn.send_message(MOSDOpReply(
                    tid=msg.tid, attempt=getattr(msg, "attempt", 0),
                    result=-11, epoch=self.osdmap.epoch
                    if self.osdmap else 0, data=b"", extra=""))
                return True
            from ceph_tpu.osd.messages import OSD_OP_NOTIFY_ACK
            if msg.op_codes and all(c == OSD_OP_NOTIFY_ACK
                                    for c in msg.op_codes):
                # acks complete a notify the op worker may itself be
                # awaiting — bypass the serialized queue. ONLY pure
                # ack bundles: a mixed bundle with mutating ops must
                # keep the per-PG serialization the queue provides.
                await pg._execute(msg)
                return True
            if any(c in MUTATING_OPS for c in msg.op_codes) and \
                    self.failsafe_full():
                # stale-map-proof failsafe: this store is past
                # osd_failsafe_full_ratio — reject BEFORE any txn is
                # built, whatever epoch (or FULL_TRY flag) the op
                # carries. Nothing is partially applied.
                from ceph_tpu.osd.messages import MOSDOpReply
                OVERLOAD_PERF.inc("failsafe_rejections")
                log.dout(1, f"osd.{self.whoami} failsafe ENOSPC "
                            f"for {msg.oid}")
                await msg.conn.send_message(MOSDOpReply(
                    tid=msg.tid, attempt=getattr(msg, "attempt", 0),
                    result=-28, epoch=self.osdmap.epoch
                    if self.osdmap else 0, data=b"", extra=""))
                return True
            if pg.merge_ready():
                # merge-source quiesce (ref: the not-ready-to-merge op
                # block): once a source reported ready, NEW client ops
                # park via backoff until the pg_num decrease commits —
                # the parked client then retargets the merged parent.
                # This is the data-safety invariant's "parked" half;
                # ops admitted before readiness land in the log and
                # fold into the parent ("land in the merged parent").
                await pg.send_backoff(msg)
                return True
            queue_cap = int(
                self.config.get("osd_pg_op_queue_cap", 512))
            entity = msg.src or "?"
            if not pg.role_active() or \
                    pg.op_queue.qsize() >= queue_cap or \
                    self.scheduler.backlog(
                        ("client", entity, msg.pool)) >= queue_cap or \
                    self.scheduler.queued >= int(self.config.get(
                        "osd_qos_backlog_cap", 4096)):
                # not ready (peering) or saturated — the per-PG queue,
                # this TENANT's admission backlog (the throttle caps
                # dispatched ops below the PG cap, so the backlog is
                # where a flood actually piles up; per-tenant, so a
                # hot tenant's pile-up backs off the hot tenant, not
                # everyone), OR the OSD-WIDE backlog bound (per-tenant
                # caps alone would let 10k distinct tenants hold 10k x
                # queue_cap payloads in memory): backoff instead of
                # queueing unboundedly — the client parks and resends
                # after our UNBLOCK (ref: the PG Backoff machinery)
                await pg.send_backoff(msg)
                return True
            # admission: ops queue at the scheduler (dmClock tags per
            # client/pool queue; FIFO with osd_op_queue=fifo) rather
            # than dispatch (ref: mClockScheduler::enqueue)
            op_span = self.tracer.from_msg(
                "osd_op", msg, tags={"osd": self.whoami,
                                     "oid": msg.oid})
            if op_span is not None:
                # the op's primary-side span opens at admission; its
                # "queue" child covers throttle + pg-queue wait and is
                # closed by the op worker when execution starts
                msg._span = op_span
                msg._queue_span = op_span.child("queue")
            self.scheduler.submit(
                msg, key=("client", entity, msg.pool),
                profile=self._client_profile(entity, pg.pool),
                cost=self._op_cost(msg))
            return True
        if isinstance(msg, MOSDRepOp):
            pg = self._pg_for(msg.pgid, create=True)
            if pg is not None:
                pg.handle_rep_op(msg)
            return True
        if isinstance(msg, MOSDRepOpReply):
            pg = self._pg_for(msg.pgid)
            if pg is not None:
                pg.handle_rep_reply(msg)
            return True
        if isinstance(msg, MOSDECSubOpWrite):
            pg = self._pg_for(msg.pgid, create=True)
            if isinstance(pg, ECPG):
                pg.handle_ec_sub_write(msg)
            else:
                log.dout(1, f"ec sub-write for non-ec pg {msg.pgid}")
                await msg.conn.send_message(MOSDECSubOpWriteReply(
                    tid=msg.tid, result=-22, pgid=msg.pgid,
                    from_osd=self.whoami))
            return True
        if isinstance(msg, MOSDECSubOpWriteReply):
            pg = self._pg_for(msg.pgid)
            if isinstance(pg, ECPG):
                pg.handle_ec_sub_write_reply(msg)
            return True
        if isinstance(msg, MOSDECSubOpRead):
            pg = self._pg_for(msg.pgid, create=True)
            if isinstance(pg, ECPG):
                pg.handle_ec_sub_read(msg)
            else:
                log.dout(1, f"ec sub-read for non-ec pg {msg.pgid}")
            return True
        if isinstance(msg, MOSDECSubOpReadReply):
            pg = self._pg_for(msg.pgid)
            if isinstance(pg, ECPG):
                pg.handle_ec_sub_read_reply(msg)
            return True
        if isinstance(msg, MOSDPGQuery):
            pg = self._pg_for(msg.pgid, create=True)
            if pg is not None:
                pg.handle_pg_query(msg)
            return True
        if isinstance(msg, MOSDPGInfo):
            # create=True: an unsolicited stray NOTIFY may beat this
            # primary's own consume_map to the PG — dropping it loses
            # the only pointer to the data's old location
            pg = self._pg_for(msg.pgid, create=bool(
                getattr(msg, "notify", 0)))
            if pg is not None:
                pg.handle_pg_info(msg)
            return True
        if isinstance(msg, MOSDPGPull):
            pg = self._pg_for(msg.pgid)
            if pg is not None:
                pg.handle_pg_pull(msg)
            return True
        if isinstance(msg, MOSDPGPush):
            pg = self._pg_for(msg.pgid, create=True)
            if pg is not None and pg.apply_push(msg):
                # ack ONLY on durable apply: the primary counts acked
                # pushes as recovered (durability promotion gate)
                await self.send_osd(msg.from_osd, MOSDPGPushReply(
                    pgid=msg.pgid, oid=msg.oid, from_osd=self.whoami))
            return True
        if isinstance(msg, MOSDPGPushReply):
            pg = self._pg_for(msg.pgid)
            if pg is not None:
                pg.handle_push_reply(msg)
            return True
        if isinstance(msg, MPGCleanNotice):
            pg = self._pg_for(msg.pgid)
            if pg is not None:
                pg.handle_clean_notice(msg)
            return True
        if isinstance(msg, MOSDPGScan):
            # create=True: a scan can beat the target's own map
            # consume to a PG it is about to host
            pg = self._pg_for(msg.pgid, create=True)
            if pg is not None:
                pg.handle_pg_scan(msg)
            return True
        if isinstance(msg, MOSDPGScanReply):
            pg = self._pg_for(msg.pgid)
            if pg is not None:
                pg.handle_scan_reply(msg)
            return True
        if isinstance(msg, MOSDPGBackfill):
            pg = self._pg_for(msg.pgid, create=True)
            if pg is not None:
                pg.handle_backfill(msg)
            return True
        if isinstance(msg, MOSDPGBackfillReply):
            pg = self._pg_for(msg.pgid)
            if pg is not None:
                pg.handle_backfill_reply(msg)
            return True
        if isinstance(msg, MBackfillReserve):
            pg = self._pg_for(msg.pgid, create=True)
            if pg is not None:
                pg.handle_backfill_reserve(msg)
            return True
        if isinstance(msg, MOSDBackoff):
            # a client's ACK_BLOCK — informational only (the backoff
            # stays asserted until we UNBLOCK)
            return True
        if isinstance(msg, MOSDPGRepair):
            pg = self._pg_for(msg.pgid)
            if pg is not None and pg.is_primary():
                # ref: the PG_REPAIR scrub flavor: detect + rewrite
                # from the authoritative copy, then re-verify
                asyncio.ensure_future(pg.scrubber.repair())
            return True
        if isinstance(msg, MOSDRepScrub):
            pg = self._pg_for(msg.pgid)
            if pg is not None:
                from ceph_tpu.osd.scrub import build_scrub_map
                await msg.conn.send_message(MOSDRepScrubMap(
                    pgid=msg.pgid, tid=msg.tid, from_osd=self.whoami,
                    scrub_map=build_scrub_map(pg)))
            return True
        if isinstance(msg, MOSDRepScrubMap):
            pg = self._pg_for(msg.pgid)
            if pg is not None and pg._scrubber is not None:
                pg.scrubber.handle_map(msg)
            return True
        return False

    def _op_cap_denied(self, msg) -> bool:
        """Per-op OSD cap check (ref: OSDCap::is_capable, scoped to
        the r/w class): True when the sender has a configured cap
        table whose `osd` spec does not grant the op's class. Capless
        entities are unrestricted — same legacy-boot-key policy as the
        mon command slice."""
        kr = self.msgr.keyring
        if kr is None or not msg.src:
            return False
        caps = kr.caps_of(msg.src)
        if not caps:
            return False
        from ceph_tpu.msg.auth import cap_allows
        need = "w" if any(c in MUTATING_OPS for c in msg.op_codes) \
            else "r"
        return not cap_allows(str(caps.get("osd", "")), need)

    def _op_cost(self, msg) -> float:
        """Size-scaled dmClock cost over the op bundle's bytes, so a
        4 MiB op is charged honestly against 4 KiB ops sharing the
        weight (scheduler.size_scaled_cost — the same divisor the
        recovery throttle charges). Writes carry their bytes in the
        data blobs; READS carry theirs in op_lens with empty blobs —
        both count, or a 4 MiB reader rides at the flat minimum
        (a length-0 whole-object read still does: its size is
        unknowable at admission, the reference mclock limitation)."""
        datas = getattr(msg, "op_datas", ())
        lens = getattr(msg, "op_lens", None) or (0,) * len(datas)
        nbytes = sum(max(len(d), int(ln))
                     for d, ln in zip(datas, lens))
        return size_scaled_cost(self.config, nbytes)

    def _client_profile(self, entity: str, pool) -> QoSProfile:
        """QoS profile resolution for one client op: per-entity
        `osd client-profile` (rides the osdmap) > pool `qos_*` >
        the osd_qos_default_* knobs."""
        om = self.osdmap
        ent = om.client_profiles.get(entity) if om is not None else None
        if ent:
            return QoSProfile(reservation=float(ent[0]),
                              weight=float(ent[1]) or 1.0,
                              limit=float(ent[2]))
        if pool is not None and (pool.qos_reservation or
                                 pool.qos_weight or pool.qos_limit):
            return QoSProfile(reservation=float(pool.qos_reservation),
                              weight=float(pool.qos_weight) or 1.0,
                              limit=float(pool.qos_limit))
        return self.scheduler.default_profile()

    async def _admit_loop(self) -> None:
        """Admission drain: the scheduler decides ORDER (reservation
        -> weight -> limit across client/recovery/scrub queues; plain
        FIFO with osd_op_queue=fifo), the MessageThrottle decides
        VOLUME — a dequeued client op still takes a throttle slot
        before reaching its PG queue, released when the PG op worker
        finishes. Backpressure lands HERE, not on the connection
        reader loop. Recovery/scrub grants resolve inline (their
        concurrency bound is SchedulerThrottle's semaphore)."""
        try:
            while not self._stopped:
                msg, _op_class = await self.scheduler.dequeue()
                if isinstance(msg, _Grant):
                    if not msg.fut.done():
                        msg.fut.set_result(True)
                    continue
                cost = sum(len(d) for d in msg.op_datas)
                if self.client_throttle._would_block(cost):
                    # THIS op's acquire would park (op-count cap or
                    # byte budget — .saturated alone misses the
                    # byte-budget case). Park WITHOUT stalling grants:
                    # a saturated client cap (e.g. ops wedged on a
                    # degraded replica) must not block the recovery
                    # pushes that may be needed to unwedge it —
                    # grants never consume throttle slots, so they
                    # keep flowing while this op waits its turn
                    OVERLOAD_PERF.inc("throttle_queued")
                    acq = asyncio.ensure_future(
                        self.client_throttle.acquire(cost))
                    try:
                        while not acq.done():
                            g = self.scheduler.pop_grant()
                            if isinstance(g, _Grant):
                                if not g.fut.done():
                                    g.fut.set_result(True)
                                continue
                            # sleep until the slot frees OR a new
                            # submission arrives (a grant may ride
                            # it) — no timer polling: clearing the
                            # event first is safe because try_dequeue
                            # scans the queues directly, never the
                            # event
                            self.scheduler._event.clear()
                            ev = asyncio.ensure_future(
                                self.scheduler._event.wait())
                            try:
                                await asyncio.wait(
                                    {acq, ev},
                                    return_when=asyncio
                                    .FIRST_COMPLETED)
                            finally:
                                if not ev.done():
                                    ev.cancel()
                        await acq
                    except asyncio.CancelledError:
                        acq.cancel()
                        try:
                            await acq
                            # the acquire raced the cancel and WON:
                            # give the slot back or it leaks
                            self.client_throttle.release(cost)
                        except asyncio.CancelledError:
                            pass
                        raise
                else:
                    await self.client_throttle.acquire(cost)
                msg._throttle_cost = cost
                pg = self._pg_for(str(pg_t(msg.pool, msg.seed)))
                if pg is None or not pg.is_primary():
                    # the map moved while the op waited for admission
                    self.client_throttle.release(cost)
                    from ceph_tpu.osd.messages import MOSDOpReply
                    try:
                        await msg.conn.send_message(MOSDOpReply(
                            tid=msg.tid,
                            attempt=getattr(msg, "attempt", 0),
                            result=-11,
                            epoch=self.osdmap.epoch
                            if self.osdmap else 0, data=b"", extra=""))
                    except Exception:
                        pass
                    continue
                await pg.queue_op(msg)
        except asyncio.CancelledError:
            pass

    def _release_admission(self, msg) -> None:
        """Release a drained op's admission-throttle slot (no-op for
        ops that never reached the throttle)."""
        cost = getattr(msg, "_throttle_cost", None)
        if cost is not None:
            self.client_throttle.release(cost)

    # -- heartbeats --------------------------------------------------------
    async def _hb_loop(self) -> None:
        """ref: OSD::heartbeat + heartbeat_check. Guard: when OUR event
        loop stalls (e.g. a long jit compile elsewhere in-process), the
        silence is ours, not the peers' — reset rx stamps instead of
        accusing everyone (the reference's equivalent is the grace
        adjustment by osd_heartbeat_stale / clock skew checks)."""
        last_iter = asyncio.get_event_loop().time()
        try:
            while not self._stopped:
                await asyncio.sleep(self.hb_interval)
                if self.osdmap is None:
                    continue
                now = asyncio.get_event_loop().time()
                if now - last_iter > self.hb_grace:
                    for o in list(self._hb_last_rx):
                        self._hb_last_rx[o] = now
                    for o in list(self._hb_ping_pending):
                        # our own stall: don't let pending ages accuse
                        # peers of our silence
                        self._hb_ping_pending[o] = now
                last_iter = now
                for o in range(self.osdmap.max_osd):
                    if o == self.whoami or not self.osd_is_up(o):
                        self._hb_last_rx.pop(o, None)
                        self._peer_rtt.pop(o, None)   # stale evidence
                        self._hb_ping_pending.pop(o, None)
                        continue
                    addr = self.osd_hb_addr(o)
                    if addr is None:
                        continue
                    self._hb_last_rx.setdefault(o, now)
                    try:
                        await asyncio.wait_for(
                            self.hb_msgr.send_message(MOSDPing(
                                op=PING, from_osd=self.whoami,
                                epoch=self.osdmap.epoch,
                                stamp=now), addr, f"osd.{o}"),
                            timeout=1.0)
                        # only the OLDEST outstanding ping is kept: its
                        # age is the peer's unanswered-for window
                        self._hb_ping_pending.setdefault(o, now)
                    except Exception:
                        pass
                    if now - self._hb_last_rx[o] > self.hb_grace and \
                            now - self._hb_reported.get(o, 0) > \
                            self.hb_grace:
                        self._hb_reported[o] = now
                        await self._report_failure(o)
                    elif o in self._hb_reported and \
                            now - self._hb_last_rx[o] <= self.hb_grace:
                        # the peer resumed within grace after we
                        # accused it: withdraw the report (ref:
                        # OSD::send_still_alive) so our stale
                        # accusation can't later pair with another
                        # reporter's and wrongly mark it down
                        self._hb_reported.pop(o, None)
                        await self.monc.send_report(MOSDFailure(
                            target=o, failed_for=0,
                            epoch=self.osdmap.epoch,
                            reporter=f"osd.{self.whoami}", alive=1))
        except asyncio.CancelledError:
            pass

    async def _scrub_loop(self) -> None:
        """Round-robin background scrub (ref: OSD::sched_scrub).
        Each PG's round takes a `scrub`-class grant from the op
        scheduler first (weight-only, `osd_qos_scrub_*`), so scrub is
        background best-effort against client and recovery work."""
        try:
            while not self._stopped:
                await asyncio.sleep(self.scrub_interval)
                for pg in list(self.pgs.values()):
                    # never scrub mid-recovery: legitimately missing
                    # objects would read as inconsistencies
                    if pg.is_primary() and pg.state in ("active",
                                                        "clean"):
                        await self.scheduler.grant("scrub")
                        await pg.scrubber.scrub()
        except asyncio.CancelledError:
            pass

    async def _report_failure(self, target: int) -> None:
        """ref: OSD::send_failures -> MOSDFailure to the mon."""
        await self.monc.send_report(MOSDFailure(
            target=target, failed_for=int(self.hb_grace),
            epoch=self.osdmap.epoch,
            reporter=f"osd.{self.whoami}"))

    def _hb_rx(self, m: MOSDPing) -> None:
        now = asyncio.get_event_loop().time()
        self._hb_last_rx[m.from_osd] = now
        self._hb_ping_pending.pop(m.from_osd, None)
        if m.op == PING_REPLY and m.stamp:
            # gray-failure signal: the PING_REPLY echoes OUR send
            # stamp, so now - stamp is a full round trip through the
            # peer's event loop — a slow-but-alive disk/host inflates
            # it long before heartbeats time out. EWMA smooths
            # scheduler jitter; the mon turns the fleet's reports into
            # a relative slow-score (ref: the osd_perf ping-time data
            # `dump_osd_network` exposes upstream).
            rtt = max(now - m.stamp, 0.0)
            prev = self._peer_rtt.get(m.from_osd)
            self._peer_rtt[m.from_osd] = rtt if prev is None else \
                0.7 * prev + 0.3 * rtt

    # -- stats -------------------------------------------------------------
    async def _stats_loop(self) -> None:
        """ref: OSD::ms_handle / MPGStats reporting loop."""
        try:
            while not self._stopped:
                await asyncio.sleep(self.stats_interval)
                if self.osdmap is None:
                    continue
                # keep subscriptions alive even with nothing to report
                # (2s-throttled, background): our session mon may have
                # died/been removed, taking the subs with it
                self.monc.renew_subs()
                stats = {p: json.dumps(pg.stats()).encode()
                         for p, pg in self.pgs.items()
                         if pg.is_primary()}
                slow = len(self.op_tracker.slow_ops())
                # statfs piggyback (ref: osd_stat_t): the mon derives
                # NEARFULL/FULL state and the cluster FULL flag from
                # it — reported whenever a capacity is configured
                cap = int(self.config.get("osd_capacity_bytes", 0))
                used = self.store_used_bytes() if cap > 0 else 0
                # trace spans ride the stats report (ref: the daemon
                # perf/health reporting the mgr aggregates upstream)
                spans = self.tracer.drain_ship()
                # per-peer heartbeat RTTs (µs) piggyback too: the
                # mon's slow-score sweep needs a FRESH fleet view
                # every tick, so holding rtts forces the report.
                # Pending-ping inflation (round 18): a peer that has
                # stopped answering (SIGSTOP gray failure) would
                # otherwise keep its last — stale-low — EWMA; the
                # oldest unanswered ping's age is the honest floor.
                _hb_now = asyncio.get_event_loop().time()
                peer_lat = {}
                for o in set(self._peer_rtt) | \
                        set(self._hb_ping_pending):
                    r = self._peer_rtt.get(o, 0.0)
                    pend = self._hb_ping_pending.get(o)
                    if pend is not None:
                        r = max(r, _hb_now - pend)
                    peer_lat[str(o)] = int(r * 1e6)
                # device-runtime piggyback (round 14): the cumulative
                # kernel-path/compile/transfer view — reported while
                # it moves, so the mon's per-report deltas track
                # ACTIVE sweep traffic (an idle daemon's unchanged
                # cumulative is delta 0, which heals the warning)
                dh = self.devmon.health_report()
                # EC degrade evidence rides the same piggyback: ops
                # this OSD served from the reference encoder after
                # device retries exhausted (round 16)
                agg = self.ec_agg.perf.dump()
                ragg = self.ec_read_agg.perf.dump()
                dh["ec_fallback_ops"] = int(
                    agg.get("fallback_ops", 0)) + int(
                    ragg.get("fallback_ops", 0))
                dh["ec_flush_failures"] = int(
                    agg.get("flush_failures", 0)) + int(
                    ragg.get("flush_failures", 0))
                # keep reporting until a zero count has been sent: a
                # daemon whose slow ops drained (or whose capacity
                # went back to unbounded) while it held no primary
                # PGs must still clear the mon's warning/utilization
                if not stats and not slow and not cap and not spans \
                        and not peer_lat \
                        and dh == self._device_reported \
                        and not self._slow_reported and \
                        not self._statfs_reported:
                    continue
                await self.monc.send_report(MPGStats(
                    osd=self.whoami, epoch=self.osdmap.epoch,
                    stats=stats, slow_ops=slow,
                    used_bytes=used, capacity_bytes=cap,
                    trace_spans=spans, peer_latency=peer_lat,
                    device_health=dh,
                    device_engine=_engine_name()))
                self._slow_reported = slow
                self._statfs_reported = cap
                self._device_reported = dh
                # merge readiness barrier: re-reported EVERY tick
                # while the decrease is pending, so a mon leader
                # change can't lose the barrier state
                from ceph_tpu.mon.messages import MOSDPGReadyToMerge
                for pg in list(self.pgs.values()):
                    if pg.merge_ready():
                        await self.monc.send_report(
                            MOSDPGReadyToMerge(
                                pgid=pg.cid, epoch=self.osdmap.epoch,
                                from_osd=self.whoami,
                                pending=pg.pool.pg_num_pending))
        except asyncio.CancelledError:
            pass


class _HBDispatcher(Dispatcher):
    """Heartbeat messenger dispatcher (front/back network analog)."""

    def __init__(self, osd: OSD):
        self.osd = osd

    async def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MOSDPing):
            self.osd._hb_rx(msg)
            if msg.op == PING:
                try:
                    await msg.conn.send_message(MOSDPing(
                        op=PING_REPLY, from_osd=self.osd.whoami,
                        epoch=msg.epoch,
                        stamp=msg.stamp))
                except Exception:
                    pass
            return True
        return False
