"""PG: per-placement-group replicated state machine.

ref: src/osd/PG.cc + PeeringState.{h,cc} + PrimaryLogPG.cc — one PG
owns one ObjectStore collection and an ordered op pipeline. The
reference's boost::statechart phases map to:

- ``advance_map``: new acting set from the OSDMap ends the current
  interval (ref: PeeringState::advance_map / start_peering_interval);
- ``peering`` (primary): query every acting peer's info+log, adopt the
  authoritative log (max last_update — ref: find_best_info), merge to
  produce per-peer missing sets (ref: GetMissing), pull what the
  primary itself lacks, then activate;
- ``active``: client ops execute (PrimaryLogPG::execute_ctx):
  writes get an eversion, a pg-log entry, and an ObjectStore
  transaction replicated to acting peers as MOSDRepOp, acked to the
  client when every live acting replica commits
  (ref: ReplicatedBackend::submit_transaction);
- ``recovery``: missing objects are pushed whole at their
  authoritative version (ref: PGBackend::run_recovery_op); when no
  peer is missing anything the PG is clean.

The pg log + per-object versions persist in the collection's
``_pgmeta_`` object (ref: pgmeta_oid omap), so a restarted OSD
re-peers from durable state.
"""

from __future__ import annotations

import asyncio
import json

from ceph_tpu.os_.objectstore import StoreError, Transaction
from ceph_tpu.osd.messages import (
    MOSDOp, MOSDOpReply, MOSDPGInfo, MOSDPGPull, MOSDPGPush,
    MOSDPGPushReply, MOSDPGQuery, MOSDRepOp, MOSDRepOpReply, OSD_OP_DELETE,
    OSD_OP_GETXATTR, OSD_OP_OMAP_GET, OSD_OP_OMAP_SET, OSD_OP_PGLS,
    OSD_OP_OMAP_RM, OSD_OP_READ, OSD_OP_SETXATTR, OSD_OP_STAT,
    OSD_OP_TRUNCATE, OSD_OP_WRITE, OSD_OP_WRITEFULL, OSD_OP_ZERO,
)
from ceph_tpu.osd.pg_log import OP_DELETE, OP_MODIFY, LogEntry, PGLog, \
    eversion
from ceph_tpu.osd.types import pg_t
from ceph_tpu.utils.logging import get_logger

log = get_logger("osd")

PGMETA = "_pgmeta_"


class PG:
    def __init__(self, osd, pool, pgid: pg_t):
        self.osd = osd                    # OSD daemon (service facade)
        self.pool = pool
        self.pgid = pgid
        self.cid = str(pgid)
        self.pg_log = PGLog()
        self.state = "initial"
        self.epoch = 0                    # interval epoch
        self.acting: list[int] = []
        self.up: list[int] = []
        self.primary = -1
        self.last_user_version = 0
        # peering scratch
        self.peer_logs: dict[int, PGLog] = {}
        self.peer_missing: dict[int, dict[str, LogEntry]] = {}
        self.my_missing: dict[str, LogEntry] = {}
        self._peering_task: asyncio.Task | None = None
        self._info_waiter: asyncio.Future | None = None
        # op pipeline
        self.op_queue: asyncio.Queue = asyncio.Queue()
        self._worker: asyncio.Task | None = None
        self._repop_waiters: dict[int, tuple[set[int], asyncio.Future]] = {}
        self._push_waiters: dict[str, asyncio.Future] = {}
        # (client, tid) -> (result, extra): replays of mutating ops whose
        # reply was lost return the recorded outcome instead of
        # re-executing (ref: pg_log_entry_t reqid dedup)
        self._reqid_results: dict[tuple, tuple] = {}
        self.scrub_errors = 0
        self.last_scrub = 0.0
        self._scrubber = None
        self._ensure_collection()
        self._load_meta()

    # -- persistence -------------------------------------------------------
    def _ensure_collection(self) -> None:
        if self.cid not in self.osd.store.list_collections():
            t = Transaction().create_collection(self.cid)
            t.touch(self.cid, PGMETA)
            self.osd.store.queue_transaction(t)

    def _load_meta(self) -> None:
        try:
            omap = self.osd.store.omap_get(self.cid, PGMETA)
        except StoreError:
            return
        blob = omap.get("pg_log")
        if blob:
            self.pg_log = PGLog.decode(blob)
            self.last_user_version = self.pg_log.head.v

    def _meta_txn(self, t: Transaction) -> Transaction:
        t.omap_setkeys(self.cid, PGMETA,
                       {"pg_log": self.pg_log.encode()})
        return t

    @property
    def scrubber(self):
        if self._scrubber is None:
            from ceph_tpu.osd.scrub import Scrubber
            self._scrubber = Scrubber(self)
        return self._scrubber

    def is_primary(self) -> bool:
        return self.primary == self.osd.whoami

    def role_active(self) -> bool:
        return self.state in ("active", "recovering", "clean")

    # -- interval changes --------------------------------------------------
    def advance(self, up: list[int], acting: list[int], primary: int,
                epoch: int) -> None:
        """ref: PeeringState::advance_map — a changed acting set starts
        a new interval; the primary re-peers."""
        changed = (acting != self.acting or primary != self.primary)
        self.up = up
        self.acting = acting
        self.primary = primary
        self.epoch = epoch
        if not changed and self.role_active():
            return
        if self._peering_task:
            self._peering_task.cancel()
            self._peering_task = None
        if self.is_primary():
            self.state = "peering"
            self._peering_task = asyncio.ensure_future(self._peer())
        else:
            self.state = "replica" if self.osd.whoami in acting \
                else "stray"
            if self._worker:
                self._worker.cancel()
                self._worker = None

    def live_acting(self) -> list[int]:
        return [o for o in self.acting
                if o >= 0 and self.osd.osd_is_up(o)]

    # -- peering (primary) -------------------------------------------------
    async def _peer(self) -> None:
        try:
            await self._peer_inner()
        except asyncio.CancelledError:
            pass
        except Exception as e:
            log.dout(1, f"pg {self.pgid} peering failed ({e}); retrying")
            self.state = "peering"
            self.osd.request_repeer(self, delay=0.5)

    async def _peer_inner(self) -> None:
        interval_epoch = self.epoch
        peers = [o for o in self.live_acting() if o != self.osd.whoami]
        self.peer_logs = {}
        if len(self.live_acting()) < self.pool.min_size:
            self.state = "peering"        # undersized: wait for map
            return
        if peers:
            fut = asyncio.get_event_loop().create_future()
            self._info_waiter = fut
            for o in peers:
                await self.osd.send_osd(o, MOSDPGQuery(
                    pgid=self.cid, epoch=interval_epoch,
                    from_osd=self.osd.whoami))
            try:
                await asyncio.wait_for(fut, timeout=3.0)
            except asyncio.TimeoutError:
                pass
            finally:
                self._info_waiter = None
            if set(self.peer_logs) < set(peers):
                # a peer didn't answer; retry soon (map may be stale)
                self.state = "peering"
                self.osd.request_repeer(self, delay=0.5)
                return
        if self.epoch != interval_epoch:
            return                        # superseded interval
        # authoritative log: max head (ref: find_best_info)
        best_osd = self.osd.whoami
        best = self.pg_log
        for o, plog in self.peer_logs.items():
            if plog.head > best.head:
                best, best_osd = plog, o
        if best_osd != self.osd.whoami:
            self.my_missing = self.pg_log.merge(best)
            t = self._meta_txn(Transaction())
            self.osd.store.queue_transaction(t)
            # pull objects the primary itself lacks
            for oid, entry in list(self.my_missing.items()):
                await self._pull(best_osd, oid)
            if self.my_missing:
                # do NOT activate with stale objects: a client read
                # would serve pre-outage data. Retry the interval.
                self.state = "peering"
                self.osd.request_repeer(self, delay=0.5)
                return
        self.last_user_version = max(self.last_user_version,
                                     self.pg_log.head.v)
        # per-peer missing sets (ref: GetMissing)
        self.peer_missing = {
            o: plog.missing_vs(self.pg_log)
            for o, plog in self.peer_logs.items()}
        self.state = "active"
        if self._worker is None:
            self._worker = asyncio.ensure_future(self._op_worker())
        asyncio.ensure_future(self._recover())
        log.dout(5, f"pg {self.pgid} active; acting {self.acting} "
                    f"missing {sum(map(len, self.peer_missing.values()))}")

    def handle_pg_query(self, m: MOSDPGQuery) -> None:
        asyncio.ensure_future(self.osd.send_osd(m.from_osd, MOSDPGInfo(
            pgid=self.cid, epoch=self.epoch, from_osd=self.osd.whoami,
            log=self.pg_log.encode())))

    def handle_pg_info(self, m: MOSDPGInfo) -> None:
        self.peer_logs[m.from_osd] = PGLog.decode(m.log)
        peers = [o for o in self.live_acting() if o != self.osd.whoami]
        if self._info_waiter and not self._info_waiter.done() and \
                set(self.peer_logs) >= set(peers):
            self._info_waiter.set_result(True)

    # -- recovery ----------------------------------------------------------
    async def _pull(self, from_osd: int, oid: str) -> None:
        """Primary pulls an object it is missing (ref: RecoveryOp pull)."""
        fut = asyncio.get_event_loop().create_future()
        self._push_waiters[oid] = fut
        await self.osd.send_osd(from_osd, MOSDPGPull(
            pgid=self.cid, epoch=self.epoch, oid=oid,
            from_osd=self.osd.whoami))
        try:
            await asyncio.wait_for(fut, timeout=3.0)
        except asyncio.TimeoutError:
            log.dout(1, f"pg {self.pgid} pull of {oid} timed out")
        finally:
            self._push_waiters.pop(oid, None)

    def handle_pg_pull(self, m: MOSDPGPull) -> None:
        asyncio.ensure_future(
            self.osd.send_osd(m.from_osd, self.make_push(m.oid)))

    def _object_state(self, oid: str):
        """(exists, data, attrs, omap, version)"""
        try:
            data = self.osd.store.read(self.cid, oid)
            attrs = self.osd.store.getattrs(self.cid, oid)
            omap = self.osd.store.omap_get(self.cid, oid)
        except StoreError:
            return False, b"", {}, {}, eversion()
        vb = attrs.get("_v")
        ver = eversion() if not vb else eversion(
            int.from_bytes(vb[:4], "little"),
            int.from_bytes(vb[4:12], "little"))
        return True, data, attrs, omap, ver

    def make_push(self, oid: str) -> MOSDPGPush:
        exists, data, attrs, omap, ver = self._object_state(oid)
        return MOSDPGPush(
            pgid=self.cid, epoch=self.epoch, oid=oid,
            version_epoch=ver.epoch, version_v=ver.v, exists=exists,
            data=data, attrs=attrs, omap=omap,
            from_osd=self.osd.whoami)

    def apply_push(self, m: MOSDPGPush) -> None:
        t = Transaction()
        if m.exists:
            t.remove(self.cid, m.oid)
            t.write(self.cid, m.oid, 0, m.data)
            if m.attrs:
                t.setattrs(self.cid, m.oid, m.attrs)
            if m.omap:
                t.omap_setkeys(self.cid, m.oid, m.omap)
        else:
            t.remove(self.cid, m.oid)
        try:
            self.osd.store.queue_transaction(t)
        except StoreError as e:
            log.error(f"pg {self.pgid} push apply failed: {e}")
        self.my_missing.pop(m.oid, None)
        fut = self._push_waiters.get(m.oid)
        if fut and not fut.done():
            fut.set_result(True)

    async def _recover(self) -> None:
        """Push every peer's missing objects (ref: run_recovery_op)."""
        if not self.is_primary():
            return
        self.state = "recovering" if any(self.peer_missing.values()) \
            else self.state
        for o, missing in list(self.peer_missing.items()):
            for oid in list(missing):
                try:
                    await self.osd.send_osd(o, self.make_push(oid))
                except Exception as e:
                    log.dout(1, f"pg {self.pgid} push {oid}->{o} "
                                f"failed: {e}")
                    continue
                missing.pop(oid, None)
        if not any(self.peer_missing.values()) and \
                self.state in ("active", "recovering"):
            self.state = "clean" if \
                len(self.live_acting()) >= self.pool.size else "active"

    # -- op execution ------------------------------------------------------
    async def queue_op(self, m: MOSDOp) -> None:
        await self.op_queue.put(m)

    async def _op_worker(self) -> None:
        try:
            while True:
                m = await self.op_queue.get()
                tracked = self.osd.op_tracker.create(
                    f"osd_op({m.src} {self.cid} {m.oid} "
                    f"tid={m.tid})")
                if not self.role_active():
                    tracked.mark_event("waiting_for_active")
                    while not self.role_active():
                        await asyncio.sleep(0.05)
                tracked.mark_event("started")
                try:
                    await self._execute(m)
                except Exception as e:
                    log.error(f"pg {self.pgid} op failed: {e}")
                    await self._reply(m, -5, b"", {})       # -EIO
                finally:
                    tracked.finish()
        except asyncio.CancelledError:
            pass

    async def _reply(self, m: MOSDOp, result: int, data: bytes,
                     extra: dict) -> None:
        if m.conn is None:
            return
        try:
            await m.conn.send_message(MOSDOpReply(
                tid=m.tid, result=result, epoch=self.epoch, data=data,
                extra=json.dumps(extra) if extra else ""))
        except Exception:
            pass                          # client resends via objecter

    async def _execute(self, m: MOSDOp) -> None:
        """ref: PrimaryLogPG::execute_ctx — reads serve immediately,
        writes run the replication pipeline. Mutations are deduped by
        (client, tid) so objecter resends of an applied-but-unacked op
        (e.g. a non-idempotent DELETE) return the original result."""
        # reqid = (entity, messenger incarnation, tid) — distinct client
        # processes sharing a name must not collide
        reqid = (m.src, getattr(m.conn, "peer_session", 0), m.tid)
        mutating = {OSD_OP_WRITE, OSD_OP_WRITEFULL, OSD_OP_TRUNCATE,
                    OSD_OP_ZERO, OSD_OP_DELETE, OSD_OP_SETXATTR,
                    OSD_OP_OMAP_SET}
        if any(c in mutating for c in m.op_codes) and \
                reqid in self._reqid_results:
            # resend of an applied-but-unacked mutation: return the
            # recorded outcome, never re-execute (a DELETE replay would
            # spuriously return -ENOENT; a write would duplicate log
            # entries). ref: PrimaryLogPG::already_complete (reqids)
            result, extra = self._reqid_results[reqid]
            await self._reply(m, result, b"", extra)
            return
        store = self.osd.store
        cid = self.cid
        oid = m.oid
        data_out = b""
        extra: dict = {}
        t = Transaction()
        mutated = False
        deleted = False
        for code, off, length, name, data in m.unpack_ops():
            if code == OSD_OP_READ:
                try:
                    data_out = store.read(
                        cid, oid, off, length if length else None)
                except StoreError:
                    await self._reply(m, -2, b"", {})       # -ENOENT
                    return
            elif code == OSD_OP_STAT:
                try:
                    extra["size"] = store.stat(cid, oid)
                except StoreError:
                    await self._reply(m, -2, b"", {})
                    return
            elif code == OSD_OP_GETXATTR:
                try:
                    attrs = store.getattrs(cid, oid)
                except StoreError:
                    await self._reply(m, -2, b"", {})
                    return
                if name not in attrs:
                    await self._reply(m, -61, b"", {})      # -ENODATA
                    return
                data_out = attrs[name]
            elif code == OSD_OP_OMAP_GET:
                try:
                    omap = store.omap_get(cid, oid)
                except StoreError:
                    await self._reply(m, -2, b"", {})
                    return
                extra["omap"] = {k: v.hex() for k, v in omap.items()
                                 if not k.startswith("_")}
            elif code == OSD_OP_PGLS:
                objs = [o for o in store.list_objects(cid)
                        if o != PGMETA]
                extra["objects"] = objs
            elif code == OSD_OP_WRITE:
                t.write(cid, oid, off, data)
                mutated = True
            elif code == OSD_OP_WRITEFULL:
                t.remove(cid, oid)
                t.write(cid, oid, 0, data)
                mutated = True
            elif code == OSD_OP_TRUNCATE:
                t.truncate(cid, oid, off)
                mutated = True
            elif code == OSD_OP_ZERO:
                t.zero(cid, oid, off, length)
                mutated = True
            elif code == OSD_OP_DELETE:
                if not store.exists(cid, oid):
                    await self._reply(m, -2, b"", {})
                    return
                t.remove(cid, oid)
                mutated = True
                deleted = True
            elif code == OSD_OP_SETXATTR:
                t.touch(cid, oid)
                t.setattrs(cid, oid, {name: data})
                mutated = True
            elif code == OSD_OP_OMAP_SET:
                t.touch(cid, oid)
                t.omap_setkeys(cid, oid, {name: data})
                mutated = True
            elif code == OSD_OP_OMAP_RM:
                if not store.exists(cid, oid):
                    await self._reply(m, -2, b"", {})
                    return
                t.omap_rmkeys(cid, oid, [name])
                mutated = True
            else:
                await self._reply(m, -95, b"", {})   # -EOPNOTSUPP
                return
        if not mutated:
            await self._reply(m, 0, data_out, extra)
            return
        result, applied = await self._submit_write(oid, t, deleted)
        extra["version"] = str(self.pg_log.head)
        if applied:
            # The op is in the pg log: once the PG is active in any
            # later interval, log-based recovery has made it durable on
            # the whole acting set, so a RESEND must see success rather
            # than a re-execution (ref: PrimaryLogPG::already_complete).
            # A repop-timeout -EAGAIN is therefore recorded as 0 for
            # dedup while the CURRENT attempt still reports -EAGAIN.
            self._reqid_results[reqid] = (0 if result == -11 else result,
                                          extra)
        if len(self._reqid_results) > 2000:      # bounded (log-trim analog)
            for k in list(self._reqid_results)[:1000]:
                self._reqid_results.pop(k, None)
        await self._reply(m, result, data_out, extra)

    async def _submit_write(self, oid: str, t: Transaction,
                            deleted: bool) -> tuple[int, bool]:
        """The replication pipeline (ref: ReplicatedBackend::
        submit_transaction + issue_repop). Returns (result, applied):
        ``applied`` is True iff the op landed in the local store+log
        (it may still report -EAGAIN when replicas never confirmed)."""
        if len(self.live_acting()) < self.pool.min_size:
            return -11, False                           # -EAGAIN
        self.last_user_version += 1
        version = eversion(self.epoch, self.last_user_version)
        entry = self.pg_log.add(
            version, oid, OP_DELETE if deleted else OP_MODIFY)
        self.pg_log.trim()
        if not deleted:
            t.setattrs(self.cid, oid, {"_v":
                       version.epoch.to_bytes(4, "little") +
                       version.v.to_bytes(8, "little")})
        self._meta_txn(t)
        txn_blob = t.encode()
        replicas = [o for o in self.live_acting()
                    if o != self.osd.whoami]
        tid = self.osd.next_tid()
        waiter = None
        if replicas:
            waiter = asyncio.get_event_loop().create_future()
            self._repop_waiters[tid] = (set(replicas), waiter)
        try:
            self.osd.store.queue_transaction(t)
        except StoreError as e:
            log.error(f"pg {self.pgid} local commit failed: {e}")
            self._repop_waiters.pop(tid, None)
            return -5, False
        for o in replicas:
            await self.osd.send_osd(o, MOSDRepOp(
                tid=tid, epoch=self.epoch, pgid=self.cid,
                txn=txn_blob, log_entry=entry.encode()))
        if waiter is not None:
            try:
                await asyncio.wait_for(waiter, timeout=5.0)
            except asyncio.TimeoutError:
                # A replica never committed: the client MUST NOT see
                # success, or a subsequent primary failure could lose an
                # acknowledged write (ref: ReplicatedBackend's
                # all-replica-commit-before-ack contract). -EAGAIN makes
                # the objecter resend once the map moves and the PG
                # re-peers.
                log.dout(1, f"pg {self.pgid} repop {tid} timed out")
                return -11, True                        # -EAGAIN
            finally:
                self._repop_waiters.pop(tid, None)
        return 0, True

    def handle_rep_op(self, m: MOSDRepOp) -> None:
        """Replica applies the shipped transaction (ref:
        ReplicatedBackend::do_repop)."""
        entry = LogEntry.decode(m.log_entry)
        t = Transaction.decode(m.txn)
        try:
            self.osd.store.queue_transaction(t)
        except StoreError as e:
            log.error(f"pg {self.pgid} repop apply failed: {e}")
            return
        self.pg_log.append(entry)
        self.pg_log.trim()
        self.last_user_version = max(self.last_user_version,
                                     entry.version.v)

        async def _ack():
            try:
                # reply on the incoming connection: the replica may not
                # have seen the map naming the primary yet
                await m.conn.send_message(MOSDRepOpReply(
                    tid=m.tid, result=0, pgid=self.cid,
                    from_osd=self.osd.whoami))
            except Exception:
                pass      # primary's repop timeout covers the loss
        asyncio.ensure_future(_ack())

    def handle_rep_reply(self, m: MOSDRepOpReply) -> None:
        ent = self._repop_waiters.get(m.tid)
        if ent is None:
            return
        pending, fut = ent
        pending.discard(m.from_osd)
        if not pending and not fut.done():
            fut.set_result(True)

    # -- stats -------------------------------------------------------------
    def stats(self) -> dict:
        objs = [o for o in self.osd.store.list_objects(self.cid)
                if o != PGMETA] if self.cid in \
            self.osd.store.list_collections() else []
        nbytes = 0
        for o in objs:
            try:
                nbytes += self.osd.store.stat(self.cid, o)
            except StoreError:
                pass
        state = self.state
        if self.is_primary():
            live = len(self.live_acting())
            if live < self.pool.size and self.role_active():
                state = f"{self.state}+undersized+degraded"
        return {"state": state, "num_objects": len(objs),
                "num_bytes": nbytes,
                "acting": self.acting, "up": self.up,
                "last_update": str(self.pg_log.head),
                "scrub_errors": self.scrub_errors}
