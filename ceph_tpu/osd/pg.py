"""PG: per-placement-group replicated state machine.

ref: src/osd/PG.cc + PeeringState.{h,cc} + PrimaryLogPG.cc — one PG
owns one ObjectStore collection and an ordered op pipeline. The
reference's boost::statechart phases map to:

- ``advance_map``: new acting set from the OSDMap ends the current
  interval (ref: PeeringState::advance_map / start_peering_interval);
- ``peering`` (primary): query every acting peer's info+log, adopt the
  authoritative log (max last_update — ref: find_best_info), merge to
  produce per-peer missing sets (ref: GetMissing), pull what the
  primary itself lacks, then activate;
- ``active``: client ops execute (PrimaryLogPG::execute_ctx):
  writes get an eversion, a pg-log entry, and an ObjectStore
  transaction replicated to acting peers as MOSDRepOp, acked to the
  client when every live acting replica commits
  (ref: ReplicatedBackend::submit_transaction);
- ``recovery``: missing objects are pushed whole at their
  authoritative version (ref: PGBackend::run_recovery_op); when no
  peer is missing anything the PG is clean.

The pg log + per-object versions persist in the collection's
``_pgmeta_`` object (ref: pgmeta_oid omap), so a restarted OSD
re-peers from durable state.
"""

from __future__ import annotations

import asyncio
import json

from ceph_tpu.msg.messenger import ConnectionError_
from ceph_tpu.os_.objectstore import StoreError, Transaction
from ceph_tpu.osd.messages import (
    BACKFILL_OP_FINISH, BACKFILL_OP_PROGRESS, BACKFILL_OP_RESET,
    MBackfillReserve, MOSDOp, MOSDOpReply, MOSDPGBackfill,
    MOSDPGBackfillReply, MOSDPGInfo, MOSDPGPull, MOSDPGPush,
    MOSDPGPushReply, MOSDPGQuery, MOSDPGScan, MOSDPGScanReply,
    MOSDRepOp, MOSDRepOpReply, MUTATING_OPS,
    MWatchNotify, OSD_OP_DELETE,
    OSD_OP_GETXATTR, OSD_OP_NOTIFY, OSD_OP_NOTIFY_ACK, OSD_OP_OMAP_GET,
    OSD_OP_OMAP_SET, OSD_OP_PGLS,
    OSD_OP_OMAP_RM, OSD_OP_READ, OSD_OP_SETXATTR, OSD_OP_SNAPTRIM,
    OSD_OP_STAT,
    OSD_OP_TRUNCATE, OSD_OP_UNWATCH, OSD_OP_WATCH, OSD_OP_WRITE,
    OSD_OP_WRITEFULL, OSD_OP_ZERO,
    RESERVE_GRANT, RESERVE_REJECT, RESERVE_RELEASE, RESERVE_REQUEST,
    RESERVE_TOOFULL,
)
from ceph_tpu.osd.pg_log import OP_DELETE, OP_MODIFY, LogEntry, PGLog, \
    eversion
from ceph_tpu.osd.recovery import PERF as RECOVERY_PERF
from ceph_tpu.osd.types import MAX_OID, MIN_OID, pg_t
from ceph_tpu.utils.logging import get_logger


def _finish_store_span(span, store) -> None:
    """Close an objectstore_commit span, attaching the store's
    per-phase sub-spans (the kv/WAL split: WALStore reports
    apply/wal_kv_commit, BlueStore block_write/kv_commit/
    deferred_write) recorded during the synchronous commit."""
    if span is None:
        return
    for phase, dt in getattr(store, "last_txn_phases", {}).items():
        span.annotate(phase, dt)
    span.finish()

log = get_logger("osd")

PGMETA = "_pgmeta_"

# snapshot clone objects live beside their head in the same PG under a
# reserved prefix (ref: the SnapSet clone list; upstream names clones
# hobject(oid, snapid) — here the snapid rides in the name)
CLONE_PREFIX = "_snapclone."


def clone_name(oid: str, clone_id: int) -> str:
    return f"{CLONE_PREFIX}{clone_id}.{oid}"


def clone_head(name: str) -> str | None:
    """The head oid a clone object belongs to, or None for non-clones."""
    if not name.startswith(CLONE_PREFIX):
        return None
    rest = name[len(CLONE_PREFIX):]
    parts = rest.split(".", 1)
    return parts[1] if len(parts) == 2 else None


class PG:
    def __init__(self, osd, pool, pgid: pg_t):
        self.osd = osd                    # OSD daemon (service facade)
        self.pool = pool
        self.pgid = pgid
        self.cid = str(pgid)
        self.pg_log = PGLog()
        self.state = "initial"
        self.epoch = 0                    # interval epoch
        self.acting: list[int] = []
        self.up: list[int] = []
        self.primary = -1
        self.last_user_version = 0
        # PastIntervals (ref: osd_types PastIntervals + PeeringState::
        # build_prior): every acting set this PG has had since it was
        # last clean, [[first_epoch, last_epoch, [acting...]], ...].
        # Peering must hear from at least one member of EACH past
        # interval before activating — the current acting set's logs
        # alone cannot prove no other interval acknowledged writes
        # (e.g. acting flipped A->B->A: B took writes while A was out).
        # Persisted in the pg meta object; trimmed at last_epoch_clean.
        self.past_intervals: list[list] = []
        self.interval_start = 0           # epoch current acting set began
        self.last_epoch_clean = 0
        # backfill (ref: pg_info_t.last_backfill + PeeringState's
        # backfill machinery). ``last_backfill`` is THIS instance's
        # persisted watermark: the store holds every object <= it (in
        # sorted-name order); MAX_OID = complete. ``backfill_targets``
        # is primary-side state: acting peers whose logs are NOT
        # continuous with the authoritative log (or who reported an
        # incomplete watermark) -> their current watermark; log-delta
        # recovery cannot serve them, the scan/push machinery must.
        self.last_backfill = MAX_OID
        # the authoritative head last_backfill was last valid AT (ref:
        # the role of pg_info_t.last_update for backfill peers):
        # resuming from the watermark after a rejoin is only sound if
        # the authoritative log is still continuous with this point —
        # then every sub-watermark change since is derivable from the
        # retained log; otherwise the scan must restart from MIN.
        self.backfill_at = eversion()
        self.backfill_targets: dict[int, str] = {}
        self.peer_last_backfill: dict[int, str] = {}
        self.peer_backfill_at: dict[int, eversion] = {}
        # last_epoch_started (ref: pg_info_t.last_epoch_started): the
        # interval_start of the newest interval this OSD saw ACTIVATE
        # for this PG — recorded by the primary when peering completes
        # and pushed to acting replicas (MOSDPGInfo activate=1), so
        # every survivor of an interval can out-elect a revived
        # pre-failover primary's divergent log (find_best_info orders
        # by (les, head), not head alone). Persisted with the pg meta.
        self.last_epoch_started = 0
        self.peer_les: dict[int, int] = {}
        self._backfill_task: asyncio.Task | None = None
        # the (wm, end] name range a backfill scan is comparing RIGHT
        # NOW: mutations inside it park with -EAGAIN so a write — or a
        # brand-new object, invisible to the batch snapshot — cannot
        # slip between the scan's version read and the watermark
        # advance (the reference blocks ops on objects being
        # backfilled). None = no scan in flight.
        self._backfill_inflight: tuple[str, str] | None = None
        self._backfill_waiters: dict[int, asyncio.Future] = {}
        # reservation nonces: the tid under which the target granted
        # its remote slot (target side) / each target granted ours
        # (primary side). A RELEASE only frees the grant whose tid it
        # carries — the fault layer duplicates messages by design, and
        # a duplicated release must not free a RE-acquired grant.
        self._remote_grant_tid = 0
        self._reserve_tids: dict[int, int] = {}
        self.backfill_stats = {"scanned": 0, "pushed": 0,
                               "removed": 0, "resumed_from": ""}
        # per-client op counts (round 17): the mgr tuner's hot-pool
        # protector reads these off `pg dump` and diffs across ticks
        # to rank pools/entities by live op rate — no wire change, the
        # counts ride the MPGStats stats blob like backfill progress.
        # Primary-only and reset with the PG object (a new primary
        # restarts at zero; the tuner diffs, so baselines self-heal).
        self.client_ops: dict[str, int] = {}
        # peering scratch
        self.peer_logs: dict[int, PGLog] = {}
        self.peer_missing: dict[int, dict[str, LogEntry]] = {}
        self.my_missing: dict[str, LogEntry] = {}
        self._peering_task: asyncio.Task | None = None
        self._info_waiter: asyncio.Future | None = None
        self._expected_infos: set[int] = set()
        # OSDs that announced data for this PG (MOSDPGNotify model):
        # their identity survives the per-round peer_logs rebuild, so
        # every peering round re-queries them even if their one
        # announcement raced a wipe
        self._notifiers: set[int] = set()
        # op pipeline
        self.op_queue: asyncio.Queue = asyncio.Queue()
        self._worker: asyncio.Task | None = None
        # the op the serialized worker is executing RIGHT NOW, as its
        # trace "execute" span (the worker is one-op-at-a-time, so an
        # instance slot is race-free); _submit_write hangs the
        # objectstore/repop child spans off it
        self._active_span = None
        # asserted client backoffs (ref: PG::Backoff / backoff_map):
        # client entity -> [backoff id, conn]. Asserted while the PG
        # is not active (peering) or its op queue is saturated;
        # re-asserted across interval change, released on activation /
        # drain. The Objecter parks matching ops until UNBLOCK.
        self.backoffs: dict[str, list] = {}
        # tid -> [pending_replica_set, future, reqid, timed_out]: one
        # record per in-flight repop. ``timed_out`` marks repops whose
        # client already got -EAGAIN; a late completing reply (or a
        # re-peer + completed recovery) promotes the recorded dedup
        # result to success so resends stop seeing -EAGAIN.
        self._repop_waiters: dict[int, list] = {}
        self._push_waiters: dict[str, asyncio.Future] = {}
        # (peer_osd, oid) -> future completed by MOSDPGPushReply: the
        # primary's recovery only counts ACKED pushes as recovered
        self._push_ack_waiters: dict[tuple[int, str],
                                     asyncio.Future] = {}
        # (client, tid) -> (result, extra): replays of mutating ops whose
        # reply was lost return the recorded outcome instead of
        # re-executing (ref: pg_log_entry_t reqid dedup)
        self._reqid_results: dict[tuple, tuple] = {}
        # watch/notify (ref: PrimaryLogPG watchers_): oid ->
        # {(client, cookie): conn}. In-memory on the primary; clients
        # re-watch after a primary change (the reference persists watch
        # state in the object info — documented simplification).
        self._watchers: dict[str, dict[tuple, object]] = {}
        self._notify_waiters: dict[int, list] = {}   # id -> [pending, fut, acks]
        # head oid -> [(clone_id, covered_snaps)], lazily built from the
        # store and INVALIDATED whenever clone state changes (COW, trim,
        # recovery push, split). Keeps the hot snapc-write path O(1) —
        # without it every snap-context write scanned the whole PG
        # collection (r4 review finding).
        self._clone_idx: dict[str, list] | None = None
        self.scrub_errors = 0
        self.last_scrub = 0.0
        self._scrubber = None
        # set by merge_from: the parent absorbed a source's objects +
        # log — the next advance() must re-peer even though the acting
        # set may be unchanged, so replicas reconcile any divergence
        # the folded logs carry
        self._force_repeer = False
        self._ensure_collection()
        self._load_meta()

    # -- persistence -------------------------------------------------------
    def _ensure_collection(self) -> None:
        if self.cid not in self.osd.store.list_collections():
            t = Transaction().create_collection(self.cid)
            t.touch(self.cid, PGMETA)
            self.osd.store.queue_transaction(t)

    def _load_meta(self) -> None:
        try:
            omap = self.osd.store.omap_get(self.cid, PGMETA)
        except StoreError:
            return
        blob = omap.get("pg_log")
        if blob:
            self.pg_log = PGLog.decode(blob)
            self.last_user_version = self.pg_log.head.v
        pblob = omap.get("peering")
        if pblob:
            meta = json.loads(pblob)
            self.past_intervals = meta.get("past_intervals", [])
            self.interval_start = meta.get("interval_start", 0)
            self.last_epoch_clean = meta.get("last_epoch_clean", 0)
            self.last_epoch_started = meta.get("last_epoch_started", 0)
            self.last_backfill = meta.get("last_backfill", MAX_OID)
            self.backfill_at = eversion(
                *meta.get("backfill_at", (0, 0)))

    def _meta_txn(self, t: Transaction) -> Transaction:
        t.omap_setkeys(self.cid, PGMETA, {
            "pg_log": self.pg_log.encode(),
            "peering": json.dumps({
                "past_intervals": self.past_intervals,
                "interval_start": self.interval_start,
                "last_epoch_clean": self.last_epoch_clean,
                "last_epoch_started": self.last_epoch_started,
                "last_backfill": self.last_backfill,
                "backfill_at": list(self.backfill_at),
            }).encode()})
        return t

    def _trim_keep(self) -> int:
        """Retained pg-log length (ref: osd_min_pg_log_entries). The
        log tail this leaves behind is the log-delta recovery horizon:
        a peer whose head predates it must be backfilled."""
        return int(self.osd.config.get("osd_min_pg_log_entries", 1000))

    def _backfill_enabled(self) -> bool:
        """Escape hatch for the seed-reproduction regression test
        (tests/test_backfill.py): with backfill off, a peer past the
        log horizon silently gets only the retained log delta — the
        exact data-loss hole backfill exists to close."""
        return bool(self.osd.config.get("osd_backfill", True))

    @property
    def scrubber(self):
        if self._scrubber is None:
            from ceph_tpu.osd.scrub import Scrubber
            self._scrubber = Scrubber(self)
        return self._scrubber

    def is_primary(self) -> bool:
        return self.primary == self.osd.whoami

    def role_active(self) -> bool:
        # backfill runs ONLINE: client ops keep flowing while the scan
        # copies history (only the per-object gates in _execute park)
        return self.state in ("active", "recovering", "clean",
                              "backfilling", "backfill_wait",
                              "backfill_toofull")

    # -- interval changes --------------------------------------------------
    def advance(self, up: list[int], acting: list[int], primary: int,
                epoch: int) -> None:
        """ref: PeeringState::advance_map — a changed acting set starts
        a new interval; the primary re-peers. The closing interval is
        recorded in past_intervals (every member of it may hold writes
        this PG acknowledged — see _peer_inner's prior coverage)."""
        changed = (acting != self.acting or primary != self.primary)
        if changed:
            old = [o for o in self.acting if o >= 0]
            if old and epoch > self.interval_start:
                self.past_intervals.append(
                    [self.interval_start, epoch - 1, old,
                     self.primary])
                self.past_intervals = [
                    iv for iv in self.past_intervals
                    if iv[1] >= self.last_epoch_clean]
            self.interval_start = epoch
            try:        # survive restarts: intervals gate activation
                self.osd.store.queue_transaction(
                    self._meta_txn(Transaction()))
            except StoreError as e:
                # degraded to in-memory-only intervals until the next
                # successful meta write (every log append retries it) —
                # loud, because a crash before then re-opens the
                # pre-PastIntervals activation hole
                log.error(f"pg {self.pgid} interval persist failed: {e}")
        self.up = up
        self.acting = acting
        self.primary = primary
        self.epoch = epoch
        if not changed and self.role_active() and \
                not self._force_repeer:
            return
        self._force_repeer = False
        if changed:
            # interval actually ended: stop any backfill run and free
            # its reservations. NOT on mere epoch bumps — a replica
            # falls through here on every unrelated map change, and
            # releasing its remote reservation slot mid-scan would let
            # a second primary in past osd_max_backfills.
            self._cancel_backfill()
        if self._peering_task:
            self._peering_task.cancel()
            self._peering_task = None
        if self.is_primary():
            self.state = "peering"
            if changed:
                # blocked clients stay blocked across the interval
                # change; released when this peering round activates
                self.reassert_backoffs()
            self._peering_task = asyncio.ensure_future(self._peer())
        else:
            self.state = "replica" if self.osd.whoami in acting \
                else "stray"
            # no longer the primary: our backoffs must not park
            # clients that should now talk to the new primary
            self.release_backoffs()
            if self._worker:
                self._worker.cancel()
                self._worker = None
                # admitted-but-unexecuted ops die with the worker:
                # give their admission-throttle slots back (clients
                # resend to the new primary) — leaked slots would
                # eventually wedge the whole OSD's op admission
                self._drain_op_queue()
            if self.state == "stray" and primary >= 0 \
                    and primary != self.osd.whoami:
                # announce ourselves to the new primary (ref:
                # MOSDPGNotify): a pgp_num change (pg splitting's
                # migration phase) can hand the PG to OSDs that hold
                # none of its data — without this notify a FRESH
                # primary instance has no way to learn the data's old
                # location and would activate empty. Re-sent on EVERY
                # map advance while stray: a one-shot notify can land
                # mid-peering (peer_logs is rebuilt there) and be lost.
                asyncio.ensure_future(self.osd.send_osd(
                    primary, MOSDPGInfo(
                        pgid=self.cid, epoch=epoch,
                        from_osd=self.osd.whoami,
                        log=self.pg_log.encode(), notify=1,
                        intervals=json.dumps(self.past_intervals),
                        last_backfill=self.last_backfill,
                        backfill_at_epoch=self.backfill_at.epoch,
                        backfill_at_v=self.backfill_at.v,
                        les=self.last_epoch_started, activate=0)))

    # -- client backoffs (ref: PG::add_backoff/release_backoffs) ---------
    async def send_backoff(self, m: MOSDOp) -> None:
        """BLOCK the whole PG range for this op's client instead of
        queueing while we are not active / saturated; the op itself is
        dropped (the parked Objecter resends after UNBLOCK)."""
        from ceph_tpu.osd.daemon import OVERLOAD_PERF
        from ceph_tpu.osd.messages import BACKOFF_OP_BLOCK, MOSDBackoff
        ent = self.backoffs.get(m.src)
        if ent is None:
            ent = [self.osd.next_tid(), m.conn]
            self.backoffs[m.src] = ent
        else:
            ent[1] = m.conn               # freshest connection wins
        OVERLOAD_PERF.inc("backoffs_sent")
        try:
            await m.conn.send_message(MOSDBackoff(
                op=BACKOFF_OP_BLOCK, id=ent[0], pool=self.pgid.pool,
                seed=self.pgid.seed, begin=MIN_OID, end=MAX_OID,
                epoch=self.epoch, from_osd=self.osd.whoami))
        except Exception:
            pass          # client's backoff self-heal covers the loss

    def release_backoffs(self) -> None:
        """UNBLOCK every asserted backoff (activation, drain, or this
        OSD ceasing to be the primary — a new primary owes the client
        nothing, so it must stop waiting on us)."""
        if not self.backoffs:
            return
        from ceph_tpu.osd.daemon import OVERLOAD_PERF
        from ceph_tpu.osd.messages import BACKOFF_OP_UNBLOCK, \
            MOSDBackoff
        released = list(self.backoffs.items())
        self.backoffs = {}

        async def _send(bid, conn):
            OVERLOAD_PERF.inc("backoffs_released")
            try:
                await conn.send_message(MOSDBackoff(
                    op=BACKOFF_OP_UNBLOCK, id=bid,
                    pool=self.pgid.pool, seed=self.pgid.seed,
                    begin=MIN_OID, end=MAX_OID, epoch=self.epoch,
                    from_osd=self.osd.whoami))
            except Exception:
                pass
        for _src, (bid, conn) in released:
            asyncio.ensure_future(_send(bid, conn))

    def reassert_backoffs(self) -> None:
        """Interval change while still primary: the blocked clients
        stay blocked — re-send the BLOCKs so a client that raced the
        change keeps parking (ref: backoffs surviving interval
        change)."""
        from ceph_tpu.osd.messages import BACKOFF_OP_BLOCK, MOSDBackoff

        async def _send(bid, conn):
            try:
                await conn.send_message(MOSDBackoff(
                    op=BACKOFF_OP_BLOCK, id=bid, pool=self.pgid.pool,
                    seed=self.pgid.seed, begin=MIN_OID, end=MAX_OID,
                    epoch=self.epoch, from_osd=self.osd.whoami))
            except Exception:
                pass
        for _src, (bid, conn) in list(self.backoffs.items()):
            asyncio.ensure_future(_send(bid, conn))

    def dump_backoffs(self) -> dict:
        return {src: {"id": bid, "begin": MIN_OID, "end": "MAX"}
                for src, (bid, _conn) in self.backoffs.items()}

    def _cancel_backfill(self) -> None:
        """Interval change / teardown: stop the scan and free every
        reservation (the target's persisted watermark survives — the
        next primary resumes from it, which is the whole point)."""
        if self._backfill_task is not None:
            self._backfill_task.cancel()
            self._backfill_task = None
        self._backfill_inflight = None
        self.osd.local_reserver.cancel(self.cid)
        # target-side slot too: a dead primary never sends RELEASE, but
        # its death moves the map, which lands here on every target
        self.osd.remote_reserver.cancel(self.cid)
        for o in list(self.backfill_targets):
            if o != self.osd.whoami and self.osd.osd_is_up(o):
                asyncio.ensure_future(self._send_reserve_op(
                    o, RESERVE_RELEASE,
                    self._reserve_tids.get(o, 0)))
        self.backfill_targets = {}

    async def _send_reserve_op(self, osd: int, op: int,
                               tid: int = 0) -> None:
        try:
            await self.osd.send_osd(osd, MBackfillReserve(
                pgid=self.cid, epoch=self.epoch, tid=tid, op=op,
                from_osd=self.osd.whoami))
        except Exception:
            pass          # peer death releases its slots anyway

    def live_acting(self) -> list[int]:
        return [o for o in self.acting
                if o >= 0 and self.osd.osd_is_up(o)]

    # -- peering (primary) -------------------------------------------------
    async def _peer(self) -> None:
        try:
            await self._peer_inner()
        except asyncio.CancelledError:
            pass
        except Exception as e:
            log.dout(1, f"pg {self.pgid} peering failed ({e}); retrying")
            self.state = "peering"
            self.osd.request_repeer(self, delay=0.5)

    async def _peer_inner(self) -> None:
        interval_epoch = self.epoch
        peers = [o for o in self.live_acting() if o != self.osd.whoami]
        self.peer_logs = {}
        self.peer_les = {}
        if len(self.live_acting()) < self.pool.min_size:
            self.state = "peering"        # undersized: wait for map
            return
        # prior set (ref: PeeringState::build_prior): members of every
        # past interval since last clean that MAY have gone active —
        # any of them may hold writes acknowledged while the current
        # acting set was out. An interval whose primary never received
        # an up_thru grant >= its first epoch never activated (the
        # grant precedes activation below), so it cannot hold acked
        # writes and is excluded — without this test, every transient
        # one-epoch acting set whose members later die would block the
        # PG forever. Reachable prior strays are queried alongside the
        # acting peers; their logs compete in find_best_info below.
        om = self.osd.osdmap
        active_ivs = []
        for iv in self.past_intervals:
            prim = iv[3] if len(iv) > 3 else \
                (iv[2][0] if iv[2] else -1)
            if om is not None and prim >= 0 and \
                    om.up_thru.get(prim, 0) < iv[0]:
                continue                  # never activated
            active_ivs.append(iv)
        prior = set()
        for iv in active_ivs:
            prior.update(iv[2])
        prior |= self._notifiers     # announced data holders (notify)
        prior -= set(self.acting)
        prior.discard(self.osd.whoami)
        strays = [o for o in sorted(prior) if self.osd.osd_is_up(o)]
        query = peers + strays
        if query:
            fut = asyncio.get_event_loop().create_future()
            self._info_waiter = fut
            self._expected_infos = set(query)
            for o in query:
                await self.osd.send_osd(o, MOSDPGQuery(
                    pgid=self.cid, epoch=interval_epoch,
                    from_osd=self.osd.whoami))
            try:
                await asyncio.wait_for(fut, timeout=3.0)
            except asyncio.TimeoutError:
                pass
            finally:
                self._info_waiter = None
            if not set(query) <= set(self.peer_logs):
                # a QUERIED peer didn't answer; retry soon (map may be
                # stale). Subset test, not proper-subset: an
                # unsolicited notify landing in peer_logs mid-wait must
                # not mask a queried peer's silence.
                self.state = "peering"
                self.osd.request_repeer(self, delay=0.5)
                return
        if self.epoch != interval_epoch:
            return                        # superseded interval
        # interval coverage gate: activation requires having heard
        # from >=1 member of EACH past interval — an interval whose
        # every member is down blocks peering (upstream: 'down' /
        # 'incomplete'; recovery needs those OSDs back or an operator
        # decision, never silent activation that may discard their
        # acknowledged writes).
        heard = set(self.peer_logs) | {self.osd.whoami}
        for iv in active_ivs:
            _f, _l, members = iv[0], iv[1], iv[2]
            if not (set(members) & heard):
                log.dout(1, f"pg {self.pgid} down: no member of past "
                            f"interval [{_f},{_l}] {members} reachable")
                self.state = "peering"
                self.osd.request_repeer(self, delay=1.0)
                return
        # up_thru grant (ref: OSDMonitor::prepare_alive / PeeringState
        # need_up_thru): BEFORE activating, this interval must be
        # recorded in the map — that is what lets FUTURE peers apply
        # the maybe-went-active test above to THIS interval.
        if om is not None and \
                om.up_thru.get(self.osd.whoami, 0) < self.interval_start:
            from ceph_tpu.mon.messages import MOSDAlive
            await self.osd.monc.send_report(MOSDAlive(
                osd=self.osd.whoami, epoch=self.interval_start))
            # re-want the map stream explicitly: the grant may ALREADY
            # be committed (the mon dedupes re-requests, so no new inc
            # will ever be published for it) with the publish lost to
            # a dropped subscription — without this re-subscribe the
            # retry loop below waits forever on a map that will never
            # arrive
            await self.osd.monc.subscribe("osdmap", om.epoch + 1)
            self.state = "peering"    # retry once the grant's map lands
            self.osd.request_repeer(self, delay=0.3)
            return
        # authoritative log: max head (ref: find_best_info) — among
        # COMPLETE candidates only (last_backfill == MAX): a mid-
        # backfill peer's log may be current while its store lacks most
        # objects, so its info must never win authority (ref:
        # find_best_info's infos-with-incomplete-last_backfill skip).
        # With every candidate incomplete there is no authoritative
        # store anywhere: block rather than activate and serve holes.
        backfill_on = self._backfill_enabled()
        infos = [(self.osd.whoami, self.pg_log, self.last_backfill)]
        infos += [(o, plog, self.peer_last_backfill.get(o, MAX_OID))
                  for o, plog in self.peer_logs.items()]
        if backfill_on:
            complete = [c for c in infos if c[2] == MAX_OID]
            if not complete:
                log.dout(1, f"pg {self.pgid} incomplete: every "
                            f"candidate is mid-backfill")
                self.state = "peering"
                self.osd.request_repeer(self, delay=1.0)
                return
        else:
            complete = infos
        # order candidates by (last_epoch_started, head) — ref:
        # find_best_info's max-les-then-max-last_update. Head alone is
        # WRONG here: a revived pre-failover primary can carry a
        # divergent entry (logged locally, never committed on enough
        # replicas/shards) whose version outranks everything the
        # surviving interval wrote — but its les predates the interval
        # that peered without it, so the survivors' log must win and
        # the divergent entry rolls back below.
        def _key(o: int, plog: PGLog) -> tuple:
            les = self.last_epoch_started if o == self.osd.whoami \
                else self.peer_les.get(o, 0)
            if plog.head == eversion():
                # an empty log testifies to nothing: a fresh primary
                # that activated empty (pgp_num split migration) must
                # not out-elect a stray actually holding the data
                les = 0
            return (les, plog.head)
        best_osd, best, _ = complete[0]
        for o, plog, _lb in complete[1:]:
            if _key(o, plog) > _key(best_osd, best):
                best, best_osd = plog, o
        if backfill_on and \
                _key(best_osd, best) < max(
                    _key(c[0], c[1]) for c in infos):
            # the newest log lives ONLY on a mid-backfill candidate:
            # adopting the best complete log would roll back writes
            # acknowledged in a later interval (the incomplete holder
            # has them for oids <= its watermark; the dead primary had
            # the rest). Upstream calls this 'down' — block until the
            # missing holder returns, never silently discard.
            log.dout(1, f"pg {self.pgid} down: newest log only on an "
                        f"incomplete (mid-backfill) peer")
            self.state = "peering"
            self.osd.request_repeer(self, delay=1.0)
            return
        if backfill_on and best_osd != self.osd.whoami and \
                not best.continuous_with(self.pg_log.head) and \
                self.last_backfill == MAX_OID:
            # THIS osd's own history predates the authoritative log's
            # tail (fresh store, or a rejoin from past the horizon)
            # AND the map made it primary: its missing set below is
            # incomplete by construction, so demote its own watermark —
            # the self-backfill block under it rebuilds the store from
            # a complete peer before anything is served. (A persisted
            # watermark < MAX is kept: that is resume progress.)
            self.last_backfill = MIN_OID
        if best_osd != self.osd.whoami:
            # divergent-entry revert (ref: PGLog::_merge_divergent_
            # entries rolling back to the authoritative version): any
            # local entry NEWER than the authoritative log's newest for
            # that object is an uncommitted write the elected interval
            # never saw — the store may hold its bytes, so queue a pull
            # back to the authoritative version before serving anything
            auth_newest = best.newest_per_object()
            for oid, e in self.pg_log.newest_per_object().items():
                ae = auth_newest.get(oid)
                if ae is not None and e.version > ae.version:
                    log.dout(1, f"pg {self.pgid} reverting divergent "
                                f"{oid} {e.version} -> {ae.version}")
                    self.my_missing[oid] = ae
            # merge may ADD to my_missing; leftovers from an earlier
            # interval whose pulls failed must stay until recovered —
            # our log may now BE the best (merged last round) while the
            # object bytes still aren't here
            self.my_missing.update(self.pg_log.merge(best))
            # our log now IS the authoritative interval's: adopt its
            # les so the raced-notify check below (and any election we
            # testify in before re-activating) ranks us where the
            # merged log actually stands
            self.last_epoch_started = max(self.last_epoch_started,
                                          self.peer_les.get(best_osd,
                                                            0))
            t = self._meta_txn(Transaction())
            self.osd.store.queue_transaction(t)
        if backfill_on and self.last_backfill != MAX_OID:
            # our own resume-safety check (mirror of the per-target
            # one below): entries newer than our backfill_at with oids
            # under our watermark are changes we provably missed —
            # pull them as log-delta; if the log can no longer prove
            # the sub-watermark region, restart our scan from MIN
            if self.pg_log.continuous_with(self.backfill_at):
                for oid, e in self.pg_log.newest_per_object().items():
                    if oid <= self.last_backfill and \
                            e.version > self.backfill_at and \
                            self._version_blob(oid) != \
                            e.version.epoch.to_bytes(4, "little") + \
                            e.version.v.to_bytes(8, "little"):
                        self.my_missing[oid] = e
            else:
                self.last_backfill = MIN_OID
        if self.my_missing:
            # pull objects the primary itself lacks. Source selection
            # matters: a peer whose log never saw the object would stay
            # silent (handle_pg_pull), so prefer one whose log carries
            # the exact entry we need; the merged-from peer qualifies.
            peer_newest = {o: plog.newest_per_object()
                           for o, plog in self.peer_logs.items()}
            for oid, entry in list(self.my_missing.items()):
                # candidate sources in preference order; ROTATE through
                # them — a single fixed source whose log has the entry
                # but whose store lacks the bytes (its own pulls failed
                # earlier) stays silent, and retrying only it would
                # livelock while another peer holds the object
                cands: list[int] = []
                if best_osd != self.osd.whoami:
                    cands.append(best_osd)
                for o, newest in peer_newest.items():
                    ne = newest.get(oid)
                    if ne is not None and ne.version == entry.version:
                        cands.append(o)
                cands.extend(o for o in self.live_acting())
                seen: set[int] = set()
                for src in cands:
                    if src in seen or src < 0 or \
                            src == self.osd.whoami or \
                            not self.osd.osd_is_up(src):
                        continue
                    seen.add(src)
                    await self._pull(src, oid)
                    if oid not in self.my_missing:
                        break
            if self.my_missing:
                # do NOT activate with stale objects: a client read
                # would serve pre-outage data. Retry the interval.
                self.state = "peering"
                self.osd.request_repeer(self, delay=0.5)
                return
        if backfill_on and self.last_backfill != MAX_OID:
            # THIS primary is itself mid-backfill (it was a target when
            # the map promoted it — there is no pg_temp here to prevent
            # that): before serving anything it must finish its own
            # copy, pulling the scan from a complete peer. Runs inline
            # in peering (ops queue behind role_active) — the working
            # sets this framework runs keep it short.
            src = best_osd if best_osd != self.osd.whoami else next(
                (o for o, _pl, _lb in complete
                 if o != self.osd.whoami and self.osd.osd_is_up(o)),
                None)
            if src is None or not await self._backfill_self(src):
                self.state = "peering"
                self.osd.request_repeer(self, delay=0.5)
                return
        self.last_user_version = max(self.last_user_version,
                                     self.pg_log.head.v)
        # per-peer missing sets (ref: GetMissing) — acting peers only:
        # prior strays answered queries but take no recovery pushes
        # (they leave the set at the next clean interval). A peer whose
        # log is NOT continuous with the authoritative log (its head
        # predates our tail — it missed more history than the retained
        # log can describe) or who reports an incomplete last_backfill
        # becomes a BACKFILL TARGET: its missing set cannot be derived
        # from the log, the scan machinery rebuilds it. Its log-derived
        # missing is kept only for oids <= its watermark (objects it is
        # supposed to hold current — e.g. it missed repops while briefly
        # down mid-backfill); everything above is the scan's job.
        self.backfill_targets = {}
        self.peer_missing = {}
        for o, plog in self.peer_logs.items():
            if o not in self.acting:
                continue
            missing = plog.missing_vs(self.pg_log)
            lb = self.peer_last_backfill.get(o, MAX_OID)
            if backfill_on and \
                    (lb != MAX_OID or
                     not self.pg_log.continuous_with(plog.head)):
                at = self.peer_backfill_at.get(o, eversion())
                if lb != MAX_OID and \
                        self.pg_log.continuous_with(at):
                    # RESUME: the retained log proves exactly what
                    # changed below the watermark since it was last
                    # valid — push those as log-delta, scan the rest
                    wm = lb
                    missing = {oid: e for oid, e in missing.items()
                               if oid <= wm}
                    for oid, e in \
                            self.pg_log.newest_per_object().items():
                        if oid <= wm and e.version > at:
                            missing[oid] = e
                else:
                    # fresh join, or the target was away so long the
                    # sub-watermark deltas fell off the log: nothing
                    # below the watermark is provably current — the
                    # scan must restart from MIN
                    wm = MIN_OID
                    missing = {}
                self.backfill_targets[o] = wm
                log.dout(1, f"pg {self.pgid} osd.{o} needs backfill "
                            f"(log head {plog.head} < tail "
                            f"{self.pg_log.tail}; watermark "
                            f"{wm!r})")
            self.peer_missing[o] = missing
        # a notify that raced this round (landed after find_best_info
        # ran) may know newer acked writes: go again rather than
        # activating and serving stale data. Terminates: the next round
        # adopts that log, making its head ours. (Backfill targets are
        # exempt: their entries are a subset of ours by construction.)
        if any(_key(o, pl) > _key(self.osd.whoami, self.pg_log)
               for o, pl in self.peer_logs.items()
               if o not in self.backfill_targets):
            log.dout(1, f"pg {self.pgid} raced notify knows newer "
                        f"writes; re-peering")
            self.state = "peering"
            self.osd.request_repeer(self, delay=0.2)
            return
        self.state = "active"
        # record + broadcast the activation epoch: this interval is
        # now "started", and every acting survivor must be able to
        # testify to it in a future election (see MOSDPGInfo.les) —
        # persist BEFORE serving so a crash can't forget the interval
        if self.interval_start > self.last_epoch_started:
            self.last_epoch_started = self.interval_start
            self.osd.store.queue_transaction(
                self._meta_txn(Transaction()))
            for o in self.acting:
                if o < 0 or o == self.osd.whoami or \
                        not self.osd.osd_is_up(o):
                    continue
                asyncio.ensure_future(self.osd.send_osd(
                    o, MOSDPGInfo(
                        pgid=self.cid, epoch=self.epoch,
                        from_osd=self.osd.whoami,
                        log=self.pg_log.encode(), notify=0,
                        intervals="", last_backfill=self.last_backfill,
                        backfill_at_epoch=self.backfill_at.epoch,
                        backfill_at_v=self.backfill_at.v,
                        les=self.last_epoch_started, activate=1)))
        # activation releases the peering backoffs: parked clients
        # resend and the ops now dispatch (ref: on_activate_complete
        # releasing PG backoffs)
        self.release_backoffs()
        if self._worker is None:
            self._worker = asyncio.ensure_future(self._op_worker())
        asyncio.ensure_future(self._recover())
        log.dout(5, f"pg {self.pgid} active; acting {self.acting} "
                    f"missing {sum(map(len, self.peer_missing.values()))}")

    def handle_pg_query(self, m: MOSDPGQuery) -> None:
        asyncio.ensure_future(self.osd.send_osd(m.from_osd, MOSDPGInfo(
            pgid=self.cid, epoch=self.epoch, from_osd=self.osd.whoami,
            log=self.pg_log.encode(), notify=0, intervals="",
            last_backfill=self.last_backfill,
            backfill_at_epoch=self.backfill_at.epoch,
            backfill_at_v=self.backfill_at.v,
            les=self.last_epoch_started, activate=0)))

    def handle_pg_info(self, m: MOSDPGInfo) -> None:
        if getattr(m, "activate", 0):
            # primary's activation broadcast: adopt the started epoch
            # so THIS replica can out-elect a revived older primary
            # even if the broadcasting primary later dies too
            if m.les > self.last_epoch_started:
                self.last_epoch_started = m.les
                try:
                    self.osd.store.queue_transaction(
                        self._meta_txn(Transaction()))
                except StoreError as e:
                    log.error(f"pg {self.pgid} les persist failed: "
                              f"{e}")
            return
        plog = PGLog.decode(m.log)
        self.peer_logs[m.from_osd] = plog
        self.peer_les[m.from_osd] = getattr(m, "les", 0)
        self.peer_last_backfill[m.from_osd] = m.last_backfill
        self.peer_backfill_at[m.from_osd] = eversion(
            m.backfill_at_epoch, m.backfill_at_v)
        if m.notify:
            # unsolicited stray announcement (ref: MOSDPGNotify): merge
            # its interval history so the coverage gate knows this OSD,
            # and if it knows writes we don't (a pgp_num change moved
            # the PG here before any data followed), re-peer — its log
            # now competes in find_best_info and recovery pulls from it
            self._notifiers.add(m.from_osd)
            if m.intervals:
                try:
                    have = {json.dumps(iv) for iv in self.past_intervals}
                    added = False
                    for iv in json.loads(m.intervals):
                        # prune like advance() does: an interval that
                        # closed before our last clean epoch is already
                        # covered — merging it verbatim could wedge the
                        # coverage gate on long-dead OSDs
                        if json.dumps(iv) not in have and \
                                len(iv) >= 2 and \
                                iv[1] >= self.last_epoch_clean:
                            self.past_intervals.append(iv)
                            added = True
                    if added:
                        # persist: merged intervals gate activation
                        # exactly like our own (advance() persists for
                        # the same reason) — a crash must not forget
                        # them
                        try:
                            self.osd.store.queue_transaction(
                                self._meta_txn(Transaction()))
                        except StoreError as e:
                            log.error(f"pg {self.pgid} interval "
                                      f"persist failed: {e}")
                except (ValueError, TypeError):
                    pass
            if self.is_primary() and plog.head > self.pg_log.head:
                # the stray knows writes we don't. Re-peer when settled;
                # when a round is mid-flight (it may already have passed
                # find_best_info), queue ANOTHER round — peer_logs keeps
                # this log, and _notifiers guarantees the stray is
                # re-queried even if it gets wiped
                log.dout(1, f"pg {self.pgid} stray osd.{m.from_osd} "
                            f"knows newer writes; re-peering")
                if self.state in ("active", "recovering", "clean"):
                    self.state = "peering"
                    self.osd.request_repeer(self, delay=0.1)
                # mid-peering arrivals are handled by the end-of-round
                # raced-notify check in _peer_inner
        expected = self._expected_infos or set(
            o for o in self.live_acting() if o != self.osd.whoami)
        if self._info_waiter and not self._info_waiter.done() and \
                set(self.peer_logs) >= expected:
            self._info_waiter.set_result(True)

    # -- self-managed snapshots (ref: PrimaryLogPG make_writeable /
    # SnapSet; clones are first-class objects in the same PG) ------------
    def _clone_list(self, oid: str) -> list[tuple[int, list[int]]]:
        """[(clone_id, covered_snap_ids)] ascending, from the clone
        objects' _clsnaps xattrs (served from the lazy per-PG index)."""
        if self._clone_idx is None:
            store = self.osd.store
            idx: dict[str, list] = {}
            prefix = CLONE_PREFIX
            try:
                names = store.list_objects(self.cid)
            except StoreError:
                names = []
            for name in names:
                head = clone_head(name)
                if head is None:
                    continue
                cid_ = int(name[len(prefix):].split(".", 1)[0])
                try:
                    blob = store.getattrs(self.cid, name).get("_clsnaps")
                except StoreError:
                    continue
                covered = json.loads(blob) if blob else []
                idx.setdefault(head, []).append((cid_, covered))
            for lst in idx.values():
                lst.sort()
            self._clone_idx = idx
        return self._clone_idx.get(oid, [])

    def _resolve_snap_read(self, oid: str, snap_id: int) -> str | None:
        """Object name serving a read AT snap_id, or None (-ENOENT):
        the clone covering the snap, else the head if the object
        existed unmodified since (and was not created after the snap)
        (ref: PrimaryLogPG::find_object_context snapid resolution)."""
        for cid_, covered in self._clone_list(oid):
            if snap_id in covered:
                return clone_name(oid, cid_)
        store = self.osd.store
        if not store.exists(self.cid, oid):
            return None
        try:
            pre = store.getattrs(self.cid, oid).get("_pre")
        except StoreError:
            return None
        if pre and snap_id in json.loads(pre):
            return None                 # created after this snap
        return oid

    def _maybe_cow(self, t: Transaction, oid: str, snap_seq: int,
                   snaps: list[int]) -> str | None:
        """Clone-on-write: preserve the head state for every live snap
        not yet covered by a clone, as part of the SAME transaction as
        the incoming mutation (ref: make_writeable). Returns the clone
        name when one was made (caller logs it so recovery tracks it)."""
        store = self.osd.store
        live = [s for s in snaps if s <= snap_seq]
        if not store.exists(self.cid, oid):
            return None     # born-after marking happens post-mutation
        covered: set[int] = set()
        for _, csnaps in self._clone_list(oid):
            covered |= set(csnaps)
        try:
            pre = store.getattrs(self.cid, oid).get("_pre")
            if pre:
                covered |= set(json.loads(pre))
        except StoreError:
            pass
        new_snaps = sorted(s for s in live if s not in covered)
        if not new_snaps:
            return None
        clone = clone_name(oid, snap_seq)
        if store.exists(self.cid, clone):
            # a clone for this snap id already exists (e.g. a stale
            # client snapc still names a snap whose clone was since
            # trimmed down): NEVER overwrite it — that would replace
            # data preserved for OTHER snaps with the current head
            # (silent snapshot corruption, r4 review finding)
            return None
        # O(metadata) clone (ref: make_writeable -> _make_clone): the
        # store's OP_CLONE carries data+attrs+omap to the clone object —
        # on BlueStore by sharing the head's blobs (refcount bump, zero
        # data bytes move), so snapshotting never costs O(size) here.
        size = store.stat(self.cid, oid)
        t.clone(self.cid, oid, clone)
        t.setattrs(self.cid, clone,
                   {"_clsnaps": json.dumps(new_snaps).encode()})
        t.rmattr(self.cid, clone, "_pre")
        # clone_overlap (ref: SnapSet::clone_overlap): byte ranges the
        # clone still shares with the head. Starts as the full clone
        # extent; head writes in this same op (and later ones) subtract
        # themselves in do_op. Only the NEWEST clone's overlap is live:
        # once a younger clone exists, the older clone's overlap-vs-head
        # at that moment equals its overlap vs the younger clone, and
        # both sides are immutable from then on — so freezing it is
        # exact, not an approximation. Recovery/scrub can use it to push
        # only divergent bytes.
        t.setattrs(self.cid, clone, {"_clover": json.dumps(
            [[0, size]] if size else []).encode()})
        self._clone_idx = None          # clone set changes when t lands
        return clone

    def _newest_clone_overlap(self, oid: str) -> tuple[str, list] | None:
        """(clone_name, overlap_intervals) of the newest existing clone
        of oid, or None when there is no clone / no recorded overlap."""
        clones = self._clone_list(oid)
        if not clones:
            return None
        name = clone_name(oid, clones[-1][0])
        try:
            blob = self.osd.store.getattrs(self.cid, name).get("_clover")
        except StoreError:
            return None
        if not blob:
            return None
        return name, json.loads(blob)

    @staticmethod
    def _overlap_sub(ivals: list, off: int, end: int | None) -> list:
        """Subtract [off, end) (end None = to infinity) from sorted
        disjoint [lo, hi) intervals (ref: interval_set::subtract)."""
        out = []
        for lo, hi in ivals:
            if (end is not None and end <= lo) or off >= hi:
                out.append([lo, hi])
                continue
            if lo < off:
                out.append([lo, off])
            if end is not None and end < hi:
                out.append([end, hi])
        return out

    def _snaptrim(self, t: Transaction, oid: str, snap_id: int) -> list:
        """Drop snap_id from the object's clones; clones covering no
        remaining snap are removed (ref: the snap trimmer /
        PrimaryLogPG::trim_object). Returns touched clone names."""
        touched = []
        for cid_, covered in self._clone_list(oid):
            if snap_id not in covered:
                continue
            covered = [s for s in covered if s != snap_id]
            name = clone_name(oid, cid_)
            if covered:
                t.setattrs(self.cid, name,
                           {"_clsnaps": json.dumps(covered).encode()})
            else:
                t.remove(self.cid, name)
            touched.append(name)
        if touched:
            self._clone_idx = None
        return touched

    async def snap_trim_removed(self, snap_id: int, batch: int,
                                sleep: float) -> int:
        """Primary-driven background trim of one deleted snapid (ref:
        PrimaryLogPG::do_snap_trim / the SnapTrimmer state machine,
        driven here from the osdmap's removed_snaps queue): every clone
        covering snap_id drops it, clones covering nothing are removed.
        Replicated via the normal repop pipeline (one log entry per
        touched clone), `batch` objects per burst with `sleep` between
        bursts so client I/O is not starved. Idempotent — a restart
        replays the whole removed_snaps queue. Returns objects trimmed."""
        if not self.is_primary():
            return 0
        store = self.osd.store
        try:
            names = store.list_objects(self.cid)
        except StoreError:
            return 0
        heads = sorted({h for h in (clone_head(n) for n in names)
                        if h is not None})
        done = 0
        for i, head in enumerate(heads):
            if not self.is_primary():       # map moved the PG away
                break
            t = Transaction()
            touched = self._snaptrim(t, head, snap_id)
            if not touched:
                continue
            reqid = (f"osd.{self.osd.whoami}.snaptrim", 0,
                     self.osd.next_tid())
            await self._submit_write(head, t, False, reqid,
                                     extra_oids=touched)
            done += 1
            if sleep and batch and (i + 1) % batch == 0:
                await asyncio.sleep(sleep)
        return done

    # -- watch/notify ------------------------------------------------------
    async def _do_notify(self, m, oid: str, timeout_ms: int,
                         payload: bytes) -> None:
        """Fan a notify out to every watcher and gather acks (ref:
        PrimaryLogPG::do_osd_op NOTIFY + watch_info_t). Runs as its own
        task so the op worker is not head-of-line blocked; NOTIFY_ACK
        ops bypass the worker queue (daemon routes them directly)."""
        notify_id = self.osd.next_tid()
        watchers = dict(self._watchers.get(oid, {}))
        # every watcher is pending BEFORE any send: an ack that races
        # in while later sends still await must neither be dropped nor
        # complete the future early (NOTIFY_ACK bypasses the op queue,
        # so it can arrive mid-loop)
        pending = set(watchers.keys())
        fut = asyncio.get_event_loop().create_future()
        acks: list = []
        self._notify_waiters[notify_id] = [pending, fut, acks]
        for (client, cookie), conn in list(watchers.items()):
            try:
                await conn.send_message(MWatchNotify(
                    oid=oid, pgid=self.cid, notify_id=notify_id,
                    cookie=cookie, payload=payload))
            except Exception:
                # dead watcher: drop the registration (the reference
                # ages watchers out via the watch timeout)
                self._watchers.get(oid, {}).pop((client, cookie), None)
                pending.discard((client, cookie))
        if pending:
            await asyncio.wait([fut],
                               timeout=(timeout_ms or 2000) / 1000.0)
        self._notify_waiters.pop(notify_id, None)
        await self._reply(m, 0, b"", {
            "notify_id": notify_id,
            "acks": sorted(str(k) for k in acks),
            "timeouts": sorted(str(k) for k in pending - set(acks))})

    def handle_notify_ack(self, client: str, notify_id: int,
                          cookie: int) -> None:
        ent = self._notify_waiters.get(notify_id)
        if ent is None:
            return
        pending, fut, acks = ent
        key = (client, cookie)
        if key in pending:
            acks.append(key)
            pending.discard(key)
        if not pending and not fut.done():
            fut.set_result(True)

    # -- pg splitting ------------------------------------------------------
    def split_objects(self, osdmap, new_pool) -> set:
        """pg_num grew: move every local object whose name now folds to
        a CHILD pg seed into that child's collection (ref: PG::
        split_into + pg_t::is_split — ceph_stable_mod guarantees a
        child's placement equals the parent's while pgp_num is
        unchanged, so the split is a local collection move; a later
        pgp_num bump migrates whole child PGs through normal peering).

        Runs on every replica identically (deterministic name fold), so
        post-split logs and stores stay consistent across the acting
        set. Idempotent: re-running moves nothing. Returns the child
        cids that received objects or log entries — the caller must
        ensure those children have local PG instances even when this
        OSD is not in their latest acting set (a batched pg_num +
        pgp_num map consume can move a child away in the same pass;
        without an instance there is no stray to announce the data)."""
        self._clone_idx = None          # clones move with their heads
        import numpy as np
        from ceph_tpu.osd.types import ObjectLocator, pg_t as _pg_t
        store = self.osd.store
        if self.cid not in store.list_collections():
            return set()
        moved = 0
        touched: set[str] = set()
        loc = ObjectLocator(pool=self.pool.id)
        for oid in list(store.list_objects(self.cid)):
            if oid == PGMETA:
                continue
            # snap clones fold by their HEAD's name (they must stay in
            # the head's PG)
            raw = osdmap.object_locator_to_pg(clone_head(oid) or oid,
                                              loc)
            # fold the raw hash by the NEW pg_num (the objecter's
            # _calc_target fold — ceph_stable_mod)
            seed = int(new_pool.raw_pg_to_pg(
                np.asarray([raw.seed]), xp=np)[0])
            if seed == self.pgid.seed:
                continue
            child_cid = str(_pg_t(self.pool.id, seed))
            t = Transaction()
            if child_cid not in store.list_collections():
                t.create_collection(child_cid)
                t.touch(child_cid, PGMETA)
            try:
                data = store.read(self.cid, oid)
                attrs = store.getattrs(self.cid, oid)
                omap = store.omap_get(self.cid, oid)
            except StoreError:
                continue
            t.touch(child_cid, oid)
            if data:
                t.write(child_cid, oid, 0, data)
            if attrs:
                t.setattrs(child_cid, oid, attrs)
            if omap:
                t.omap_setkeys(child_cid, oid, omap)
            t.remove(self.cid, oid)
            store.queue_transaction(t)
            moved += 1
            touched.add(child_cid)
        # Split the PG LOG with the objects (ref: PGLog::split_into).
        # Store moves alone are NOT enough: a replica that missed the
        # writes (down during them) has the hole in neither child store
        # nor child log — every peer's child log would be empty, the
        # logs compare equal, and the acked object is never recovered
        # (objects vanished under the round-4 deep thrash's pg_num
        # growth mid-recovery). Moving the entries lets the child's
        # peering see exactly the divergence the parent's log recorded.
        child_logs: dict[str, PGLog] = {}
        child_seen: dict[str, set] = {}
        keep: list[LogEntry] = []
        for entry in self.pg_log.entries:
            raw = osdmap.object_locator_to_pg(
                clone_head(entry.oid) or entry.oid, loc)
            seed = int(new_pool.raw_pg_to_pg(
                np.asarray([raw.seed]), xp=np)[0])
            if seed == self.pgid.seed:
                keep.append(entry)
                continue
            child_cid = str(_pg_t(self.pool.id, seed))
            clog = child_logs.get(child_cid)
            if clog is None:
                clog = PGLog()
                try:
                    blob = store.omap_get(child_cid, PGMETA).get(
                        "pg_log")
                    if blob:
                        clog = PGLog.decode(blob)
                except StoreError:
                    pass
                child_logs[child_cid] = clog
                # crash idempotency: a crash after the child's merged
                # log persisted but before the parent's trimmed meta
                # did re-runs this split with the moved entries ALREADY
                # in the loaded child log — appending them again would
                # duplicate them and skew head/version accounting
                child_seen[child_cid] = {
                    (e.version.epoch, e.version.v, e.oid)
                    for e in clog.entries}
            key = (entry.version.epoch, entry.version.v, entry.oid)
            if key in child_seen[child_cid]:
                continue
            child_seen[child_cid].add(key)
            clog.append(entry)
        if child_logs:
            self.pg_log.entries = keep
            # the parent's head must describe entries it still HAS:
            # keeping a head that moved to a child would win
            # find_best_info with a log that lacks writes a sibling
            # replica retained
            self.pg_log.head = keep[-1].version if keep else eversion()
            for child_cid, clog in child_logs.items():
                clog.entries.sort(key=lambda en: (en.version.epoch,
                                                  en.version.v))
                if clog.entries:
                    clog.head = clog.entries[-1].version
                t = Transaction()
                if child_cid not in store.list_collections():
                    t.create_collection(child_cid)
                    t.touch(child_cid, PGMETA)
                t.omap_setkeys(child_cid, PGMETA,
                               {"pg_log": clog.encode()})
                store.queue_transaction(t)
                # an already-instantiated child loaded its pre-split
                # persisted log; hand it the split result in memory too
                child_pg = self.osd.pgs.get(child_cid)
                if child_pg is not None:
                    child_pg.pg_log = clog
                    child_pg.last_user_version = max(
                        child_pg.last_user_version, clog.head.v)
            store.queue_transaction(self._meta_txn(Transaction()))
        touched.update(child_logs)
        if moved or child_logs:
            log.dout(1, f"pg {self.pgid} split: moved {moved} objects, "
                        f"{sum(len(c.entries) for c in child_logs.values())} "
                        f"log entries (pg_num -> {new_pool.pg_num})")
        return touched

    # -- pg merging (round 6: the inverse of split) ------------------------
    def is_merge_source(self) -> bool:
        """This PG is folded away by the pool's pending pg_num
        decrease (ref: pg_t::is_merge_source)."""
        return self.pool.is_merge_source(self.pgid.seed)

    def merge_ready(self) -> bool:
        """Quiesce barrier (ref: PeeringState ready_to_merge): a
        source is ready once it is CLEAN at the folded placement —
        pgp_num dropped with the pg_num_pending commit, so clean means
        the source already sits on its fold target's OSDs. From this
        moment new client ops are backed off (see OSD.ms_dispatch), so
        the store+log contents the fold will move are frozen modulo
        already-admitted ops, every one of which lands in the log and
        therefore in the merged parent."""
        return self.is_merge_source() and self.is_primary() and \
            self.state == "clean"

    def _stop_tasks(self) -> None:
        """Tear down a source PG's machinery before the fold."""
        if self._worker:
            self._worker.cancel()
            self._worker = None
            self._drain_op_queue()
        if self._peering_task:
            self._peering_task.cancel()
            self._peering_task = None
        self._cancel_backfill()

    def merge_from(self, source: "PG") -> None:
        """Fold ``source``'s collection back into this (parent) PG:
        objects, log entries and versions move; the source collection
        is removed (ref: PG::merge_from + PGLog merge on pg_num
        decrease).

        Runs on every OSD holding the source collection, off the SAME
        committed map, with the same deterministic fold — so replicas
        stay consistent, exactly like split_objects in reverse. The
        log merge dedups by (epoch, v, oid) (crash-idempotent: a
        crash between the parent meta persisting and the source
        collection removal re-runs the fold with the entries already
        present) and the parent re-peers afterwards so any divergence
        a replica's folded log carries is reconciled by the normal
        missing-set machinery."""
        store = self.osd.store
        source.release_backoffs()
        source._stop_tasks()
        self._clone_idx = None
        moved = 0
        if source.cid in store.list_collections():
            for oid in list(store.list_objects(source.cid)):
                if oid == PGMETA:
                    continue
                try:
                    data = store.read(source.cid, oid)
                    attrs = store.getattrs(source.cid, oid)
                    omap = store.omap_get(source.cid, oid)
                except StoreError:
                    continue
                t = Transaction()
                t.touch(self.cid, oid)
                if data:
                    t.write(self.cid, oid, 0, data)
                if attrs:
                    t.setattrs(self.cid, oid, attrs)
                if omap:
                    t.omap_setkeys(self.cid, oid, omap)
                t.remove(source.cid, oid)
                store.queue_transaction(t)
                moved += 1
        # merge the source's log (same dedup discipline as
        # split_objects' child_seen): without it a replica that held
        # the only copy of a source write would fold a log nobody
        # compares, and the write could be silently dropped
        seen = {(e.version.epoch, e.version.v, e.oid)
                for e in self.pg_log.entries}
        folded = 0
        for entry in source.pg_log.entries:
            key = (entry.version.epoch, entry.version.v, entry.oid)
            if key in seen:
                continue
            seen.add(key)
            self.pg_log.entries.append(entry)
            folded += 1
        if folded:
            self.pg_log.entries.sort(
                key=lambda en: (en.version.epoch, en.version.v))
            self.pg_log.head = self.pg_log.entries[-1].version
        # horizon: the merged log's tail is the YOUNGER of the two —
        # claiming the older horizon would promise log-delta recovery
        # for history only one half retains (conservative: peers below
        # it backfill, which is always safe)
        if source.pg_log.tail > self.pg_log.tail:
            self.pg_log.tail = source.pg_log.tail
        self.last_user_version = max(self.last_user_version,
                                     source.last_user_version,
                                     self.pg_log.head.v)
        # an incomplete party taints the merged watermark (upstream
        # marks the merged PG for backfill; the readiness barrier
        # makes this the crash-race path, not the normal one)
        if source.last_backfill != MAX_OID:
            self.last_backfill = min(self.last_backfill,
                                     source.last_backfill)
        try:
            self.osd.store.queue_transaction(
                self._meta_txn(Transaction()))
            if source.cid in store.list_collections():
                store.queue_transaction(
                    Transaction().remove_collection(source.cid))
        except StoreError as e:
            log.error(f"pg {self.pgid} merge meta persist failed: {e}")
        self._force_repeer = True
        log.dout(1, f"pg {self.pgid} absorbed {source.pgid}: "
                    f"{moved} objects, {folded} log entries "
                    f"(pg_num -> {self.pool.pg_num})")

    # -- recovery ----------------------------------------------------------
    async def _pull(self, from_osd: int, oid: str) -> None:
        """Primary pulls an object it is missing (ref: RecoveryOp pull)."""
        fut = asyncio.get_event_loop().create_future()
        self._push_waiters[oid] = fut
        await self.osd.send_osd(from_osd, MOSDPGPull(
            pgid=self.cid, epoch=self.epoch, oid=oid,
            from_osd=self.osd.whoami))
        try:
            await asyncio.wait_for(fut, timeout=3.0)
        except asyncio.TimeoutError:
            log.dout(1, f"pg {self.pgid} pull of {oid} timed out")
        finally:
            self._push_waiters.pop(oid, None)

    def handle_pg_pull(self, m: MOSDPGPull) -> None:
        # only answer exists=False when OUR LOG says the object was
        # deleted — a peer that merely never had the object (stale log,
        # mid-split, mid-recovery itself) must stay silent, or the
        # puller would "recover" the absence as an authoritative delete
        # and drop an acked object (round-4 deep thrash, obj35)
        if not self.osd.store.exists(self.cid, m.oid):
            newest = self.pg_log.newest_per_object().get(m.oid)
            if newest is None or newest.op != OP_DELETE:
                log.dout(1, f"pg {self.pgid} pull of {m.oid}: absent "
                            f"here with no delete entry; not answering")
                return
        asyncio.ensure_future(
            self.osd.send_osd(m.from_osd, self.make_push(m.oid)))

    def _object_state(self, oid: str):
        """(exists, data, attrs, omap, version)"""
        try:
            data = self.osd.store.read(self.cid, oid)
            attrs = self.osd.store.getattrs(self.cid, oid)
            omap = self.osd.store.omap_get(self.cid, oid)
        except StoreError:
            return False, b"", {}, {}, eversion()
        vb = attrs.get("_v")
        ver = eversion() if not vb else eversion(
            int.from_bytes(vb[:4], "little"),
            int.from_bytes(vb[4:12], "little"))
        return True, data, attrs, omap, ver

    def make_push(self, oid: str) -> MOSDPGPush:
        exists, data, attrs, omap, ver = self._object_state(oid)
        return MOSDPGPush(
            pgid=self.cid, epoch=self.epoch, oid=oid,
            version_epoch=ver.epoch, version_v=ver.v, exists=exists,
            data=data, attrs=attrs, omap=omap,
            from_osd=self.osd.whoami)

    def apply_push(self, m: MOSDPGPush) -> bool:
        """Apply a recovery push. Returns True iff the object durably
        landed — the caller must only ack on success, because the
        primary counts an ACKED push as 'recovered' for the durability
        promotion (_promote_pending_eagain)."""
        self._clone_idx = None          # pushes can create/replace clones
        t = Transaction()
        if m.exists:
            t.remove(self.cid, m.oid)
            t.write(self.cid, m.oid, 0, m.data)
            if m.attrs:
                t.setattrs(self.cid, m.oid, m.attrs)
            if m.omap:
                t.omap_setkeys(self.cid, m.oid, m.omap)
        else:
            t.remove(self.cid, m.oid)
        span = self.osd.tracer.from_msg(
            "push_apply", m, tags={"osd": self.osd.whoami,
                                   "oid": m.oid})
        try:
            self.osd.store.queue_transaction(t)
        except StoreError as e:
            log.error(f"pg {self.pgid} push apply failed: {e}")
            if span is not None:
                span.tag("error", str(e)).finish()
            return False
        finally:
            if span is not None and not span.finished:
                span.finish()
        self.my_missing.pop(m.oid, None)
        fut = self._push_waiters.get(m.oid)
        if fut and not fut.done():
            fut.set_result(True)
        return True

    def handle_push_reply(self, m: MOSDPGPushReply) -> None:
        fut = self._push_ack_waiters.get((m.from_osd, m.oid))
        if fut and not fut.done():
            fut.set_result(True)

    async def _send_gated_pushes(self, sends) -> bool:
        """Send recovery pushes and gate 'recovered' on the peer's ACK
        (MOSDPGPushReply): counting at send time would let
        _promote_pending_eagain flip an -EAGAIN'd write to success
        while a live acting replica still lacks it. Shared by the
        replicated and EC recovery paths (they differ only in how the
        push message is built).

        sends: [(peer_osd, oid, MOSDPGPush)]. Retires acked oids from
        peer_missing; returns True (and schedules a retry) when a LIVE
        peer's push went unacked — a down peer is left to the next map
        change."""
        acks: list[tuple[int, str, asyncio.Future, object]] = []
        for o, oid, push in sends:
            fut = asyncio.get_event_loop().create_future()
            self._push_ack_waiters[(o, oid)] = fut
            # each push is its own (head-sampled) trace root: recovery
            # has no client op to hang off, but its store/apply time
            # on the target is exactly the interference perf work
            # needs to see
            span = self.osd.tracer.start_root(
                "recovery_push",
                tags={"pgid": self.cid, "oid": oid, "to_osd": o})
            push.set_trace(span)
            try:
                await self.osd.send_osd(o, push)
            except Exception as e:
                log.dout(1, f"pg {self.pgid} push {oid}->{o} "
                            f"failed: {e}")
                self._push_ack_waiters.pop((o, oid), None)
                if span is not None:
                    span.tag("send_failed", True).finish()
                continue
            acks.append((o, oid, fut, span))
        if acks:
            await asyncio.wait([f for _, _, f, _ in acks], timeout=5.0)
        incomplete = False
        for o, oid, fut, span in acks:
            self._push_ack_waiters.pop((o, oid), None)
            if span is not None:
                if not fut.done():
                    span.tag("unacked", True)
                span.finish()
            if fut.done():
                self.peer_missing.get(o, {}).pop(oid, None)
            elif self.osd.osd_is_up(o):
                incomplete = True
        if incomplete:
            log.dout(1, f"pg {self.pgid} recovery pushes unacked; "
                        "retrying")
            loop = asyncio.get_event_loop()
            loop.call_later(1.0, lambda: asyncio.ensure_future(
                self._recover()))
        return incomplete

    async def _recover(self) -> None:
        """Push every peer's missing objects (ref: run_recovery_op)."""
        if not self.is_primary():
            return
        self.state = "recovering" if any(self.peer_missing.values()) \
            else self.state
        sends = [(o, oid, self.make_push(oid))
                 for o, missing in list(self.peer_missing.items())
                 for oid in list(missing)]
        if await self._send_gated_pushes(sends):
            return
        if not any(self.peer_missing.values()) and \
                self.state in ("active", "recovering"):
            if self._maybe_start_backfill():
                return          # clean is decided when backfill ends
            if len(self.live_acting()) >= self.pool.size:
                self._mark_clean()
            else:
                self.state = "active"
            self._promote_pending_eagain()

    def _maybe_start_backfill(self) -> bool:
        """Kick the backfill driver when peering flagged targets.
        Returns True while backfill owns the clean decision."""
        if self._backfill_task is not None:
            return True
        if not self.backfill_targets:
            return False
        self._backfill_task = asyncio.ensure_future(
            self._backfill())
        return True

    def _mark_clean(self) -> None:
        """Every acting replica has every object at full size: past
        intervals are subsumed by the current one (ref: last_epoch_clean
        gating PastIntervals trimming). Every OSD that hosted the PG
        since the previous clean is told, so replica/stray instances
        trim their own copies too — otherwise a later promotion of one
        of them would block forever on intervals this clean made
        irrelevant (r4 review finding)."""
        notify = set(self.acting)
        for iv in self.past_intervals:
            notify.update(iv[2])
        notify.discard(self.osd.whoami)
        self.state = "clean"
        self.last_epoch_clean = self.epoch
        self.past_intervals = []
        try:
            self.osd.store.queue_transaction(
                self._meta_txn(Transaction()))
        except StoreError as e:
            log.error(f"pg {self.pgid} clean meta persist failed: {e}")
        from ceph_tpu.osd.messages import MPGCleanNotice
        for o in notify:
            if o >= 0 and self.osd.osd_is_up(o):
                asyncio.ensure_future(self.osd.send_osd(
                    o, MPGCleanNotice(pgid=self.cid, epoch=self.epoch,
                                      from_osd=self.osd.whoami)))

    def handle_clean_notice(self, m) -> None:
        """Replica/stray half of _mark_clean's trimming."""
        if m.epoch <= self.last_epoch_clean:
            return
        self.last_epoch_clean = m.epoch
        self.past_intervals = [iv for iv in self.past_intervals
                               if iv[1] >= m.epoch]
        try:
            self.osd.store.queue_transaction(
                self._meta_txn(Transaction()))
        except StoreError as e:
            log.error(f"pg {self.pgid} clean-notice persist failed: {e}")

    # -- op execution ------------------------------------------------------
    async def queue_op(self, m: MOSDOp) -> None:
        await self.op_queue.put(m)

    def _drain_op_queue(self) -> None:
        """Release the admission-throttle slot of every queued-but-
        never-executed op (worker cancelled on primaryship loss)."""
        while True:
            try:
                m = self.op_queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            cost = getattr(m, "_throttle_cost", None)
            if cost is not None:
                self.osd.client_throttle.release(cost)

    async def _op_worker(self) -> None:
        import time as _time
        try:
            while True:
                m = await self.op_queue.get()
                tracked = self.osd.op_tracker.create(
                    f"osd_op({m.src} {self.cid} {m.oid} "
                    f"tid={m.tid})")
                if not self.role_active():
                    tracked.mark_event("waiting_for_active")
                    while not self.role_active():
                        await asyncio.sleep(0.05)
                tracked.mark_event("started")
                # trace phases: "queue" (admission -> here) closes,
                # "execute" opens; _submit_write hangs the repop/store
                # children off self._active_span
                op_span = getattr(m, "_span", None)
                qspan = getattr(m, "_queue_span", None)
                if qspan is not None:
                    qspan.finish()
                self._active_span = op_span.child("execute") \
                    if op_span is not None else None
                t0 = _time.monotonic()
                try:
                    await self._execute(m)
                except Exception as e:
                    log.error(f"pg {self.pgid} op failed: {e}")
                    await self._reply(m, -5, b"", {})       # -EIO
                finally:
                    tracked.finish()
                    if self._active_span is not None:
                        self._active_span.finish()
                        self._active_span = None
                    if op_span is not None:
                        op_span.finish()
                    # per-op-class latency histogram (µs, log2
                    # buckets) — queryable tail latency even with
                    # tracing sampled out
                    cls_key = "op_w_latency_hist" if any(
                        c in MUTATING_OPS for c in m.op_codes) \
                        else "op_r_latency_hist"
                    self.osd.perf.hist_add(
                        cls_key, (_time.monotonic() - t0) * 1e6)
                    self.osd.perf.inc("ops")
                    src = str(m.src)
                    self.client_ops[src] = \
                        self.client_ops.get(src, 0) + 1
                    cost = getattr(m, "_throttle_cost", None)
                    if cost is not None:
                        self.osd.client_throttle.release(cost)
                if self.backoffs and self.role_active() and \
                        self.op_queue.qsize() <= int(self.osd.config.get(
                            "osd_pg_op_queue_cap", 512)) // 2:
                    # saturation backoffs: the queue drained — let the
                    # parked clients resend
                    self.release_backoffs()
        except asyncio.CancelledError:
            pass

    async def _reply(self, m: MOSDOp, result: int, data: bytes,
                     extra: dict) -> None:
        if m.conn is None:
            return
        try:
            await m.conn.send_message(MOSDOpReply(
                tid=m.tid, attempt=getattr(m, "attempt", 0),
                result=result, epoch=self.epoch, data=data,
                extra=json.dumps(extra) if extra else ""))
        except Exception:
            pass                          # client resends via objecter

    async def _execute(self, m: MOSDOp) -> None:
        """ref: PrimaryLogPG::execute_ctx — reads serve immediately,
        writes run the replication pipeline. Mutations are deduped by
        (client, tid) so objecter resends of an applied-but-unacked op
        (e.g. a non-idempotent DELETE) return the original result."""
        # reqid = (entity, messenger incarnation, tid) — distinct client
        # processes sharing a name must not collide
        reqid = (m.src, getattr(m.conn, "peer_session", 0), m.tid)
        if m.oid in self.my_missing:
            # a just-promoted/revived primary may not yet hold this
            # object: serving now would return -ENOENT for an existing
            # object (or mutate around missing state). Park via -EAGAIN
            # until recovery lands it (ref: PrimaryLogPG::
            # wait_for_unreadable_object).
            await self._reply(m, -11, b"", {})
            return
        mutating = {OSD_OP_WRITE, OSD_OP_WRITEFULL, OSD_OP_TRUNCATE,
                    OSD_OP_ZERO, OSD_OP_DELETE, OSD_OP_SETXATTR,
                    OSD_OP_OMAP_SET, OSD_OP_SNAPTRIM}
        if self._backfill_blocked(
                m.oid, any(c in mutating for c in m.op_codes)):
            await self._reply(m, -11, b"", {})          # -EAGAIN
            return
        if any(c in mutating for c in m.op_codes) and \
                reqid in self._reqid_results:
            # resend of an applied-but-unacked mutation: return the
            # recorded outcome, never re-execute (a DELETE replay would
            # spuriously return -ENOENT; a write would duplicate log
            # entries). ref: PrimaryLogPG::already_complete (reqids)
            # A recorded -EAGAIN means the op is applied locally but NOT
            # yet known durable: the dup keeps seeing -EAGAIN (the
            # objecter backs off and resends) until the late
            # MOSDRepOpReply or a re-peer + completed recovery promotes
            # the record to success (ref: PrimaryLogPG::already_complete
            # only short-circuits dups of committed repops). Replying
            # immediately — rather than parking the dup on the repop
            # future — keeps the serialized op worker free.
            result, extra = self._reqid_results[reqid]
            await self._reply(m, result, b"", extra)
            return
        store = self.osd.store
        cid = self.cid
        oid = m.oid
        data_out = b""
        extra: dict = {}
        t = Transaction()
        mutated = False
        deleted = False
        cow_clones: list[str] = []
        snap_seq = getattr(m, "snap_seq", 0)
        snapc = list(getattr(m, "snaps", []) or [])
        snap_id = getattr(m, "snap_id", 0)
        # filter the client's snap context against the pool's deletion
        # queue (ref: PrimaryLogPG::filter_snapc): a laggy client whose
        # context still names a deleted snap must not make the COW path
        # mint a clone covering it — the trimmer already ran for that
        # snapid and would never revisit it
        removed = self.pool.extra.get("removed_snaps")
        if removed and snapc:
            rm = set(removed)
            snapc = [s for s in snapc if s not in rm]
        # snap reads resolve once to the serving object (clone or head)
        read_oid = oid
        if snap_id:
            resolved = self._resolve_snap_read(oid, snap_id)
            if resolved is None:
                await self._reply(m, -2, b"", {})           # -ENOENT
                return
            read_oid = resolved
        born_after: list[int] = []
        # clone_overlap upkeep: (clone_name, intervals) of the newest
        # clone; data-mutating ops below subtract their ranges, and a
        # single _clover setattrs is appended after the op loop when
        # anything actually shrank (setattrs auto-creates, so writing
        # unchanged intervals back could resurrect a trimmed clone)
        overlap: tuple[str, list] | None = None
        overlap_dirty = False
        if any(c in mutating for c in m.op_codes):
            overlap = self._newest_clone_overlap(oid)
        if snap_seq and any(c in mutating for c in m.op_codes):
            # clone-on-write rides in the SAME transaction as the
            # mutation (atomic on every replica); the clone gets its own
            # log entry below so log-based recovery tracks it
            clone = self._maybe_cow(t, oid, snap_seq, snapc)
            if clone:
                cow_clones.append(clone)
                # the just-made clone (same txn) is now the newest:
                # its overlap starts at the full pre-mutation extent
                try:
                    sz = store.stat(cid, oid)
                except StoreError:
                    sz = 0
                overlap = (clone, [[0, sz]] if sz else [])
            elif not store.exists(cid, oid):
                # the object is being born after these snaps existed:
                # mark it (APPENDED after the mutation ops — a WRITEFULL
                # remove would wipe an earlier xattr) so snap reads at
                # them say -ENOENT
                born_after = sorted(s for s in snapc if s <= snap_seq)
        for code, off, length, name, data in m.unpack_ops():
            if code == OSD_OP_READ:
                try:
                    data_out = store.read(
                        cid, read_oid, off, length if length else None)
                except StoreError:
                    await self._reply(m, -2, b"", {})       # -ENOENT
                    return
            elif code == OSD_OP_STAT:
                try:
                    extra["size"] = store.stat(cid, read_oid)
                except StoreError:
                    await self._reply(m, -2, b"", {})
                    return
            elif code == OSD_OP_GETXATTR:
                try:
                    attrs = store.getattrs(cid, read_oid)
                except StoreError:
                    await self._reply(m, -2, b"", {})
                    return
                if name not in attrs:
                    await self._reply(m, -61, b"", {})      # -ENODATA
                    return
                data_out = attrs[name]
            elif code == OSD_OP_OMAP_GET:
                try:
                    omap = store.omap_get(cid, read_oid)
                except StoreError:
                    await self._reply(m, -2, b"", {})
                    return
                # name = optional key-prefix filter (ref: the role of
                # omap_get_vals' start_after/filter_prefix) — callers
                # with large omaps fetch only the range they need
                extra["omap"] = {k: v.hex() for k, v in omap.items()
                                 if not k.startswith("_")
                                 and (not name or k.startswith(name))}
            elif code == OSD_OP_PGLS:
                objs = [o for o in store.list_objects(cid)
                        if o != PGMETA and clone_head(o) is None]
                extra["objects"] = objs
            elif code == OSD_OP_WATCH:
                self._watchers.setdefault(oid, {})[(m.src, off)] = m.conn
            elif code == OSD_OP_UNWATCH:
                self._watchers.get(oid, {}).pop((m.src, off), None)
            elif code == OSD_OP_NOTIFY:
                asyncio.ensure_future(
                    self._do_notify(m, oid, off, data))
                return                      # replies when acks are in
            elif code == OSD_OP_NOTIFY_ACK:
                self.handle_notify_ack(m.src, off, length)
            elif code == OSD_OP_SNAPTRIM:
                touched = self._snaptrim(t, oid, off)
                if touched:
                    mutated = True
                    cow_clones.extend(touched)
                overlap = None      # clone set changed under us
            elif code == OSD_OP_WRITE:
                t.write(cid, oid, off, data)
                mutated = True
                if overlap:
                    overlap = (overlap[0], self._overlap_sub(
                        overlap[1], off, off + len(data)))
                    overlap_dirty = True
            elif code == OSD_OP_WRITEFULL:
                t.remove(cid, oid)
                t.write(cid, oid, 0, data)
                mutated = True
                if overlap:
                    overlap = (overlap[0], [])
                    overlap_dirty = True
            elif code == OSD_OP_TRUNCATE:
                t.truncate(cid, oid, off)
                mutated = True
                if overlap:
                    overlap = (overlap[0], self._overlap_sub(
                        overlap[1], off, None))
                    overlap_dirty = True
            elif code == OSD_OP_ZERO:
                t.zero(cid, oid, off, length)
                mutated = True
                if overlap:
                    overlap = (overlap[0], self._overlap_sub(
                        overlap[1], off, off + length))
                    overlap_dirty = True
            elif code == OSD_OP_DELETE:
                if not store.exists(cid, oid):
                    await self._reply(m, -2, b"", {})
                    return
                t.remove(cid, oid)
                mutated = True
                deleted = True
                if overlap:
                    overlap = (overlap[0], [])
                    overlap_dirty = True
            elif code == OSD_OP_SETXATTR:
                t.touch(cid, oid)
                # attrs persist past the op: copy out of the frame view
                t.setattrs(cid, oid, {name: bytes(data)})
                mutated = True
            elif code == OSD_OP_OMAP_SET:
                t.touch(cid, oid)
                t.omap_setkeys(cid, oid, {name: bytes(data)})
                mutated = True
            elif code == OSD_OP_OMAP_RM:
                if not store.exists(cid, oid):
                    await self._reply(m, -2, b"", {})
                    return
                t.omap_rmkeys(cid, oid, [name])
                mutated = True
            else:
                await self._reply(m, -95, b"", {})   # -EOPNOTSUPP
                return
        if not mutated:
            await self._reply(m, 0, data_out, extra)
            return
        if born_after and not deleted:
            t.setattrs(cid, oid,
                       {"_pre": json.dumps(born_after).encode()})
        if overlap is not None and overlap_dirty:
            # last-op-wins: this setattrs lands after _maybe_cow's
            # initial full-extent _clover in the same transaction
            t.setattrs(cid, overlap[0],
                       {"_clover": json.dumps(overlap[1]).encode()})
        result, applied, waiter = await self._submit_write(
            oid, t, deleted, reqid, extra_oids=cow_clones)
        if result == -11 and waiter is not None and waiter.done():
            # the last reply landed between the timeout firing and this
            # task resuming: the repop IS fully committed — without this
            # check the -11 would be recorded with the waiter already
            # popped, and nothing could ever promote it
            result = 0
        extra["version"] = str(self.pg_log.head)
        if applied:
            # The op is in the pg log, so a RESEND must never re-execute
            # (a DELETE replay would return -ENOENT; a write would
            # duplicate log entries) — but a repop-timeout -EAGAIN is
            # recorded AS -EAGAIN: dups keep seeing -EAGAIN until the
            # repop commits on every live acting replica (late reply) or
            # a re-peer + recovery has made the log durable on the new
            # acting set (_promote_pending_eagain). Recording 0 here
            # immediately (round 3) let a dup be acked with fewer than
            # min_size durable copies (ADVICE.md round 3, medium).
            self._reqid_results[reqid] = (result, extra)
        if len(self._reqid_results) > 2000:      # bounded (log-trim analog)
            kept_eagain = 0
            for k in list(self._reqid_results)[:1000]:
                if self._reqid_results.get(k, (0,))[0] == -11 and \
                        kept_eagain < 500:
                    # keep -EAGAIN entries awaiting promotion — but
                    # only a bounded number: a wedged replica would
                    # otherwise grow the table by one per timed-out
                    # write forever. Beyond the cap the oldest are
                    # evicted like any trimmed reqid: a later dup
                    # re-executes, which is the reference's semantics
                    # once a reqid ages out of the pg log's dup window.
                    kept_eagain += 1
                    continue
                self._reqid_results.pop(k, None)
        await self._reply(m, result, data_out, extra)

    async def _submit_write(self, oid: str, t: Transaction, deleted: bool,
                            reqid: tuple,
                            extra_oids: list[str] | None = None) -> tuple:
        """The replication pipeline (ref: ReplicatedBackend::
        submit_transaction + issue_repop). Returns (result, applied,
        waiter): ``applied`` is True iff the op landed in the local
        store+log (it may still report -EAGAIN when replicas never
        confirmed — the repop record stays registered, marked
        timed_out, so a late reply can complete it and promote the
        dedup result)."""
        if len(self.live_acting()) < self.pool.min_size:
            return -11, False, None                     # -EAGAIN
        # backfill straddle gate: one txn can touch the head AND its
        # snap clones, whose names sort far apart. For a backfill
        # target the whole txn must be send-or-skip by its watermark —
        # sending would materialize partial state for the above-
        # watermark oid, skipping would silently drop the below-
        # watermark one (the scan never revisits covered ground). A
        # straddling txn parks until the watermark moves past it.
        if self.backfill_targets:
            txn_oids = [oid] + list(extra_oids or [])
            for lb in self.backfill_targets.values():
                below = [x <= lb for x in txn_oids]
                if any(below) and not all(below):
                    return -11, False, None             # -EAGAIN
        self.last_user_version += 1
        version = eversion(self.epoch, self.last_user_version)
        entry = self.pg_log.add(
            version, oid, OP_DELETE if deleted else OP_MODIFY)
        # snap clones created/trimmed in this txn get their own log
        # entries so peering's missing computation recovers them too —
        # shipped to replicas alongside the head entry
        extra_entries = []
        for clone_oid in (extra_oids or []):
            self.last_user_version += 1
            extra_entries.append(self.pg_log.add(
                eversion(self.epoch, self.last_user_version),
                clone_oid, OP_MODIFY))
        self.pg_log.trim(keep=self._trim_keep())
        if not deleted:
            t.setattrs(self.cid, oid, {"_v":
                       version.epoch.to_bytes(4, "little") +
                       version.v.to_bytes(8, "little")})
        self._meta_txn(t)
        txn_blob = t.encode()
        replicas = [o for o in self.live_acting()
                    if o != self.osd.whoami
                    and self._should_send_repop(o, oid)]
        tid = self.osd.next_tid()
        waiter = None
        if replicas:
            waiter = asyncio.get_event_loop().create_future()
            self._repop_waiters[tid] = [set(replicas), waiter, reqid,
                                        False]
        op_span = self._active_span
        store_span = op_span.child(
            "objectstore_commit",
            tags={"osd": self.osd.whoami}) if op_span else None
        import time as _time
        _t0 = _time.monotonic()
        try:
            self.osd.store.queue_transaction(t)
        except StoreError as e:
            log.error(f"pg {self.pgid} local commit failed: {e}")
            self._repop_waiters.pop(tid, None)
            return -5, False, waiter
        finally:
            _finish_store_span(store_span, self.osd.store)
            # the `ceph osd perf` commit leg: primary-side txn commit
            # time as a reported time-avg (ref: os_commit_latency)
            self.osd.perf.avg_add("commit_latency",
                                  _time.monotonic() - _t0)
        repop_span = op_span.child(
            "repop_wait",
            tags={"replicas": sorted(replicas)}) \
            if op_span and replicas else None
        send_failed = False
        for o in replicas:
            rep = MOSDRepOp(
                tid=tid, epoch=self.epoch, pgid=self.cid,
                txn=txn_blob, log_entry=entry.encode(),
                extra_log=[e.encode() for e in extra_entries])
            rep.set_trace(repop_span)
            try:
                await self.osd.send_osd(o, rep)
            except (ConnectionError, OSError, asyncio.TimeoutError,
                    ConnectionError_) as e:
                # An unreachable replica (SIGKILLed process, dead
                # port) must NOT surface as client EIO: it is the same
                # situation as a replica that never confirms, so it
                # takes the same -EAGAIN exit below — the objecter
                # resends once the map moves and the PG re-peers.
                send_failed = True
                log.dout(1, f"pg {self.pgid} repop {tid} -> osd.{o} "
                            f"send failed: {e!r}")
        if waiter is not None and send_failed:
            ent = self._repop_waiters.get(tid)
            if ent is not None:
                ent[3] = True
            if repop_span is not None:
                repop_span.tag("send_failed", True)
                repop_span.finish()
            return -11, True, waiter                    # -EAGAIN
        if waiter is not None:
            # asyncio.wait (NOT wait_for): wait_for CANCELS the future
            # on timeout, which would make it impossible for a late
            # MOSDRepOpReply to ever complete the repop — and dups of
            # the -EAGAIN'd op would stay -EAGAIN until re-peer even
            # though every replica committed.
            done, _ = await asyncio.wait(
                [waiter],
                timeout=self.osd.config.get("osd_repop_timeout", 5.0))
            if repop_span is not None:
                if not done:
                    repop_span.tag("timed_out", True)
                repop_span.finish()
            if not done:
                # A replica never confirmed: the client MUST NOT see
                # success, or a subsequent primary failure could lose an
                # acknowledged write (ref: ReplicatedBackend's
                # all-replica-commit-before-ack contract). -EAGAIN makes
                # the objecter resend once the map moves and the PG
                # re-peers. The record stays in _repop_waiters, marked
                # timed_out: a late reply promotes the recorded dedup
                # result to success (handle_rep_reply).
                ent = self._repop_waiters.get(tid)
                if ent is not None:
                    ent[3] = True
                # bound the timed-out backlog: under a wedged-but-up
                # replica every write parks a record here; beyond the
                # cap the oldest are dropped (their dup entries age out
                # of _reqid_results the same way — reference semantics
                # once a reqid leaves the pg log's dup window)
                stale = [t_ for t_, e_ in self._repop_waiters.items()
                         if e_[3]]
                for t_ in stale[:-500]:
                    self._repop_waiters.pop(t_, None)
                log.dout(1, f"pg {self.pgid} repop {tid} timed out")
                return -11, True, waiter                # -EAGAIN
            self._repop_waiters.pop(tid, None)
        return 0, True, waiter

    def handle_rep_op(self, m: MOSDRepOp) -> None:
        """Replica applies the shipped transaction (ref:
        ReplicatedBackend::do_repop)."""
        self._clone_idx = None      # the txn may create/trim clones; a
        # later re-promotion to primary must not serve a stale index
        span = self.osd.tracer.from_msg(
            "repop_apply", m, tags={"osd": self.osd.whoami,
                                    "pgid": self.cid})
        entry = LogEntry.decode(m.log_entry)
        t = Transaction.decode(m.txn)
        store_span = span.child(
            "objectstore_commit",
            tags={"osd": self.osd.whoami}) if span else None
        import time as _time
        _t0 = _time.monotonic()
        try:
            self.osd.store.queue_transaction(t)
        except StoreError as e:
            log.error(f"pg {self.pgid} repop apply failed: {e}")
            if span is not None:
                span.tag("error", str(e)).finish()
            return
        finally:
            _finish_store_span(store_span, self.osd.store)
            # the `ceph osd perf` apply leg (ref: os_apply_latency)
            self.osd.perf.avg_add("apply_latency",
                                  _time.monotonic() - _t0)
        if span is not None:
            span.finish()
        self.pg_log.append(entry)
        for blob in getattr(m, "extra_log", None) or []:
            e2 = LogEntry.decode(blob)
            self.pg_log.append(e2)
            self.last_user_version = max(self.last_user_version,
                                         e2.version.v)
        self.pg_log.trim(keep=self._trim_keep())
        self.last_user_version = max(self.last_user_version,
                                     entry.version.v)

        async def _ack():
            try:
                # reply on the incoming connection: the replica may not
                # have seen the map naming the primary yet
                await m.conn.send_message(MOSDRepOpReply(
                    tid=m.tid, result=0, pgid=self.cid,
                    from_osd=self.osd.whoami))
            except Exception:
                pass      # primary's repop timeout covers the loss
        asyncio.ensure_future(_ack())

    def handle_rep_reply(self, m: MOSDRepOpReply) -> None:
        ent = self._repop_waiters.get(m.tid)
        if ent is None:
            return
        pending, fut, reqid, timed_out = ent
        pending.discard(m.from_osd)
        if not pending:
            if not fut.done():
                fut.set_result(True)
            self._repop_waiters.pop(m.tid, None)
            if timed_out:
                # Late completion of a timed-out repop: every live
                # acting replica has now committed, so dups of the
                # -EAGAIN'd op may see success. (If the client task has
                # not recorded the -11 yet, its waiter.done() check in
                # _execute sees the completion instead.)
                self._promote(reqid)

    def _promote(self, reqid: tuple) -> None:
        res = self._reqid_results.get(reqid)
        if res and res[0] == -11:
            self._reqid_results[reqid] = (0, res[1])

    def _promote_pending_eagain(self) -> None:
        """A re-peer + acked recovery has made every pg-log entry
        durable on the (new) live acting set — writes whose repop timed
        out in an earlier interval are now recoverable from any acting
        member, so their dedup results flip from -EAGAIN to success
        (the 'log-based recovery has made it durable' argument, gated
        on recovery pushes actually being ACKED, not merely sent).
        Only timed-out records are touched: in-flight repops of the
        current interval keep their waiters. A record whose
        never-replied replica is STILL live in the current acting set
        must NOT promote — recovery completing for older objects says
        nothing about this write, which was logged after peering and so
        was never in peer_missing (r4 review finding: promoting it
        would ack a write a live acting replica lacks)."""
        for tid, ent in list(self._repop_waiters.items()):
            if not ent[3]:                # not timed out: still in flight
                continue
            if any(r in self.acting and self.osd.osd_is_up(r)
                   for r in ent[0]):
                continue                  # wedged live replica: keep -EAGAIN
            self._repop_waiters.pop(tid, None)
            self._promote(ent[2])
            if not ent[1].done():
                ent[1].set_result(True)

    # -- backfill (ref: PrimaryLogPG's backfill state machine) -------------
    def _version_blob(self, oid: str) -> bytes:
        """The object's 12-byte ``_v`` xattr (epoch u32le + v u64le) —
        the scan digest's version token. Identical layout on replicated
        objects and EC shards, so one comparison serves both."""
        try:
            return self.osd.store.getattrs(self.cid, oid).get("_v", b"")
        except StoreError:
            return b""

    async def _build_backfill_push(self, oid: str, target: int):
        """Whole-object push for a backfill target (replicated PGs push
        the primary's byte-identical copy; ECPG overrides to rebuild
        the target POSITION's shard). None = cannot build right now."""
        return self.make_push(oid)

    async def _backfill_push_acked(self, oid: str, target: int) -> bool:
        """One throttled, ACK-gated backfill push. The QoS throttle
        (osd_recovery_max_active + osd_recovery_max_bytes) runs HERE —
        client ops never touch it, so under contention backfill queues
        behind its own budget while foreground writes flow."""
        push = await self._build_backfill_push(oid, target)
        if push is None:
            return False
        release = await self.osd.recovery_throttle.acquire(
            len(push.data))
        fut = asyncio.get_event_loop().create_future()
        self._push_ack_waiters[(target, oid)] = fut
        span = self.osd.tracer.start_root(
            "backfill_push",
            tags={"pgid": self.cid, "oid": oid, "to_osd": target})
        push.set_trace(span)
        try:
            await self.osd.send_osd(target, push)
            await asyncio.wait([fut], timeout=5.0)
            return fut.done()
        except Exception as e:
            log.dout(1, f"pg {self.pgid} backfill push {oid}->"
                        f"osd.{target} failed: {e}")
            return False
        finally:
            release()
            self._push_ack_waiters.pop((target, oid), None)
            if span is not None:
                if not fut.done():
                    span.tag("unacked", True)
                span.finish()

    async def _scan_peer(self, osd_id: int, begin: str, end: str,
                         limit: int = 0):
        """Request a peer's sorted (begin, end] object/version digest
        (ref: MOSDPGScan round trip). None on timeout/failure."""
        tid = self.osd.next_tid()
        fut = asyncio.get_event_loop().create_future()
        self._backfill_waiters[tid] = fut
        try:
            await self.osd.send_osd(osd_id, MOSDPGScan(
                pgid=self.cid, epoch=self.epoch, tid=tid, begin=begin,
                end=end, limit=limit, from_osd=self.osd.whoami))
            return await asyncio.wait_for(fut, timeout=5.0)
        except Exception:
            return None
        finally:
            self._backfill_waiters.pop(tid, None)

    async def _backfill_ctl(self, target: int, op: int,
                            watermark: str) -> bool:
        """Watermark control round trip: the target PERSISTS the new
        last_backfill before acking, so an acked PROGRESS/FINISH is a
        durable resume point (FINISH ships the authoritative log — the
        target is then log-continuous and a normal replica)."""
        tid = self.osd.next_tid()
        fut = asyncio.get_event_loop().create_future()
        self._backfill_waiters[tid] = fut
        try:
            head = self.pg_log.head
            await self.osd.send_osd(target, MOSDPGBackfill(
                pgid=self.cid, epoch=self.epoch, tid=tid, op=op,
                last_backfill=watermark,
                log=self.pg_log.encode()
                if op == BACKFILL_OP_FINISH else b"",
                at_epoch=head.epoch, at_v=head.v,
                from_osd=self.osd.whoami))
            m = await asyncio.wait_for(fut, timeout=5.0)
            return m.result == 0
        except Exception:
            return False
        finally:
            self._backfill_waiters.pop(tid, None)

    async def _reserve_remote(self, target: int) -> str:
        """'grant' | 'reject' | 'toofull' from the target's reserver."""
        tid = self.osd.next_tid()
        fut = asyncio.get_event_loop().create_future()
        self._backfill_waiters[tid] = fut
        try:
            await self.osd.send_osd(target, MBackfillReserve(
                pgid=self.cid, epoch=self.epoch, tid=tid,
                op=RESERVE_REQUEST, from_osd=self.osd.whoami))
            m = await asyncio.wait_for(fut, timeout=3.0)
            if m.op == RESERVE_GRANT:
                self._reserve_tids[target] = tid
                return "grant"
            return "toofull" if m.op == RESERVE_TOOFULL else "reject"
        except Exception:
            return "reject"
        finally:
            self._backfill_waiters.pop(tid, None)

    async def _backfill(self) -> None:
        """Primary backfill driver: reserve (local slot, then one
        remote slot per target, capped at osd_max_backfills on each
        OSD), then scan/push each target forward from its persisted
        watermark. backfill_wait = waiting on a slot; backfill_toofull
        = a target refused for fullness; backfilling = scans running."""
        # interval identity, NOT the raw epoch: map epochs advance for
        # unrelated reasons (up_thru grants, other pools) without
        # ending this interval — only an acting-set change (which bumps
        # interval_start and cancels this task anyway) invalidates us
        interval = self.interval_start
        granted_remote: list[int] = []
        try:
            self.state = "backfill_wait"
            await self.osd.local_reserver.request(self.cid)
            while True:
                if self.interval_start != interval or \
                        not self.is_primary():
                    return
                verdicts: dict[int, str] = {}
                for o in list(self.backfill_targets):
                    if self.osd.osd_is_up(o):
                        verdicts[o] = await self._reserve_remote(o)
                if not verdicts:
                    return        # every target down: the map decides
                if all(v == "grant" for v in verdicts.values()):
                    granted_remote = list(verdicts)
                    break
                for o, v in verdicts.items():
                    if v == "grant":          # don't sit on slots
                        asyncio.ensure_future(self._send_reserve_op(
                            o, RESERVE_RELEASE,
                            self._reserve_tids.get(o, 0)))
                self.state = "backfill_toofull" if "toofull" in \
                    verdicts.values() else "backfill_wait"
                await asyncio.sleep(float(self.osd.config.get(
                    "osd_backfill_retry_interval", 0.5)))
            self.state = "backfilling"
            RECOVERY_PERF.inc("backfills_started")
            for o in sorted(self.backfill_targets):
                if self.interval_start != interval or \
                        not self.is_primary():
                    return
                if self.osd.osd_is_up(o):
                    await self._backfill_one(o, interval)
            if self.interval_start != interval or \
                    not self.is_primary():
                return
            if not self.backfill_targets:
                RECOVERY_PERF.inc("backfills_completed")
            # the clean decision belongs to the ONE canonical path in
            # _recover — re-enter it after this task unwinds (the
            # finally below releases slots and clears the task pointer
            # first, so _maybe_start_backfill can restart failed
            # targets after a beat)
            self.state = "active"
            loop = asyncio.get_event_loop()
            loop.call_later(
                1.0 if self.backfill_targets else 0.0,
                lambda: asyncio.ensure_future(self._recover()))
        finally:
            # _cancel_backfill (interval change) already nulled the
            # task pointer and freed the slots — and a NEW driver may
            # have taken them by the time this cancelled frame unwinds.
            # Only the still-current task may release.
            if self._backfill_task is asyncio.current_task():
                self._backfill_task = None
                self._backfill_inflight = None
                self.osd.local_reserver.release(self.cid)
                for o in granted_remote:
                    asyncio.ensure_future(self._send_reserve_op(
                        o, RESERVE_RELEASE,
                        self._reserve_tids.get(o, 0)))

    async def _backfill_one(self, target: int, interval: int) -> bool:
        """Scan/push one target forward to MAX_OID. Every batch:
        compare the primary's sorted collection slice against the
        target's digest, push differing/missing objects (ACK-gated),
        remove target-side extras, and only THEN advance the persisted
        watermark — so a crash at any point resumes at a boundary
        where the invariant 'target holds every object <= watermark'
        still holds."""
        wm = self.backfill_targets.get(target, MIN_OID)
        if self.peer_last_backfill.get(target, MAX_OID) == MAX_OID:
            # fresh/discontinuous target: durably mark it incomplete
            # BEFORE the first scan — from here until FINISH its info
            # says 'backfill me', whatever crashes
            if not await self._backfill_ctl(target, BACKFILL_OP_RESET,
                                            MIN_OID):
                return False
            self.peer_last_backfill[target] = MIN_OID
            wm = MIN_OID
        elif wm > MIN_OID:
            self.backfill_stats["resumed_from"] = wm
        scan_max = int(self.osd.config.get("osd_backfill_scan_max", 64))
        store = self.osd.store
        while True:
            if self.interval_start != interval or \
                    not self.is_primary() or \
                    not self.osd.osd_is_up(target):
                return False
            try:
                names = sorted(
                    o for o in store.list_objects(self.cid)
                    if o != PGMETA and o > wm)
            except StoreError:
                return False
            batch = names[:scan_max]
            end = MAX_OID if len(names) <= scan_max else batch[-1]
            # block mutations over the WHOLE open range, not just the
            # snapshot: an object created in (wm, end] mid-batch would
            # be invisible to both this scan and the repop gate. Held
            # until the watermark advance lands so nothing slips into
            # the supposedly-covered region.
            self._backfill_inflight = (wm, end)
            try:
                reply = await self._scan_peer(target, wm, end)
                if reply is None:
                    return False
                theirs = dict(reply.objects)
                for oid in batch:
                    self.backfill_stats["scanned"] += 1
                    RECOVERY_PERF.inc("backfill_objects_scanned")
                    mine = self._version_blob(oid)
                    if mine and theirs.get(oid) == mine:
                        continue          # identical version: skip
                    if not await self._backfill_push_acked(oid, target):
                        return False
                    self.backfill_stats["pushed"] += 1
                    RECOVERY_PERF.inc("backfill_objects_pushed")
                for oid in sorted(set(theirs) - set(batch)):
                    # the target holds an object this primary doesn't:
                    # it was deleted past the target's horizon — the
                    # removal push (exists=False) reaps it
                    if oid == PGMETA or store.exists(self.cid, oid):
                        continue
                    if not await self._backfill_push_acked(oid, target):
                        return False
                    self.backfill_stats["removed"] += 1
                    RECOVERY_PERF.inc("backfill_objects_pushed")
                op = BACKFILL_OP_FINISH if end == MAX_OID \
                    else BACKFILL_OP_PROGRESS
                if not await self._backfill_ctl(target, op, end):
                    return False
                wm = end
                self.peer_last_backfill[target] = end
                if end != MAX_OID:
                    self.backfill_targets[target] = end
            finally:
                self._backfill_inflight = None
            if end == MAX_OID:
                self.backfill_targets.pop(target, None)
                log.dout(1, f"pg {self.pgid} backfill of osd.{target} "
                            f"complete")
                return True

    async def _backfill_self(self, src: int) -> bool:
        """Reverse backfill: THIS primary is incomplete (it was a
        backfill target when the map promoted it). Page the complete
        peer's digest and pull every object we lack or hold stale,
        advancing OUR persisted watermark; remove local objects the
        source doesn't list (deleted past our horizon). Runs inside
        peering, before any op can be served."""
        interval = self.interval_start
        scan_max = int(self.osd.config.get("osd_backfill_scan_max", 64))
        store = self.osd.store
        wm = self.last_backfill
        if wm > MIN_OID:
            self.backfill_stats["resumed_from"] = wm
        log.dout(1, f"pg {self.pgid} self-backfill from osd.{src} "
                    f"(watermark {wm!r})")
        while wm != MAX_OID:
            if self.interval_start != interval:
                return False
            reply = await self._scan_peer(src, wm, MAX_OID,
                                          limit=scan_max)
            if reply is None:
                return False
            theirs = dict(reply.objects)
            for oid in sorted(theirs):
                RECOVERY_PERF.inc("backfill_objects_scanned")
                if store.exists(self.cid, oid) and \
                        self._version_blob(oid) == theirs[oid]:
                    continue
                release = await self.osd.recovery_throttle.acquire(0)
                try:
                    await self._pull(src, oid)
                finally:
                    release()
                if self._version_blob(oid) != theirs[oid]:
                    # the pull timed out or delivered something other
                    # than the version the source listed: do NOT
                    # advance the watermark over a stale copy
                    return False
                RECOVERY_PERF.inc("backfill_objects_pushed")
            try:
                extras = [o for o in store.list_objects(self.cid)
                          if o != PGMETA and wm < o <= reply.up_to
                          and o not in theirs]
            except StoreError:
                extras = []
            for oid in extras:
                try:
                    store.queue_transaction(
                        Transaction().remove(self.cid, oid))
                    self._clone_idx = None
                except StoreError:
                    return False
            wm = reply.up_to
            self.last_backfill = wm
            # our log IS the authoritative log here (adopted in this
            # peering round), so its head is the point this watermark
            # is valid at
            self.backfill_at = self.pg_log.head
            try:
                store.queue_transaction(self._meta_txn(Transaction()))
            except StoreError as e:
                log.error(f"pg {self.pgid} self-backfill watermark "
                          f"persist failed: {e}")
                return False
        return True

    # target-side handlers --------------------------------------------------
    def handle_pg_scan(self, m: MOSDPGScan) -> None:
        out: dict[str, bytes] = {}
        up_to = m.end
        try:
            names = sorted(
                o for o in self.osd.store.list_objects(self.cid)
                if o != PGMETA and m.begin < o <= m.end)
        except StoreError:
            names = []
        if m.limit and len(names) > m.limit:
            names = names[:m.limit]
            up_to = names[-1]
        for oid in names:
            out[oid] = self._version_blob(oid)

        async def _reply():
            try:
                await m.conn.send_message(MOSDPGScanReply(
                    pgid=self.cid, tid=m.tid, from_osd=self.osd.whoami,
                    objects=out, up_to=up_to))
            except Exception:
                pass                  # requester's timeout covers it
        asyncio.ensure_future(_reply())

    def handle_scan_reply(self, m: MOSDPGScanReply) -> None:
        fut = self._backfill_waiters.get(m.tid)
        if fut and not fut.done():
            fut.set_result(m)

    def handle_backfill(self, m: MOSDPGBackfill) -> None:
        """Target half of the watermark protocol: persist BEFORE
        acking (an acked watermark must survive a crash). Messages
        from a superseded interval are dropped — a delayed/duplicated
        FINISH from a dead primary must not mark a freshly-RESET
        target complete with a stale log (the fault layer delays and
        duplicates messages by design)."""
        if m.epoch < self.interval_start:
            log.dout(1, f"pg {self.pgid} ignoring stale backfill op "
                        f"{m.op} from epoch {m.epoch} < interval "
                        f"{self.interval_start}")
            return
        result = 0
        if m.op == BACKFILL_OP_RESET:
            self.last_backfill = MIN_OID
            self.backfill_at = eversion(m.at_epoch, m.at_v)
        elif m.op == BACKFILL_OP_PROGRESS:
            self.last_backfill = m.last_backfill
            self.backfill_at = eversion(m.at_epoch, m.at_v)
        elif m.op == BACKFILL_OP_FINISH:
            if m.log:
                self.pg_log = PGLog.decode(m.log)
                self.last_user_version = max(self.last_user_version,
                                             self.pg_log.head.v)
            self.last_backfill = MAX_OID
            self.backfill_at = eversion()
        try:
            self.osd.store.queue_transaction(
                self._meta_txn(Transaction()))
        except StoreError as e:
            log.error(f"pg {self.pgid} backfill watermark persist "
                      f"failed: {e}")
            result = -5

        async def _reply():
            try:
                await m.conn.send_message(MOSDPGBackfillReply(
                    pgid=self.cid, tid=m.tid, op=m.op, result=result,
                    from_osd=self.osd.whoami))
            except Exception:
                pass
        asyncio.ensure_future(_reply())

    def handle_backfill_reply(self, m: MOSDPGBackfillReply) -> None:
        fut = self._backfill_waiters.get(m.tid)
        if fut and not fut.done():
            fut.set_result(m)

    def handle_backfill_reserve(self, m: MBackfillReserve) -> None:
        if m.op == RESERVE_REQUEST:
            if m.epoch < self.interval_start:
                return    # superseded primary: no reply, no slot leak
            if self.osd.backfill_toofull():
                verdict = RESERVE_TOOFULL
                RECOVERY_PERF.inc("reservations_toofull")
            elif self.osd.remote_reserver.try_request(self.cid):
                verdict = RESERVE_GRANT
                self._remote_grant_tid = m.tid
            else:
                verdict = RESERVE_REJECT

            async def _reply():
                try:
                    await m.conn.send_message(MBackfillReserve(
                        pgid=self.cid, epoch=self.epoch, tid=m.tid,
                        op=verdict, from_osd=self.osd.whoami))
                except Exception:
                    pass
            asyncio.ensure_future(_reply())
        elif m.op == RESERVE_RELEASE:
            if m.epoch < self.interval_start:
                return    # delayed release from a dead primary
            if m.tid and m.tid != self._remote_grant_tid:
                return    # duplicate of an ALREADY-honored release:
                #           the slot has been re-granted under a new
                #           tid in the meantime — don't free that one
            self._remote_grant_tid = 0
            self.osd.remote_reserver.release(self.cid)
        else:                             # GRANT / REJECT / TOOFULL
            fut = self._backfill_waiters.get(m.tid)
            if fut and not fut.done():
                fut.set_result(m)

    def _should_send_repop(self, peer: int, oid: str) -> bool:
        """Ongoing-write gate for backfill targets (ref: PrimaryLogPG
        should_send_op): a target holds exactly the objects <= its
        watermark, so writes at-or-below it MUST replicate (or the
        already-copied object diverges silently) and writes above it
        MUST NOT (the txn would materialize a partial object the scan
        then wrongly version-matches; the scan will copy it whole)."""
        lb = self.backfill_targets.get(peer)
        return lb is None or oid <= lb

    def _backfill_blocked(self, oid: str, mutating: bool) -> bool:
        """Degraded-object gate (ref: wait_for_unreadable_object /
        wait_for_degraded_object): ops park with -EAGAIN while (a)
        this primary's own copy is above its own watermark — it may
        not hold the object at all — or (b) the object sits in the
        batch a backfill scan is comparing RIGHT NOW (mutations only:
        a write between the version read and the watermark advance
        would be invisible to both the scan and the repop gate)."""
        if self.last_backfill != MAX_OID and oid > self.last_backfill:
            return True
        if not mutating or self._backfill_inflight is None:
            return False
        lo, hi = self._backfill_inflight
        return lo < oid <= hi

    # -- stats -------------------------------------------------------------
    def stats(self) -> dict:
        objs = [o for o in self.osd.store.list_objects(self.cid)
                if o != PGMETA] if self.cid in \
            self.osd.store.list_collections() else []
        nbytes = 0
        for o in objs:
            try:
                nbytes += self.osd.store.stat(self.cid, o)
            except StoreError:
                pass
        state = self.state
        if self.is_primary():
            live = len(self.live_acting())
            if live < self.pool.size and self.role_active():
                # also during backfill states: a SECOND replica down
                # mid-backfill is genuine under-replication monitoring
                # must see, not business-as-usual backfill
                state = f"{self.state}+undersized+degraded"
        out = {"state": state, "num_objects": len(objs),
               "num_bytes": nbytes,
               "acting": self.acting, "up": self.up,
               "last_update": str(self.pg_log.head),
               "scrub_errors": self.scrub_errors}
        if self.client_ops:
            out["num_ops"] = sum(self.client_ops.values())
            out["client_ops"] = dict(self.client_ops)
        if self.is_merge_source():
            # merge progress rides MPGStats into pg dump / status
            out["merge"] = {"pending": self.pool.pg_num_pending,
                            "target": self.pool.merge_target(
                                self.pgid.seed),
                            "ready": int(self.merge_ready())}
        if self.backfill_targets or \
                self.last_backfill != MAX_OID or \
                self.backfill_stats["pushed"] or \
                self.backfill_stats["scanned"]:
            # backfill progress rides MPGStats into `ceph status` /
            # pg dump (ref: pg_stat_t's backfill fields)
            out["backfill"] = {
                "targets": {str(o): wm for o, wm in
                            sorted(self.backfill_targets.items())},
                "last_backfill": self.last_backfill,
                **self.backfill_stats}
        return out
