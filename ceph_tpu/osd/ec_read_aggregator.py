"""OSD-side EC decode aggregator: cross-op degraded-read/repair
coalescing — the read-side twin of osd/ec_aggregator.py.

A degraded read, a recovery shard rebuild and a backfill push all end
in the same place: ``decode_batch`` over a gathered stripe range. Each
used to launch its own kernel from ``ECPG._gather`` — during repair
churn (an OSD dies, every PG it touched starts rebuilding while
clients keep reading) the decode path is dispatch-bound exactly the
way the write path was before round 13. This aggregator coalesces
concurrent decodes from ALL the PGs on one OSD into a single padded
batched launch per flush window.

Contract (mirrors the encode aggregator, pinned in
tests/test_ec_read_agg.py):

- **bit-exact**: decode kernels are stripe-row-independent, so the
  concatenated batch's rows equal the per-op results lane for lane;
  the per-op path survives as the measured baseline behind
  ``osd_ec_read_agg=off`` (read LIVE);
- **latency-bounded**: a batch flushes when
  ``osd_ec_read_agg_window_us`` expires, when
  ``osd_ec_read_agg_max_stripes`` accumulate, or when the queue goes
  IDLE — a lone degraded read is never held past the window;
- **padded launches**: pow2 zero-padding bounds the jit cache to
  O(log max_batch) shapes per (erasure pattern, chunk size);
- **QoS-honest**: repair decodes (rebuild/backfill — not client
  degraded reads, which were already cost-tagged at admission) charge
  a recovery-class grant at the same bytes/osd_qos_cost_per_io_bytes
  divisor client writes pay, so repair churn can't starve cold
  tenants;
- **degrade ladder** (round 16 discipline): a failed batch flush
  disaggregates per-op, each op gets ``osd_ec_fallback_retries``
  device attempts, then the bit-exact host reference decoder; repeated
  device failures quarantine the device decode on exponential backoff
  (``osd_ec_fallback_quarantine_base/_max``) during which ops are
  served by the reference directly, probing the device again after the
  deadline.

Groups are keyed by (profile, avail, want, C): the decode kernel is a
pure function of the erasure pattern, so only ops reconstructing the
same missing set from the same available set share a launch — exactly
the granularity of ``ErasureCodeJax._decode_kernel``'s cache.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np

from ceph_tpu.utils.logging import get_logger
from ceph_tpu.utils.perf_counters import PerfCountersBuilder

log = get_logger("osd")


def _read_agg_perf():
    """Per-OSD counter family (register=False: several in-process OSDs
    each own one; they reach prometheus through the daemon->mgr report
    path as ``ceph_osd_ec_read_agg_*`` rows)."""
    return (
        PerfCountersBuilder("osd_ec_read_agg")
        .add_u64_counter("batches", "coalesced decode launches")
        .add_u64_counter("stripes", "stripes decoded through batches")
        .add_u64_counter("ops", "decode requests served")
        .add_u64_counter("bypass",
                         "decodes served per-op (osd_ec_read_agg=off)")
        .add_u64_counter("flush_window",
                         "flushes triggered by the window expiring")
        .add_u64_counter("flush_full",
                         "flushes triggered by "
                         "osd_ec_read_agg_max_stripes")
        .add_u64_counter("flush_idle",
                         "flushes triggered by queue idleness")
        .add_time_avg("batch_occupancy",
                      "stripes per flushed batch (long-run avg)")
        .add_time_avg("batch_wait",
                      "seconds an op waited for its flush (long-run "
                      "avg)")
        .add_u64_counter("flush_failures",
                         "batched flushes whose device decode raised "
                         "(the batch disaggregated per-op)")
        .add_u64_counter("per_op_retries",
                         "bounded per-op device retries after a "
                         "failed batch (osd_ec_fallback_retries)")
        .add_u64_counter("fallback_ops",
                         "ops served by the bit-exact reference "
                         "(numpy) decoder after device retries "
                         "exhausted")
        .add_u64_counter("quarantined_ops",
                         "ops served by the reference decoder while "
                         "the device decode sat in failure-backoff "
                         "quarantine")
        .add_u64_counter("qos_grants",
                         "repair decodes that paid a recovery-class "
                         "size-scaled QoS grant before queueing")
        .create_perf_counters(register=False))


class _Entry:
    __slots__ = ("chunks", "fut", "t0")

    def __init__(self, chunks, fut, t0):
        self.chunks = chunks
        self.fut = fut
        self.t0 = t0


class _Group:
    """One in-flight coalescing batch; staleness is decided by
    identity (``self._groups.get(key) is g``), never by counters."""

    __slots__ = ("ec", "want", "avail", "entries", "stripes", "task")

    def __init__(self, ec, want, avail):
        self.ec = ec
        self.want = want
        self.avail = avail
        self.entries: list[_Entry] = []
        self.stripes = 0
        self.task: asyncio.Task | None = None


class ECReadAggregator:
    """One per OSD daemon; every ECPG decode routes through it."""

    def __init__(self, config: dict | None = None, scheduler=None):
        self.config = config if config is not None else {}
        self.scheduler = scheduler
        self.perf = _read_agg_perf()
        self._groups: dict[tuple, _Group] = {}
        self.stopped = False
        # device-decode quarantine (round 16 hooks): after per-op
        # device retries exhaust, decodes serve the host reference
        # until the backoff deadline passes, then the device is probed
        # again by simply running the next flush on it
        self._dev_q_until = 0.0
        self._dev_failures = 0

    # -- knobs (read LIVE) -------------------------------------------------
    def enabled(self) -> bool:
        return bool(self.config.get("osd_ec_read_agg", True))

    def window_s(self) -> float:
        return float(
            self.config.get("osd_ec_read_agg_window_us", 500)) / 1e6

    def max_stripes(self) -> int:
        return int(self.config.get("osd_ec_read_agg_max_stripes", 4096))

    def _retries(self) -> int:
        return int(self.config.get("osd_ec_fallback_retries", 1))

    # -- submit ------------------------------------------------------------
    async def decode(self, ec, want, avail, chunks,
                     charge_bytes: int = 0):
        """Decode a (B, len(avail), C) uint8 batch into the ``want``
        chunk rows; returns np (B, len(want), C).

        ``charge_bytes`` > 0 marks a REPAIR decode (rebuild/backfill):
        a recovery-class QoS grant scaled by
        bytes/osd_qos_cost_per_io_bytes is paid before the op queues,
        the same divisor client writes pay at admission. Client
        degraded reads pass 0 — their cost tag was already charged by
        the daemon's admission path."""
        chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
        want = tuple(want)
        avail = tuple(avail)
        if charge_bytes > 0 and self.scheduler is not None \
                and not self.stopped:
            from ceph_tpu.osd.scheduler import size_scaled_cost
            await self.scheduler.grant(
                "recovery",
                cost=size_scaled_cost(self.config, charge_bytes))
            self.perf.inc("qos_grants")
        if not self.enabled() or self.stopped:
            # the measured per-op baseline: one UNPADDED launch per
            # op, exactly the pre-aggregator path — padding here would
            # flatter the aggregator's speedup
            self.perf.inc("bypass")
            try:
                return self._run(ec, want, avail, chunks, pad=False)
            except Exception as e:
                return self._degrade_one(ec, want, avail, chunks, e)
        key = (str(ec.profile), avail, want, int(chunks.shape[2]))
        g = self._groups.get(key)
        if g is None:
            g = self._groups[key] = _Group(ec, want, avail)
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        g.entries.append(_Entry(chunks, fut, loop.time()))
        g.stripes += chunks.shape[0]
        if g.stripes >= self.max_stripes():
            self._flush(key, g, "full")
        elif g.task is None:
            g.task = asyncio.ensure_future(self._flush_later(key, g))
        return await fut

    async def _flush_later(self, key: tuple, g: _Group) -> None:
        """Window/idle flusher for one group generation. Yields to the
        loop once so a concurrent burst of submitters lands, then
        soaks window slices; two consecutive looks with no new arrival
        mean the queue is idle — flush early instead of pinning a lone
        op to the full window."""
        loop = asyncio.get_event_loop()
        window = self.window_s()
        deadline = loop.time() + window
        seen = -1
        try:
            while True:
                await asyncio.sleep(0)
                if self._groups.get(key) is not g:
                    return                   # full-trigger beat us
                now = loop.time()
                if now >= deadline:
                    self._flush(key, g, "window")
                    return
                if len(g.entries) == seen:
                    self._flush(key, g, "idle")
                    return
                seen = len(g.entries)
                await asyncio.sleep(
                    min(deadline - now, max(window / 8, 1e-4)))
        except asyncio.CancelledError:
            if self._groups.get(key) is g:
                self._flush(key, g, "window")
            raise

    # -- flush -------------------------------------------------------------
    def _flush(self, key: tuple, g: _Group, trigger: str) -> None:
        if self._groups.get(key) is g:
            del self._groups[key]
        if g.task is not None and g.task is not asyncio.current_task():
            g.task.cancel()
            g.task = None
        entries = g.entries
        if not entries:
            return
        datas = [e.chunks for e in entries]
        big = datas[0] if len(datas) == 1 else \
            np.concatenate(datas, axis=0)
        loop = asyncio.get_event_loop()
        try:
            out = self._run(g.ec, g.want, g.avail, big)
        except Exception as e:
            self._degrade(g, entries, e)
            return
        off = 0
        now = loop.time()
        for ent in entries:
            b = ent.chunks.shape[0]
            if not ent.fut.done():
                ent.fut.set_result(out[off:off + b])
            self.perf.avg_add("batch_wait", now - ent.t0)
            off += b
        self.perf.inc("batches")
        self.perf.inc("stripes", int(big.shape[0]))
        self.perf.inc("ops", len(entries))
        self.perf.inc(f"flush_{trigger}")
        self.perf.avg_add("batch_occupancy", float(big.shape[0]))
        log.dout(10, f"ec_read_agg flush {trigger}: {len(entries)} "
                     f"ops, {big.shape[0]} stripes")

    # -- degrade ladder ----------------------------------------------------
    def _degrade(self, g: _Group, entries, err: Exception) -> None:
        """Failed batch flush: DISAGGREGATE — retry each member as its
        own device decode, then the bit-exact reference decoder; only
        the op whose chunks still fail under the reference sees the
        exception. One poisoned stripe must not fail its batchmates,
        and a degraded READ must never error because the accelerator
        did — the data is reconstructible on the host by definition."""
        self.perf.inc("flush_failures")
        log.dout(0, f"ec_read_agg batch flush failed "
                    f"({type(err).__name__}: {str(err)[:200]}) — "
                    f"disaggregating {len(entries)} ops")
        loop = asyncio.get_event_loop()
        for ent in entries:
            try:
                res = self._run(g.ec, g.want, g.avail, ent.chunks,
                                pad=False)
            except Exception as e:
                try:
                    res = self._degrade_one(g.ec, g.want, g.avail,
                                            ent.chunks, e)
                except Exception as e2:
                    if not ent.fut.done():
                        ent.fut.set_exception(e2)
                    self.perf.avg_add("batch_wait",
                                      loop.time() - ent.t0)
                    continue
            if not ent.fut.done():
                ent.fut.set_result(res)
            self.perf.avg_add("batch_wait", loop.time() - ent.t0)

    def _degrade_one(self, ec, want, avail, chunks, err: Exception):
        """Per-op tail of the ladder: osd_ec_fallback_retries more
        device attempts, then the reference decoder (host numpy,
        bit-exact by construction). Raises the last device error only
        when the reference itself fails. Retries are skipped while the
        device decode is quarantined."""
        exc = err
        if time.monotonic() >= self._dev_q_until:
            for _ in range(max(0, self._retries())):
                self.perf.inc("per_op_retries")
                try:
                    out = self._run(ec, want, avail, chunks, pad=False)
                except Exception as e:
                    exc = e
                else:
                    self._dev_failures = 0
                    return out
            self._dev_fail(exc)
        try:
            out = np.asarray(
                ec.decode_batch_reference(want, avail, chunks),
                dtype=np.uint8)
        except Exception:
            raise exc
        self.perf.inc("fallback_ops")
        log.dout(1, f"ec_read_agg op served by the reference decoder "
                    f"({chunks.shape[0]} stripes) after device "
                    f"retries exhausted")
        return out

    def _dev_fail(self, e: Exception) -> None:
        self._dev_failures += 1
        base = float(self.config.get(
            "osd_ec_fallback_quarantine_base", 1.0))
        cap = float(self.config.get(
            "osd_ec_fallback_quarantine_max", 30.0))
        backoff = min(base * (2 ** (self._dev_failures - 1)), cap)
        self._dev_q_until = time.monotonic() + backoff
        log.dout(0, f"device decode failed "
                    f"({type(e).__name__}: {str(e)[:200]}) — serving "
                    f"the reference decoder for {backoff:.2f}s")

    @staticmethod
    def _pad(b: int) -> int:
        """Next power of two: bounds the jit cache to O(log) shapes."""
        return 1 << (int(b) - 1).bit_length() if b > 1 else 1

    def _run(self, ec, want, avail, chunks, pad: bool = True):
        """One device launch over a (possibly padded) batch; while the
        device decode is quarantined, serves the reference decoder
        instead (bit-exact, so callers can't tell beyond latency)."""
        if time.monotonic() < self._dev_q_until:
            self.perf.inc("quarantined_ops")
            return np.asarray(
                ec.decode_batch_reference(want, avail, chunks),
                dtype=np.uint8)
        b = chunks.shape[0]
        padded = self._pad(b) if pad else b
        if padded != b:
            z = np.zeros((padded - b,) + chunks.shape[1:],
                         dtype=np.uint8)
            chunks = np.concatenate([chunks, z], axis=0)
        out = np.asarray(ec.decode_batch(want, avail, chunks))[:b]
        self._dev_failures = 0
        return out

    # -- lifecycle / observability ----------------------------------------
    def drain(self) -> int:
        """Daemon stop: flush nothing more — cancel every waiter (their
        PG op workers are being cancelled too) and kill flush timers.
        Returns the number of ops dropped."""
        self.stopped = True
        n = 0
        for key, g in list(self._groups.items()):
            if g.task is not None:
                g.task.cancel()
                g.task = None
            for ent in g.entries:
                n += 1
                if not ent.fut.done():
                    ent.fut.cancel()
            self._groups.pop(key, None)
        return n

    def dump(self) -> dict:
        d = self.perf.dump()
        occ = d.get("batch_occupancy", {})
        wait = d.get("batch_wait", {})
        return {
            "enabled": self.enabled(),
            "window_us": float(
                self.config.get("osd_ec_read_agg_window_us", 500)),
            "max_stripes": self.max_stripes(),
            "pending_groups": len(self._groups),
            "pending_ops": sum(len(g.entries)
                               for g in self._groups.values()),
            "batches": d.get("batches", 0),
            "stripes": d.get("stripes", 0),
            "ops": d.get("ops", 0),
            "bypass": d.get("bypass", 0),
            "fallback_ops": d.get("fallback_ops", 0),
            "quarantined_ops": d.get("quarantined_ops", 0),
            "qos_grants": d.get("qos_grants", 0),
            "flushes": {t: d.get(f"flush_{t}", 0)
                        for t in ("window", "full", "idle")},
            "avg_occupancy": (occ.get("sum", 0.0) /
                              occ.get("avgcount", 1)
                              if occ.get("avgcount") else 0.0),
            "avg_batch_wait_s": (wait.get("sum", 0.0) /
                                 wait.get("avgcount", 1)
                                 if wait.get("avgcount") else 0.0),
        }
