"""Op QoS scheduler: the dmClock-analog admission queue.

ref: src/osd/scheduler/mClockScheduler.{h,cc} + src/dmclock — the
reference's answer to "one hot tenant must not starve everyone else".
Every queue (a client/pool pair, the recovery class, the scrub class)
carries a QoS profile (reservation IOPS, weight, limit IOPS) and every
submitted op is stamped with three tags, exactly dmClock's algebra:

    R  = max(now, prev_R + cost / reservation)     (ρ tag)
    P  = max(now, prev_P + cost / weight)          (δ/proportion tag)
    L  = max(now, prev_L + cost / limit)

Dequeue is two-phase:

1. **reservation phase** — among queue heads whose R tag has come due
   (R <= now), serve the smallest R. Reservations are hard floors:
   they are paid first, whatever the weights say.
2. **weight phase** — otherwise, among heads whose L tag has come due
   (limit not exceeded), serve the smallest P tag. Weights split the
   *surplus* capacity proportionally.

A queue whose limit tag is in the future is ineligible until it comes
due, so `limit` is a hard ceiling even for an otherwise-idle cluster.
``max(now, ...)`` resets an idle queue's tags, so sleeping tenants
don't bank credit (the standard dmClock idle rule).

Three op classes ride the same instance (ref: mClock's op classes):

- ``client`` — one queue per (entity, pool); profile resolution:
  per-entity ``ceph osd client-profile`` > pool ``qos_*`` > the
  ``osd_qos_default_*`` knobs;
- ``recovery`` — PR 2's RecoveryThrottle folded in: recovery pushes
  take a grant from THIS queue (``osd_qos_recovery_*``) instead of a
  side token bucket, so client-vs-recovery arbitration happens at one
  decision point (no starvation in either direction: recovery has a
  reservation, clients have theirs);
- ``scrub`` — background best-effort (weight-only, limited).

Scaling: queue heads live in two lazy heaps (by R and by P/L), so a
dequeue is O(log n_queues) — a 10k-session harness must not turn every
admission into an O(tenants) scan (the mClockScheduler uses the same
shape: per-class sub-queues + an eligibility heap).

``mode() == "fifo"`` (the ``osd_op_queue`` knob, read LIVE) disables
the tag algebra: one FIFO queue, exactly the pre-scheduler admission
loop — the baseline the QoS bench/tests compare against.
"""

from __future__ import annotations

import asyncio
import heapq
from collections import deque
from dataclasses import dataclass

from ceph_tpu.utils.logging import get_logger
from ceph_tpu.utils.perf_counters import PerfCountersBuilder

log = get_logger("osd")

# process-wide counters (-> prometheus ceph_osd_qos_*, like osd_recovery's)
QOS_PERF = (
    PerfCountersBuilder("osd_qos")
    .add_u64_counter("dequeued_client", "client ops granted")
    .add_u64_counter("dequeued_recovery", "recovery grants issued")
    .add_u64_counter("dequeued_scrub", "scrub grants issued")
    .add_u64_counter("reservation_grants",
                     "grants issued in the reservation phase")
    .add_u64_counter("weight_grants",
                     "grants issued in the weight phase")
    .add_u64_counter("limit_waits",
                     "dequeue passes that found nothing due yet "
                     "(limit- or reservation-deferred heads) and "
                     "slept until the next tag horizon")
    .add_u64_counter("fifo_grants",
                     "grants issued with the scheduler in fifo mode")
    .create_perf_counters())

INF = float("inf")


def size_scaled_cost(config: dict, nbytes: int) -> float:
    """dmClock size-scaled cost (ROADMAP #3a; ref: the mclock
    cost-per-byte options): an op advances its queue's virtual time
    by ``max(1, bytes / osd_qos_cost_per_io_bytes)`` tag units
    instead of a flat 1. ONE definition — client admission
    (daemon._op_cost) and the recovery throttle charge through it,
    so the two paths can never silently diverge."""
    per_io = int(config.get("osd_qos_cost_per_io_bytes", 65536))
    return max(1.0, nbytes / max(per_io, 1))


@dataclass(frozen=True)
class QoSProfile:
    """One queue's dmClock parameters. ``reservation``/``limit`` are
    ops/s (0 = none/unlimited); ``weight`` is the proportional share
    (0 falls back to the default weight)."""

    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0

    def effective_weight(self) -> float:
        return self.weight if self.weight > 0 else 1.0


class _Queue:
    __slots__ = ("key", "op_class", "profile", "items",
                 "r_prev", "p_prev", "l_prev")

    def __init__(self, key, op_class: str, profile: QoSProfile):
        self.key = key
        self.op_class = op_class
        self.profile = profile
        # each entry: (r_tag, p_tag, l_tag, item, cost)
        self.items: deque = deque()

        self.r_prev = 0.0
        self.p_prev = 0.0
        self.l_prev = 0.0


class OpScheduler:
    """The admission scheduler. One per OSD daemon.

    ``submit(item, ...)`` stamps and enqueues; ``dequeue()`` awaits
    the next grant honoring reservation -> weight -> limit. The knobs
    (``osd_op_queue``, ``osd_qos_default_*``) are read LIVE from the
    daemon's config dict so a runtime flip to fifo applies to the next
    dequeue decision. ``now_fn`` is injectable for deterministic
    virtual-clock unit tests."""

    def __init__(self, config: dict | None = None, now_fn=None):
        self.config = config if config is not None else {}
        self._now = now_fn or (
            lambda: asyncio.get_event_loop().time())
        self.queues: dict[object, _Queue] = {}
        # lazy eligibility heaps over queue HEADS; entries go stale
        # when a head is dequeued — validated on pop. _rheap is
        # R-ordered (reservation phase); _pheap is P-ordered and holds
        # only heads whose LIMIT tag was already due when pushed;
        # limit-deferred heads park in the lim-ordered _lheap and
        # migrate to _pheap as they come due — so a dequeue touches
        # O(log tenants) entries, not every due head.
        self._rheap: list = []
        self._pheap: list = []
        self._lheap: list = []
        self._seq = 0
        self._fifo: deque = deque()
        self._event = asyncio.Event()
        self.queued = 0
        self.dequeued_total = 0
        # set by drain(): straggler grant() calls (a late recovery
        # retry firing after daemon stop) resolve immediately instead
        # of parking on a queue nothing drains anymore
        self.stopped = False

    # -- knobs (live) -----------------------------------------------------
    def _get(self, name: str, default):
        v = self.config.get(name)
        return default if v is None else v

    def mode(self) -> str:
        return str(self._get("osd_op_queue", "mclock"))

    def default_profile(self) -> QoSProfile:
        return QoSProfile(
            reservation=float(self._get("osd_qos_default_reservation",
                                        0.0)),
            weight=float(self._get("osd_qos_default_weight", 1.0)),
            limit=float(self._get("osd_qos_default_limit", 0.0)))

    def class_profile(self, op_class: str) -> QoSProfile:
        if op_class == "recovery":
            return QoSProfile(
                reservation=float(self._get(
                    "osd_qos_recovery_reservation", 10.0)),
                weight=float(self._get("osd_qos_recovery_weight", 1.0)),
                limit=float(self._get("osd_qos_recovery_limit", 0.0)))
        if op_class == "scrub":
            return QoSProfile(
                reservation=0.0,
                weight=float(self._get("osd_qos_scrub_weight", 0.5)),
                limit=float(self._get("osd_qos_scrub_limit", 10.0)))
        return self.default_profile()

    # -- submit -----------------------------------------------------------
    def submit(self, item, key=("client", "", 0),
               op_class: str = "client",
               profile: QoSProfile | None = None,
               cost: float = 1.0) -> None:
        """Stamp ``item`` with dmClock tags and enqueue it under
        ``key``. ``cost`` scales the tag increments (an op that is N
        times as expensive advances the queue's virtual time N times
        as far)."""
        if self.mode() == "fifo":
            self._fifo.append(item)
            self.queued += 1
            self._event.set()
            return
        q = self.queues.get(key)
        prof = profile or self.class_profile(op_class)
        if q is None:
            q = self.queues[key] = _Queue(key, op_class, prof)
        else:
            q.profile = prof          # live re-resolution (knob/CLI edits)
        now = self._now()
        cost = max(float(cost), 1e-9)
        r = max(now, q.r_prev + cost / prof.reservation) \
            if prof.reservation > 0 else INF
        p = max(now, q.p_prev + cost / prof.effective_weight())
        lim = max(now, q.l_prev + cost / prof.limit) \
            if prof.limit > 0 else now
        q.r_prev = r if r != INF else q.r_prev
        q.p_prev = p
        q.l_prev = lim
        q.items.append((r, p, lim, item, cost))
        if len(q.items) == 1:
            self._push_head(q)
        self.queued += 1
        self._event.set()

    def _push_head(self, q: _Queue, now: float | None = None) -> None:
        r, p, lim, _item, _c = q.items[0]
        self._seq += 1
        if r != INF:
            # reservation eligibility = max(R, L): the limit is a hard
            # ceiling over BOTH phases — a profile with reservation >
            # limit must be served at the limit rate, not the
            # reservation rate
            heapq.heappush(self._rheap, (max(r, lim), self._seq,
                                         q.key))
        if now is None:
            now = self._now()
        if lim <= now:
            heapq.heappush(self._pheap, (p, lim, self._seq, q.key))
        else:
            heapq.heappush(self._lheap, (lim, p, self._seq, q.key))

    # -- dequeue ----------------------------------------------------------
    def _head(self, key):
        q = self.queues.get(key)
        if q is None or not q.items:
            return None
        return q

    def try_dequeue(self, now: float | None = None):
        """One synchronous scheduling decision. Returns
        ``(item, op_class)`` or ``(None, wake_at)`` where ``wake_at``
        is the earliest time any head becomes eligible (None = queue
        empty). Split from the async loop for virtual-clock tests."""
        if self.mode() == "fifo":
            if self._fifo:
                self.queued -= 1
                self.dequeued_total += 1
                QOS_PERF.inc("fifo_grants")
                return self._fifo.popleft(), "client"
            # drain anything stamped before a live flip to fifo —
            # keeping each drained queue's heap entry fresh, so a flip
            # BACK to mclock mid-backlog leaves every head reachable
            for q in self.queues.values():
                if q.items:
                    _r, _p, _l, item, _c = q.items.popleft()
                    if q.items:
                        self._push_head(q)
                    self.queued -= 1
                    self.dequeued_total += 1
                    QOS_PERF.inc("fifo_grants")
                    return item, q.op_class
            return None, None
        if now is None:
            now = self._now()
        if self._fifo:
            # backlog stamped while the knob said fifo: serve it first
            # (arrival order) — a flip back to mclock must not strand
            # un-tagged ops in a queue the tag phases never read
            self.queued -= 1
            self.dequeued_total += 1
            QOS_PERF.inc("fifo_grants")
            return self._fifo.popleft(), "client"
        # phase 1: reservation — smallest due max(R, L) tag
        while self._rheap:
            rtag, _seq, key = self._rheap[0]
            if rtag > now:
                break
            heapq.heappop(self._rheap)
            q = self._head(key)
            if q is None or \
                    max(q.items[0][0], q.items[0][2]) != rtag:
                continue                      # stale entry
            return self._pop(q, "reservation", now)
        # migrate limit-deferred heads whose L tag came due into the
        # P-ordered ready heap (amortized: each head moves once)
        while self._lheap and self._lheap[0][0] <= now:
            lim, p, seq, key = heapq.heappop(self._lheap)
            q = self._head(key)
            if q is None or q.items[0][2] != lim or q.items[0][1] != p:
                continue                      # stale entry
            heapq.heappush(self._pheap, (p, lim, seq, key))
        # phase 2: weight — smallest P among limit-due heads
        while self._pheap:
            p, lim, seq, key = heapq.heappop(self._pheap)
            q = self._head(key)
            if q is None or q.items[0][2] != lim or q.items[0][1] != p:
                continue                      # stale entry
            return self._pop(q, "weight", now)
        # nothing eligible: compute the wake-up horizon
        wake = None
        if self._rheap:
            wake = self._rheap[0][0]
        if self._lheap:
            lim = self._lheap[0][0]
            wake = lim if wake is None else min(wake, lim)
        if wake is not None:
            QOS_PERF.inc("limit_waits")
        return None, wake

    def _pop(self, q: _Queue, phase: str, now: float | None = None):
        _r, _p, _l, item, _c = q.items.popleft()
        if q.items:
            self._push_head(q, now)
        elif not q.items and q.profile.reservation <= 0 and \
                q.profile.limit <= 0 and len(self.queues) > 4096:
            # bound idle default-profile queue state (10k+ sessions):
            # tags reset on next submit anyway via max(now, ...)
            self.queues.pop(q.key, None)
        self.queued -= 1
        self.dequeued_total += 1
        QOS_PERF.inc("reservation_grants" if phase == "reservation"
                     else "weight_grants")
        QOS_PERF.inc(f"dequeued_{q.op_class}"
                     if q.op_class in ("client", "recovery", "scrub")
                     else "dequeued_client")
        return item, q.op_class

    async def dequeue(self):
        """Await the next grant: ``(item, op_class)``."""
        while True:
            item, extra = self.try_dequeue()
            if item is not None:
                return item, extra
            self._event.clear()
            if extra is None:                 # empty: wait for submit
                await self._event.wait()
                continue
            delay = max(extra - self._now(), 0.0)
            if delay <= 0:
                continue
            try:                              # sleep until eligibility
                await asyncio.wait_for(self._event.wait(),
                                       timeout=min(delay, 1.0))
            except asyncio.TimeoutError:
                pass

    def pop_grant(self):
        """Pop one due recovery/scrub grant WITHOUT running the client
        phases — the admission loop calls this while it is parked on
        the client throttle for a dequeued op, so a saturated client
        cap can never stall recovery/scrub (grants don't consume
        throttle slots; the head-of-line inversion the folded-in
        design must not reintroduce). Honors the class's limit tag."""
        now = self._now()
        for key in (("recovery",), ("scrub",)):
            q = self.queues.get(key)
            if q is not None and q.items and q.items[0][2] <= now:
                return self._pop(q, "weight", now)[0]
        return None

    # -- grants (recovery / scrub ride the same decision point) -----------
    async def grant(self, op_class: str, key=None,
                    cost: float = 1.0) -> None:
        """Submit a grant token under ``op_class`` and wait until the
        admission loop dequeues it — how non-message work (recovery
        pushes, scrub rounds) takes its turn in the same tag algebra
        client ops use. In fifo mode (or with no admission loop
        draining us) the grant is immediate, matching the
        pre-scheduler behavior."""
        if self.mode() == "fifo" or self.stopped:
            return
        fut = asyncio.get_event_loop().create_future()
        self.submit(_Grant(fut), key=key or (op_class,),
                    op_class=op_class, cost=cost)
        await fut

    def drain(self, release=None) -> int:
        """Drop every queued item (daemon stop): returns the count.
        ``release(item)`` runs per dropped item so admission-throttle
        costs (and grant futures) don't leak with the queue."""
        self.stopped = True
        n = 0
        def _one(item):
            nonlocal n
            n += 1
            if isinstance(item, _Grant):
                if not item.fut.done():
                    item.fut.cancel()
            elif release is not None:
                release(item)
        while self._fifo:
            _one(self._fifo.popleft())
        for q in self.queues.values():
            while q.items:
                _one(q.items.popleft()[3])
        self.queued = 0
        self._rheap.clear()
        self._pheap.clear()
        self._lheap.clear()
        return n

    def backlog(self, key) -> int:
        """Queued depth of ONE queue (fifo mode: the global queue) —
        the per-tenant saturation check backing MOSDBackoff, O(1) so
        admission stays scan-free at 10k tenants."""
        if self.mode() == "fifo":
            return len(self._fifo)
        q = self.queues.get(key)
        return len(q.items) if q is not None else 0

    def dump(self) -> dict:
        return {
            "mode": self.mode(),
            "queued": self.queued,
            "dequeued_total": self.dequeued_total,
            "queues": {
                "/".join(str(x) for x in
                         (k if isinstance(k, tuple) else (k,))): {
                    "class": q.op_class,
                    "depth": len(q.items),
                    "reservation": q.profile.reservation,
                    "weight": q.profile.weight,
                    "limit": q.profile.limit,
                } for k, q in self.queues.items() if q.items},
        }


class _Grant:
    """A non-message scheduler token (recovery/scrub grant)."""

    __slots__ = ("fut",)

    def __init__(self, fut: asyncio.Future):
        self.fut = fut


class SchedulerThrottle:
    """PR 2's RecoveryThrottle folded into the scheduler (the
    "scheduler class instead of a side throttle" move): ``acquire``
    first takes a grant from the scheduler's ``recovery`` queue — so
    recovery paces against client ops in one tag algebra — then the
    concurrency slot (``osd_recovery_max_active``) and, when a byte
    rate is configured, token-bucket budget. The acquire/release API
    (and ``dump``) is RecoveryThrottle's, so every PG call site is
    unchanged; with ``scheduler=None`` (or fifo mode) it degrades to
    exactly the old side throttle."""

    def __init__(self, scheduler: OpScheduler | None,
                 max_active: int = 8, bytes_per_s: int = 0,
                 config: dict | None = None):
        from ceph_tpu.osd.recovery import RecoveryThrottle
        self.scheduler = scheduler
        # with a config dict, the knobs are read LIVE per acquire
        # (round 17: the tuner's recovery governor commits `config
        # set` and every in-flight backfill follows on its next push)
        self.config = config
        self._legacy = RecoveryThrottle(max_active=max_active,
                                        bytes_per_s=bytes_per_s)

    def _sync_knobs(self) -> None:
        if self.config is None:
            return
        self._legacy.set_limits(
            max_active=self.config.get("osd_recovery_max_active", 8),
            bytes_per_s=self.config.get("osd_recovery_max_bytes", 0))

    async def acquire(self, nbytes: int = 0):
        self._sync_knobs()
        if self.scheduler is not None:
            # size-scaled cost (ROADMAP #3a), same divisor the client
            # admission path charges: a 4 MiB recovery push pays its
            # bytes against the recovery reservation instead of
            # looking as cheap as a metadata-only push
            await self.scheduler.grant(
                "recovery",
                cost=size_scaled_cost(self.scheduler.config, nbytes))
        return await self._legacy.acquire(nbytes)

    def op(self, nbytes: int = 0):
        return _ThrottledOp(self, nbytes)

    @property
    def max_active(self) -> int:
        return self._legacy.max_active

    @property
    def throttled_ops(self) -> int:
        return self._legacy.throttled_ops

    def dump(self) -> dict:
        out = self._legacy.dump()
        if self.scheduler is not None:
            out["scheduler_mode"] = self.scheduler.mode()
        return out


class _ThrottledOp:
    def __init__(self, throttle: SchedulerThrottle, nbytes: int):
        self.throttle = throttle
        self.nbytes = nbytes
        self._release = None

    async def __aenter__(self):
        self._release = await self.throttle.acquire(self.nbytes)
        return self

    async def __aexit__(self, *exc):
        if self._release is not None:
            self._release()
