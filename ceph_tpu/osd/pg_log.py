"""PGLog: the per-PG ordered op log driving delta recovery.

ref: src/osd/PGLog.{h,cc} + osd_types.h eversion_t/pg_log_entry_t —
every committed write appends (version, oid, op); after an acting-set
change the primary merges the authoritative log with each peer's and
derives the peer's *missing set* (objects whose newest log version the
peer hasn't applied), which recovery then pushes
(ref: PGLog::merge_log + pg_missing_t).
"""

from __future__ import annotations

from dataclasses import dataclass

from ceph_tpu.encoding.denc import Decoder, Encoder

OP_MODIFY = 1
OP_DELETE = 2


class eversion(tuple):  # noqa: N801  (reference spelling: eversion_t)
    """(epoch, version) — total order across primaries
    (ref: osd_types.h eversion_t)."""

    __slots__ = ()

    def __new__(cls, epoch: int = 0, v: int = 0):
        return super().__new__(cls, (epoch, v))

    @property
    def epoch(self) -> int:
        return self[0]

    @property
    def v(self) -> int:
        return self[1]

    def __str__(self) -> str:
        return f"{self.epoch}'{self.v}"


@dataclass
class LogEntry:
    version: eversion
    oid: str
    op: int          # OP_MODIFY / OP_DELETE

    def encode(self) -> bytes:
        e = Encoder()
        e.u32(self.version.epoch).u64(self.version.v)
        e.string(self.oid).u8(self.op)
        return e.tobytes()

    @classmethod
    def decode(cls, data: bytes) -> "LogEntry":
        d = Decoder(data)
        return cls(eversion(d.u32(), d.u64()), d.string(), d.u8())


class PGLog:
    def __init__(self) -> None:
        self.entries: list[LogEntry] = []
        self.head = eversion()          # newest
        self.tail = eversion()          # oldest retained

    def append(self, entry: LogEntry) -> None:
        self.entries.append(entry)
        self.head = entry.version

    def add(self, version: eversion, oid: str, op: int) -> LogEntry:
        entry = LogEntry(version, oid, op)
        self.append(entry)
        return entry

    def trim(self, keep: int = 1000) -> None:
        """Bound the log (ref: PGLog::trim, osd_min_pg_log_entries)."""
        if len(self.entries) > keep:
            self.entries = self.entries[-keep:]
            self.tail = self.entries[0].version

    def continuous_with(self, peer_head: eversion) -> bool:
        """Can a peer whose log head is ``peer_head`` be recovered by
        log delta against this (authoritative) log?

        ref: PGLog::proc_replica_log / PeeringState choose_acting's
        backfill decision — log-delta recovery is only sound when the
        peer's last_update falls inside this log's retained window
        (peer_head >= tail): everything the peer might be missing is
        then still in ``entries``. A peer whose head predates the tail
        (including a fresh empty-log join, head == 0'0, once this log
        has been trimmed) has divergence older than anything retained —
        its missing set CANNOT be computed from the log and the peer
        must be backfilled instead. An untrimmed log (tail == 0'0)
        retains full history, so every peer is log-recoverable."""
        return self.tail == eversion() or peer_head >= self.tail

    def newest_per_object(self) -> dict[str, LogEntry]:
        out: dict[str, LogEntry] = {}
        for entry in self.entries:
            out[entry.oid] = entry
        return out

    def missing_vs(self, authoritative: "PGLog") -> dict[str, LogEntry]:
        """Objects where `authoritative` has newer state than this log
        (ref: PGLog::merge_log populating pg_missing_t). Returns
        oid -> the authoritative entry to recover to."""
        mine = self.newest_per_object()
        missing: dict[str, LogEntry] = {}
        for oid, entry in authoritative.newest_per_object().items():
            have = mine.get(oid)
            if have is None or have.version < entry.version:
                missing[oid] = entry
        return missing

    def merge(self, authoritative: "PGLog") -> dict[str, LogEntry]:
        """Adopt the authoritative log, returning this peer's missing
        set. Divergent local entries (newer than the authoritative
        head from a dead primary) are discarded, matching the
        reference's divergent-entry rollback semantics."""
        missing = self.missing_vs(authoritative)
        self.entries = list(authoritative.entries)
        self.head = authoritative.head
        self.tail = authoritative.tail
        return missing

    def encode(self) -> bytes:
        e = Encoder()
        e.u32(self.head.epoch).u64(self.head.v)
        e.u32(self.tail.epoch).u64(self.tail.v)
        e.list(self.entries, lambda e, en: e.blob(en.encode()))
        return e.tobytes()

    @classmethod
    def decode(cls, data: bytes) -> "PGLog":
        d = Decoder(data)
        log = cls()
        log.head = eversion(d.u32(), d.u64())
        log.tail = eversion(d.u32(), d.u64())
        log.entries = [LogEntry.decode(b)
                       for b in d.list(lambda d: d.blob())]
        return log
