"""Full-cluster PG->OSD mapping table, maintained across epochs.

ref: src/osd/OSDMapMapping.h (OSDMapMapping / ParallelPGMapper) — the
reference keeps a whole-cluster pg->(up, acting) table beside the
OSDMap and rebuilds it, sharded over a work queue, on every new map.
Here the rebuild itself is already one batched device sweep per pool
(OSDMap.pg_to_crush_osds), so the win worth chasing is ACROSS epochs:
most incrementals touch a handful of OSDs or override entries, and the
affected-PG set is computable from the delta — remap only those seeds
instead of the whole cluster.

What each kind of map change invalidates (the delta algebra):

- **up/down/exists flips, primary affinity** — CRUSH never consults
  them (they act in the post-CRUSH pipeline), so the cached raw CRUSH
  table stays valid and the affected seeds are EXACTLY the rows whose
  raw output contains a flipped OSD; those rows replay only the cheap
  numpy pipeline.
- **reweight DECREASE (incl. mark_out)** — is_out acceptance of osd o
  is monotone in o's weight and consulted only when o is drawn, so an
  execution diverges iff o was accepted before and is rejected now:
  every affected seed has o in its OLD raw output. Those rows re-run
  CRUSH (the raw rows change), everything else is provably untouched.
- **reweight INCREASE (mark_in/revive)** — a PG that previously
  rejected o may newly accept it without o appearing anywhere in the
  old table, so the affected set is not recoverable from cached state:
  full sweep, gated to the pools whose rule root can reach a changed
  OSD (dirty buckets -> dirty pools).
- **pg_temp / primary_temp / pg_upmap / pg_upmap_items** — named PGs
  only; pipeline replay. Conversely, a state/weight/affinity change of
  an OSD that appears only INSIDE such an override (upmap target,
  pg_temp member — invisible in the raw CRUSH table) dirties exactly
  the rows carrying that override.
- **crush topology edits, max_osd, pool placement params (pg_num,
  pgp_num, size, rule, hashpspool)** — full sweep fallback (per pool
  for pool-param changes, cluster-wide for crush/max_osd).
- **flags, blocklist, up_thru, addrs, quotas** — no placement effect;
  explicitly ignored.

``update`` diffs the map against snapshots taken at the previous
update rather than trusting an Incremental, so it is correct for any
mutation path (mon-applied incrementals, direct mark_down in tests,
thrasher churn). Crush-change detection: object identity +
``OSDMap.crush_version`` when the same map object evolves in place,
falling back to an encoded-map digest when the holder decodes a fresh
OSDMap per epoch (the mon does).

An updated mapping attached via ``OSDMap.attach_mapping`` serves every
``pg_to_up_acting_osds`` call at its epoch — bulk and scalar — without
re-entering the mapper; ``OSDMap.calc_pg_upmaps`` additionally reuses
the raw CRUSH table for its candidate probes.

Invariant (tested by tests/test_osdmap_mapping.py): after ``update``,
every pool table is byte-identical to a from-scratch
``pg_to_crush_osds`` + ``_pipeline_from_crush`` sweep of the same map.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ceph_tpu.osd.osdmap import OSDMap, PERF, STATE_EXISTS, STATE_UP
from ceph_tpu.osd.types import FLAG_HASHPSPOOL


def _pool_sig(pool) -> tuple:
    """The placement-relevant pool fields: any change here means the
    pool's table must be rebuilt (quota/name/etc churn must NOT)."""
    return (pool.pg_num, pool.pgp_num, pool.size, pool.crush_rule,
            pool.type, pool.object_hash,
            pool.flags & FLAG_HASHPSPOOL)


class _PoolTable:
    __slots__ = ("craw", "pps", "up", "up_primary", "acting",
                 "acting_primary", "sig")


class OSDMapMapping:
    """Per-pool pg->(raw CRUSH, up, up_primary, acting, acting_primary)
    arrays at one epoch, plus the snapshots the delta diff needs."""

    def __init__(self, osdmap: OSDMap | None = None, mesh=None,
                 mesh_min_batch: int | None = None, tracer=None,
                 devmon=None):
        self.epoch = -1
        # optional device mesh (round 10): attached to every map this
        # table updates against, so full-pool sweeps — the expensive
        # crush-topology-change fallback — run the mesh-sharded sweep
        # (crush.sharded_sweep) instead of one chip
        self.mesh = mesh
        self.mesh_min_batch = mesh_min_batch
        # optional utils.tracing.Tracer: bulk sweeps emit a
        # `crush_sweep` span (n_pgs/path/n_devices tags) so sweep cost
        # shows up in `trace show` instead of as opaque mapper time
        self.tracer = tracer
        # optional utils.devmon.DeviceRuntimeMonitor (round 14): the
        # owning DAEMON's monitor — every full-pool sweep records its
        # per-call engine (launches by path) and an expected-vs-actual
        # check, so a daemon serving CRUSH off its expected kernel
        # path is a counted, health-checkable fact
        self.devmon = devmon
        self._pools: dict[int, _PoolTable] = {}
        self._osd_weight = None
        self._osd_state = None
        self._osd_aff = None
        self._max_osd = -1
        self._pg_temp: dict = {}
        self._primary_temp: dict = {}
        self._pg_upmap: dict = {}
        self._pg_upmap_items: dict = {}
        # strong reference on purpose: identity-based crush-change
        # detection must never compare against the id() of a freed
        # object (CPython reuses addresses after GC)
        self._crush_obj = None
        self._crush_version = -1
        self._crush_digest: bytes | None = None
        # last-update stats (bench/tests/asok)
        self.last_remap_pgs = 0
        self.last_full_sweep_pools = 0
        self.last_sharded_sweeps = 0
        if osdmap is not None:
            self.update(osdmap)

    # -- serving -----------------------------------------------------
    def serves(self, osdmap: OSDMap, pool_id: int) -> bool:
        return (self.epoch == osdmap.epoch
                and pool_id in self._pools)

    def lookup(self, pool_id: int, seeds):
        """(up, up_primary, acting, acting_primary) rows for ``seeds``
        — copies, so callers may scribble on them."""
        t = self._pools[pool_id]
        idx = np.asarray(seeds, dtype=np.int64)
        return (t.up[idx].copy(), t.up_primary[idx].copy(),
                t.acting[idx].copy(), t.acting_primary[idx].copy())

    def crush_raw(self, pool_id: int) -> np.ndarray | None:
        """The cached pure-CRUSH table for a pool (READ-ONLY; row i is
        seed i). The balancer replays the post-CRUSH pipeline over it
        for its candidate probes."""
        t = self._pools.get(pool_id)
        return t.craw if t is not None else None

    # -- maintenance -------------------------------------------------
    def _crush_changed(self, osdmap: OSDMap) -> tuple[bool, bytes]:
        """(changed, digest) — identity match implies unchanged
        content (crush_version bumps on every in-place edit), so the
        stored digest is reused; the digest computed on an ident miss
        is returned for the snapshot to keep, never recomputed."""
        if osdmap.crush is self._crush_obj \
                and osdmap.crush_version == self._crush_version:
            return False, self._crush_digest
        # a different object (or an in-place edit): compare content
        digest = self._digest(osdmap)
        return digest != self._crush_digest, digest

    @staticmethod
    def _digest(osdmap: OSDMap) -> bytes:
        from ceph_tpu.encoding import encode_crush_map
        return hashlib.sha1(encode_crush_map(osdmap.crush)).digest()

    def _sweep_pool(self, osdmap: OSDMap, pid: int) -> None:
        pool = osdmap.pools[pid]
        seeds = np.arange(pool.pg_num, dtype=np.uint32)
        span = self.tracer.start_root(
            "crush_sweep", tags={
                "n_pgs": int(pool.pg_num), "pool": int(pid),
                "n_devices": int(self.mesh.devices.size)
                if self.mesh is not None else 1,
            }) if self.tracer is not None else None
        path = expected = None
        ok = False
        try:
            craw, pps, (expected, path) = \
                osdmap.pg_to_crush_osds_path(pid, seeds)
            ok = True
        finally:
            # even a failed sweep must land in the trace buffer — it
            # is exactly the one an operator will want to drill into.
            # The engine tag is THIS call's returned path (round 14:
            # per-call, never the racy last_map_path slot).
            if span is not None:
                span.tag("path", (path or "?") if ok else "error")
                span.finish()
        craw = np.array(craw)    # writable: delta remap patches rows
        if self.devmon is not None:
            # per-daemon kernel-path health: engine launch counter +
            # expected-vs-actual (the devmon_expected_engine knob pins
            # the deployment contract; 'auto' trusts the plan)
            self.devmon.record_sweep(expected, path)
        if path is not None and path.endswith("+sharded"):
            PERF.inc("remap_sharded_sweeps")
            self.last_sharded_sweeps += 1
        up, upp, acting, actp = osdmap._pipeline_from_crush(
            pool, seeds, craw, pps)
        t = _PoolTable()
        t.craw, t.pps = craw, np.array(pps)
        t.up, t.up_primary = up, upp
        t.acting, t.acting_primary = acting, actp
        t.sig = _pool_sig(pool)
        self._pools[pid] = t
        PERF.inc("remap_full_sweeps")
        self.last_full_sweep_pools += 1

    def _rule_devices(self, osdmap: OSDMap, ruleno: int,
                      memo: dict) -> set:
        """All device ids reachable from the rule's TAKE roots
        (the dirty-bucket -> dirty-pool gate for weight increases)."""
        from ceph_tpu.crush.types import OP_TAKE
        crush = osdmap.crush
        out: set[int] = set()

        def walk(item: int) -> set:
            if item >= 0:
                return {item}
            if item in memo:
                return memo[item]
            memo[item] = set()          # cycle guard
            b = crush.buckets.get(item)
            acc: set[int] = set()
            if b is not None:
                for c in b.items:
                    acc |= walk(c)
            memo[item] = acc
            return acc

        rule = crush.rules.get(ruleno) if isinstance(crush.rules, dict) \
            else (crush.rules[ruleno] if ruleno < len(crush.rules)
                  else None)
        if rule is None:
            return out
        for s in rule.steps:
            if s.op == OP_TAKE:
                out |= walk(s.arg1)
        return out

    def _snapshot(self, osdmap: OSDMap, crush_digest: bytes) -> None:
        self._osd_weight = np.asarray(osdmap.osd_weight).copy()
        self._osd_state = np.asarray(osdmap.osd_state).copy()
        self._osd_aff = np.asarray(osdmap.osd_primary_affinity).copy()
        self._max_osd = osdmap.max_osd
        self._pg_temp = {pg: list(v)
                         for pg, v in osdmap.pg_temp.items()}
        self._primary_temp = dict(osdmap.primary_temp)
        self._pg_upmap = {pg: tuple(v)
                          for pg, v in osdmap.pg_upmap.items()}
        self._pg_upmap_items = {pg: [tuple(p) for p in v]
                                for pg, v in
                                osdmap.pg_upmap_items.items()}
        self._crush_obj = osdmap.crush
        self._crush_version = osdmap.crush_version
        self._crush_digest = crush_digest
        self.epoch = osdmap.epoch

    @staticmethod
    def _changed_pgs(old: dict, new: dict, norm=None) -> set:
        """Keys whose value differs; ``norm`` compares values through a
        normalizer (list-of-pairs overrides arrive as lists OR tuples
        depending on the mutation path) without building full
        normalized copies of either dict."""
        keys = set(old) | set(new)
        if norm is None:
            return {pg for pg in keys if old.get(pg) != new.get(pg)}
        changed = set()
        for pg in keys:
            o, nv = old.get(pg), new.get(pg)
            if o is None or nv is None:
                if o is not nv:
                    changed.add(pg)
            elif norm(o) != norm(nv):
                changed.add(pg)
        return changed

    def update(self, osdmap: OSDMap) -> None:
        """Bring the table to ``osdmap``'s epoch: delta remap when the
        diff allows it, full (per-pool) sweep fallback otherwise."""
        self.last_remap_pgs = 0
        self.last_full_sweep_pools = 0
        self.last_sharded_sweeps = 0
        if self.mesh is not None:
            # decode-fresh maps (the mgr per fetch) never carry the
            # mesh themselves; the table re-attaches every update
            osdmap.attach_mesh(self.mesh, self.mesh_min_batch)
        if self.epoch == osdmap.epoch and self._osd_weight is not None:
            # Same epoch as the last update: every placement mutation
            # bumps the epoch (OSDMap._dirty — the invariant the
            # caches rest on), so content is unchanged even when the
            # holder decoded a fresh object (the mgr per fetch) — no
            # digest, no diff scan, no snapshot copies.
            return
        digest = None
        if (self._osd_weight is None
                or self._max_osd != osdmap.max_osd
                or len(self._osd_weight) != osdmap.max_osd):
            full = True
        else:
            full, digest = self._crush_changed(osdmap)
        # pools: removed -> drop; new/param-changed -> full pool sweep
        for pid in [p for p in self._pools if p not in osdmap.pools]:
            del self._pools[pid]
        swept: set[int] = set()
        for pid, pool in osdmap.pools.items():
            t = self._pools.get(pid)
            if full or t is None or t.sig != _pool_sig(pool):
                self._sweep_pool(osdmap, pid)
                swept.add(pid)
        if not full:
            self._delta_remap(osdmap, swept)
        if digest is None:
            digest = self._digest(osdmap)
        self._snapshot(osdmap, digest)

    def _delta_remap(self, osdmap: OSDMap, swept: set) -> None:
        w_old, w_new = self._osd_weight, np.asarray(osdmap.osd_weight)
        n = min(len(w_old), len(w_new))
        dec = np.flatnonzero(w_new[:n] < w_old[:n])
        inc = np.flatnonzero(w_new[:n] > w_old[:n])
        plumb = (STATE_UP | STATE_EXISTS)
        st = np.flatnonzero(
            (self._osd_state[:n] ^ np.asarray(osdmap.osd_state)[:n])
            & plumb)
        aff = np.flatnonzero(
            self._osd_aff[:n]
            != np.asarray(osdmap.osd_primary_affinity)[:n])
        # weight INCREASE: the affected set is not recoverable from the
        # old table (newly-accepting PGs never held the OSD) — full
        # sweep, but only for pools whose rule can reach a changed OSD
        if inc.size:
            inc_set = set(int(o) for o in inc)
            memo: dict = {}
            for pid, pool in osdmap.pools.items():
                if pid in swept:
                    continue
                if inc_set & self._rule_devices(osdmap,
                                                pool.crush_rule, memo):
                    self._sweep_pool(osdmap, pid)
                    swept.add(pid)
        # per-pg override deltas
        temp_dirty = (self._changed_pgs(
            self._pg_temp, osdmap.pg_temp)
            | self._changed_pgs(self._primary_temp,
                                osdmap.primary_temp)
            | self._changed_pgs(self._pg_upmap, osdmap.pg_upmap)
            | self._changed_pgs(self._pg_upmap_items,
                                osdmap.pg_upmap_items,
                                norm=lambda v: [tuple(p) for p in v]))
        crush_touch = np.asarray(dec, dtype=np.int64)
        pipe_touch = np.concatenate([st, aff]).astype(np.int64)
        # every osd whose state/weight/affinity moved at all: override
        # entries (upmap targets, pg_temp members) can name OSDs that
        # never appear in the raw CRUSH output, so rows carrying such
        # an override are scanned against this set separately
        touched_any = set(int(o) for o in dec) | \
            set(int(o) for o in inc) | \
            set(int(o) for o in st) | set(int(o) for o in aff)
        # one pass over each cluster-wide override dict, grouped by
        # pool — the per-pool loop must stay O(delta), not rescan
        # every override entry once per pool
        dirty_by_pool: dict[int, set] = {}
        for pg in temp_dirty:
            dirty_by_pool.setdefault(pg.pool, set()).add(pg.seed)
        if touched_any:
            for pg, tgt in osdmap.pg_upmap.items():
                if touched_any.intersection(int(o) for o in tgt):
                    dirty_by_pool.setdefault(
                        pg.pool, set()).add(pg.seed)
            for pg, prs in osdmap.pg_upmap_items.items():
                if any(int(f) in touched_any or int(to) in touched_any
                       for f, to in prs):
                    dirty_by_pool.setdefault(
                        pg.pool, set()).add(pg.seed)
            for pg, osds in osdmap.pg_temp.items():
                if touched_any.intersection(int(o) for o in osds):
                    dirty_by_pool.setdefault(
                        pg.pool, set()).add(pg.seed)
            for pg, p in osdmap.primary_temp.items():
                if int(p) in touched_any:
                    dirty_by_pool.setdefault(
                        pg.pool, set()).add(pg.seed)
        for pid, pool in osdmap.pools.items():
            if pid in swept:
                continue
            t = self._pools[pid]
            # rows whose RAW output intersects the touched OSD sets
            crush_rows = np.flatnonzero(
                np.isin(t.craw, crush_touch).any(axis=1)) \
                if crush_touch.size else np.empty(0, dtype=np.int64)
            pipe_rows = np.flatnonzero(
                np.isin(t.craw, pipe_touch).any(axis=1)) \
                if pipe_touch.size else np.empty(0, dtype=np.int64)
            dirty_pgs = {s for s in dirty_by_pool.get(pid, ())
                         if s < pool.pg_num}
            pg_rows = np.asarray(sorted(dirty_pgs), dtype=np.int64)
            if crush_rows.size:
                seeds = crush_rows.astype(np.uint32)
                new_raw, _pps = osdmap.pg_to_crush_osds(pid, seeds)
                t.craw[crush_rows] = new_raw
            rows = np.unique(np.concatenate(
                [crush_rows, pipe_rows, pg_rows]))
            if not rows.size:
                continue
            seeds = rows.astype(np.uint32)
            up, upp, acting, actp = osdmap._pipeline_from_crush(
                pool, seeds, t.craw[rows], t.pps[rows])
            t.up[rows] = up
            t.up_primary[rows] = upp
            t.acting[rows] = acting
            t.acting_primary[rows] = actp
            PERF.inc("remap_pgs", int(rows.size))
            self.last_remap_pgs += int(rows.size)
