"""Stripe geometry math for erasure-coded objects.

ref: src/osd/ECUtil.h (ECUtil::stripe_info_t). An EC object is striped:
logical bytes are laid out rotor-style across k data chunks per stripe of
``stripe_width = k * chunk_size`` bytes; each chunk lands on a distinct
shard (spg_t shard id). Partial writes must be widened to full stripes
(the read-modify-write pipeline, ref: src/osd/ECCommon.h RMWPipeline).

All helpers are pure integer math (host-side planning); the data path
they feed is batched on device.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class StripeInfo:
    """ref: ECUtil::stripe_info_t (k = stripe_width / chunk_size)."""

    k: int
    chunk_size: int

    @property
    def stripe_width(self) -> int:
        return self.k * self.chunk_size

    # -- offset mapping (names mirror the reference methods) --------------
    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        """Round a logical offset down to its stripe start."""
        return offset - offset % self.stripe_width

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        """Round a logical offset up to the next stripe boundary."""
        return -(-offset // self.stripe_width) * self.stripe_width

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        """Stripe-aligned logical offset -> per-shard chunk offset."""
        assert offset % self.stripe_width == 0, offset
        return offset // self.k

    def chunk_aligned_logical_offset(self, chunk_offset: int) -> int:
        assert chunk_offset % self.chunk_size == 0, chunk_offset
        return chunk_offset * self.k

    def offset_len_to_stripe_bounds(self, offset: int,
                                    length: int) -> tuple[int, int]:
        """(aligned_offset, aligned_length) covering [offset, offset+len)
        widened to whole stripes — the RMW read set."""
        start = self.logical_to_prev_stripe_offset(offset)
        end = self.logical_to_next_stripe_offset(offset + length)
        return start, end - start

    def stripe_range(self, offset: int, length: int) -> tuple[int, int]:
        """(first_stripe, n_stripes) touched by a logical byte range."""
        start, alen = self.offset_len_to_stripe_bounds(offset, length)
        return start // self.stripe_width, alen // self.stripe_width

    def object_stripes(self, logical_size: int) -> int:
        return -(-logical_size // self.stripe_width) if logical_size else 0

    # -- byte <-> (stripe, chunk, intra) decomposition --------------------
    def logical_to_stripe_chunk(self, offset: int) -> tuple[int, int, int]:
        """logical byte -> (stripe index, data chunk index, byte within
        chunk). Layout: stripe s holds logical bytes
        [s*W, (s+1)*W) split contiguously into k chunks."""
        stripe, within = divmod(offset, self.stripe_width)
        chunk, intra = divmod(within, self.chunk_size)
        return stripe, chunk, intra
