"""OSD-side EC encode aggregator: cross-op stripe-batch coalescing.

The encode kernel hits its resident rate only on deep batches, but
every client op used to launch its own ``encode_batch`` from
``ECPG._submit_ec_write`` / ``_rebuild_shard`` / the backfill-push
builder — at production traffic (thousands of concurrent small-to-
medium writes) the data path is dispatch-bound, not compute-bound.
This aggregator coalesces concurrent stripe encodes from ALL the PGs
on one OSD into a single padded batched kernel launch per flush
window, amortizing dispatch exactly like the CRUSH sharded sweep
amortizes mapping (PR 10).

Contract:

- **bit-exact**: every encode kernel is stripe-row-independent, so the
  concatenated batch's rows equal the per-op results lane for lane
  (pinned in tests/test_ec_agg.py); the per-op path survives as the
  measured baseline behind ``osd_ec_agg=off`` (read LIVE);
- **latency-bounded**: a batch flushes when ``osd_ec_agg_window_us``
  expires, when ``osd_ec_agg_max_stripes`` accumulate, or when the
  queue goes IDLE (one event-loop yield plus a window slice with no
  new arrivals) — a lone op is never held past the window;
- **fused checksum**: when any waiter wants write-time ``_hcrc``
  stamps, the flush runs the plugin's fused checksum+encode program
  (ec/jax_plugin.encode_batch_with_crc) so checksum+encode stays ONE
  device launch for the whole coalesced batch;
- **padded launches**: the aggregate batch is zero-padded to the next
  power of two before dispatch, so the jit cache sees O(log max_batch)
  distinct shapes instead of one program per concurrency level.

Groups are keyed by (profile, k, C): two PGs of the same pool coalesce
even though each holds its own plugin instance (the kernel is a pure
function of the profile).
"""

from __future__ import annotations

import asyncio
import time

import numpy as np

from ceph_tpu.utils.logging import get_logger
from ceph_tpu.utils.perf_counters import PerfCountersBuilder

log = get_logger("osd")


def _agg_perf():
    """Per-OSD counter family (register=False: several in-process OSDs
    each own one; they reach prometheus through the PR 12 daemon->mgr
    report path as ``ceph_osd_ec_agg_*`` rows, not the process-local
    singleton collection)."""
    return (
        PerfCountersBuilder("osd_ec_agg")
        .add_u64_counter("batches", "coalesced kernel launches")
        .add_u64_counter("stripes", "stripes encoded through batches")
        .add_u64_counter("ops", "encode requests served")
        .add_u64_counter("bypass",
                         "encodes served per-op (osd_ec_agg=off)")
        .add_u64_counter("flush_window",
                         "flushes triggered by the window expiring")
        .add_u64_counter("flush_full",
                         "flushes triggered by osd_ec_agg_max_stripes")
        .add_u64_counter("flush_idle",
                         "flushes triggered by queue idleness")
        .add_time_avg("batch_occupancy",
                      "stripes per flushed batch (long-run avg)")
        .add_time_avg("batch_wait",
                      "seconds an op waited for its flush (long-run "
                      "avg)")
        .add_u64_counter("flush_failures",
                         "batched flushes whose device encode raised "
                         "(the batch disaggregated per-op)")
        .add_u64_counter("per_op_retries",
                         "bounded per-op device retries after a "
                         "failed batch (osd_ec_fallback_retries)")
        .add_u64_counter("fallback_ops",
                         "ops served by the bit-exact reference "
                         "(numpy) encoder after device retries "
                         "exhausted")
        .add_u64_counter("crc_fallbacks",
                         "fused checksum+encode failures that dropped "
                         "to plain encode + host crc (the fused jit "
                         "quarantines on backoff)")
        .create_perf_counters(register=False))


class _Entry:
    __slots__ = ("data", "with_crc", "fut", "t0")

    def __init__(self, data, with_crc, fut, t0):
        self.data = data
        self.with_crc = with_crc
        self.fut = fut
        self.t0 = t0


class _Group:
    """One in-flight coalescing batch; staleness is decided by
    identity (``self._groups.get(key) is g``), never by counters."""

    __slots__ = ("ec", "entries", "stripes", "task")

    def __init__(self, ec):
        self.ec = ec
        self.entries: list[_Entry] = []
        self.stripes = 0
        self.task: asyncio.Task | None = None


class ECAggregator:
    """One per OSD daemon; every ECPG encode routes through it."""

    def __init__(self, config: dict | None = None):
        self.config = config if config is not None else {}
        self.perf = _agg_perf()
        self._groups: dict[tuple, _Group] = {}
        self.stopped = False
        # fused checksum+encode quarantine (round 16): after the fused
        # jit raises, flushes serve plain encode + host crc until the
        # backoff deadline passes, then the fused path is retried
        self._crc_q_until = 0.0
        self._crc_failures = 0

    # -- knobs (read LIVE) -------------------------------------------------
    def enabled(self) -> bool:
        return bool(self.config.get("osd_ec_agg", True))

    def window_s(self) -> float:
        return float(self.config.get("osd_ec_agg_window_us", 500)) / 1e6

    def max_stripes(self) -> int:
        return int(self.config.get("osd_ec_agg_max_stripes", 4096))

    def _retries(self) -> int:
        return int(self.config.get("osd_ec_fallback_retries", 1))

    # -- submit ------------------------------------------------------------
    async def encode(self, ec, data, with_crc: bool = False):
        """Encode a (B, k, C) uint8 stripe batch; returns
        ``(parity np(B, m, C), row_crcs np(B, k+m) | None)``.
        ``row_crcs`` is None when ``with_crc`` is False or the plugin
        has no fused path (callers fall back to zlib via
        ec.crc.hcrc_attr)."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if not self.enabled() or self.stopped:
            # the measured per-op baseline: one UNPADDED launch per
            # op, exactly the pre-aggregator path — padding here
            # would make the baseline systematically slower than what
            # production previously ran and flatter the aggregator's
            # speedup (fused checksum still applies — the fusion is
            # orthogonal to coalescing)
            self.perf.inc("bypass")
            try:
                return self._run(ec, data, with_crc, pad=False)
            except Exception as e:
                return self._degrade_one(ec, data, with_crc, e)
        key = (str(ec.profile), int(data.shape[1]), int(data.shape[2]))
        g = self._groups.get(key)
        if g is None:
            g = self._groups[key] = _Group(ec)
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        g.entries.append(_Entry(data, with_crc, fut, loop.time()))
        g.stripes += data.shape[0]
        if g.stripes >= self.max_stripes():
            self._flush(key, g, "full")
        elif g.task is None:
            g.task = asyncio.ensure_future(self._flush_later(key, g))
        return await fut

    async def _flush_later(self, key: tuple, g: _Group) -> None:
        """Window/idle flusher for one group generation. Yields to the
        loop once so a concurrent burst of submitters lands, then
        soaks window slices; two consecutive looks with no new arrival
        mean the queue is idle — flush early instead of pinning a lone
        op to the full window."""
        loop = asyncio.get_event_loop()
        window = self.window_s()
        deadline = loop.time() + window
        seen = -1
        try:
            while True:
                await asyncio.sleep(0)
                if self._groups.get(key) is not g:
                    return                   # full-trigger beat us
                now = loop.time()
                if now >= deadline:
                    self._flush(key, g, "window")
                    return
                if len(g.entries) == seen:
                    self._flush(key, g, "idle")
                    return
                seen = len(g.entries)
                await asyncio.sleep(
                    min(deadline - now, max(window / 8, 1e-4)))
        except asyncio.CancelledError:
            if self._groups.get(key) is g:
                self._flush(key, g, "window")
            raise

    # -- flush -------------------------------------------------------------
    def _flush(self, key: tuple, g: _Group, trigger: str) -> None:
        if self._groups.get(key) is g:
            del self._groups[key]
        if g.task is not None and g.task is not asyncio.current_task():
            g.task.cancel()
            g.task = None
        entries = g.entries
        if not entries:
            return
        datas = [e.data for e in entries]
        big = datas[0] if len(datas) == 1 else \
            np.concatenate(datas, axis=0)
        want_crc = any(e.with_crc for e in entries)
        loop = asyncio.get_event_loop()
        try:
            parity, crcs = self._run(g.ec, big, want_crc)
        except Exception as e:
            self._degrade(g.ec, entries, e)
            return
        off = 0
        now = loop.time()
        for ent in entries:
            b = ent.data.shape[0]
            res = (parity[off:off + b],
                   crcs[off:off + b]
                   if crcs is not None and ent.with_crc else None)
            if not ent.fut.done():
                ent.fut.set_result(res)
            self.perf.avg_add("batch_wait", now - ent.t0)
            off += b
        self.perf.inc("batches")
        self.perf.inc("stripes", int(big.shape[0]))
        self.perf.inc("ops", len(entries))
        self.perf.inc(f"flush_{trigger}")
        self.perf.avg_add("batch_occupancy", float(big.shape[0]))
        log.dout(10, f"ec_agg flush {trigger}: {len(entries)} ops, "
                     f"{big.shape[0]} stripes")

    # -- degrade ladder (round 16) -----------------------------------------
    def _degrade(self, ec, entries, err: Exception) -> None:
        """Failed batch flush: DISAGGREGATE — retry each member stripe
        as its own device encode, then the bit-exact reference (numpy)
        encoder; only the op whose stripe still fails under the
        reference sees the exception. One poisoned stripe must not
        fail its batchmates, and a client write must never error
        because the accelerator did."""
        self.perf.inc("flush_failures")
        log.dout(0, f"ec_agg batch flush failed "
                    f"({type(err).__name__}: {str(err)[:200]}) — "
                    f"disaggregating {len(entries)} ops")
        loop = asyncio.get_event_loop()
        for ent in entries:
            try:
                res = self._run(ec, ent.data, ent.with_crc, pad=False)
            except Exception as e:
                try:
                    res = self._degrade_one(ec, ent.data,
                                            ent.with_crc, e)
                except Exception as e2:
                    if not ent.fut.done():
                        ent.fut.set_exception(e2)
                    self.perf.avg_add("batch_wait",
                                      loop.time() - ent.t0)
                    continue
            if not ent.fut.done():
                ent.fut.set_result(res)
            self.perf.avg_add("batch_wait", loop.time() - ent.t0)

    def _degrade_one(self, ec, data, with_crc: bool, err: Exception):
        """Per-op tail of the ladder: osd_ec_fallback_retries more
        device attempts, then the reference encoder (host numpy,
        bit-exact by construction; crcs fall back to the caller's
        zlib path). Raises the last device error only when the
        reference itself fails."""
        exc = err
        for _ in range(max(0, self._retries())):
            self.perf.inc("per_op_retries")
            try:
                return self._run(ec, data, with_crc, pad=False)
            except Exception as e:
                exc = e
        try:
            parity = np.asarray(ec.encode_batch_reference(data),
                                dtype=np.uint8)
        except Exception:
            raise exc
        self.perf.inc("fallback_ops")
        log.dout(1, f"ec_agg op served by the reference encoder "
                    f"({data.shape[0]} stripes) after device retries "
                    f"exhausted")
        return parity, None

    @staticmethod
    def _pad(b: int) -> int:
        """Next power of two: bounds the jit cache to O(log) shapes."""
        return 1 << (int(b) - 1).bit_length() if b > 1 else 1

    def _run(self, ec, data, want_crc: bool, pad: bool = True):
        """One device launch over a (possibly padded) batch. The fused
        checksum+encode jit carries its own quarantine: after it
        raises, flushes drop to plain encode + host crc (callers'
        zlib path) until an exponential-backoff deadline
        (osd_ec_fallback_quarantine_base/_max) passes, then the fused
        path is probed again by simply serving the next crc flush."""
        b = data.shape[0]
        padded = self._pad(b) if pad else b
        if padded != b:
            pad = np.zeros((padded - b,) + data.shape[1:],
                           dtype=np.uint8)
            data = np.concatenate([data, pad], axis=0)
        if want_crc and time.monotonic() >= self._crc_q_until:
            try:
                parity, crcs = ec.encode_batch_with_crc(data)
                parity = np.asarray(parity)[:b]
                crcs = None if crcs is None else np.asarray(crcs)[:b]
            except Exception as e:
                self._crc_fail(e)
            else:
                self._crc_failures = 0
                return parity, crcs
        return np.asarray(ec.encode_batch(data))[:b], None

    def _crc_fail(self, e: Exception) -> None:
        self.perf.inc("crc_fallbacks")
        self._crc_failures += 1
        base = float(self.config.get(
            "osd_ec_fallback_quarantine_base", 1.0))
        cap = float(self.config.get(
            "osd_ec_fallback_quarantine_max", 30.0))
        backoff = min(base * (2 ** (self._crc_failures - 1)), cap)
        self._crc_q_until = time.monotonic() + backoff
        log.dout(0, f"fused checksum+encode failed "
                    f"({type(e).__name__}: {str(e)[:200]}) — plain "
                    f"encode + host crc for {backoff:.2f}s")

    # -- lifecycle / observability ----------------------------------------
    def drain(self) -> int:
        """Daemon stop: flush nothing more — cancel every waiter (their
        PG op workers are being cancelled too) and kill flush timers.
        Returns the number of ops dropped."""
        self.stopped = True
        n = 0
        for key, g in list(self._groups.items()):
            if g.task is not None:
                g.task.cancel()
                g.task = None
            for ent in g.entries:
                n += 1
                if not ent.fut.done():
                    ent.fut.cancel()
            self._groups.pop(key, None)
        return n

    def dump(self) -> dict:
        d = self.perf.dump()
        occ = d.get("batch_occupancy", {})
        wait = d.get("batch_wait", {})
        return {
            "enabled": self.enabled(),
            "window_us": float(
                self.config.get("osd_ec_agg_window_us", 500)),
            "max_stripes": self.max_stripes(),
            "pending_groups": len(self._groups),
            "pending_ops": sum(len(g.entries)
                               for g in self._groups.values()),
            "batches": d.get("batches", 0),
            "stripes": d.get("stripes", 0),
            "ops": d.get("ops", 0),
            "bypass": d.get("bypass", 0),
            "flushes": {t: d.get(f"flush_{t}", 0)
                        for t in ("window", "full", "idle")},
            "avg_occupancy": (occ.get("sum", 0.0) /
                              occ.get("avgcount", 1)
                              if occ.get("avgcount") else 0.0),
            "avg_batch_wait_s": (wait.get("sum", 0.0) /
                                 wait.get("avgcount", 1)
                                 if wait.get("avgcount") else 0.0),
        }
