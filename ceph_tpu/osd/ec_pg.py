"""ECPG: erasure-coded placement groups in the live cluster.

ref: src/osd/ECBackend.{h,cc} + ECCommon.h — the EC strategy under a
PG: objects are striped (ECUtil::stripe_info_t); each acting POSITION
holds one shard; the primary widens partial writes to whole stripes
(RMWPipeline: sub-read old chunks, merge, re-encode), fans per-shard
chunk writes out as sub-ops (MOSDECSubOpWrite), reassembles reads from
k shards (ReadPipeline) and decodes around missing/stale shards via
``minimum_to_decode`` + ``decode_chunks``; recovery regenerates a lost
shard from any k live shards (ECBackend::handle_recovery_read_complete).

TPU-first: every encode/decode over a stripe range is ONE batched
device call ((B, k, C) -> (B, m, C)) through the jax EC plugin — the
reference encodes stripe-by-stripe on CPU.

Shard object layout: the collection object holds this shard's
concatenated chunks; xattrs ``_v`` (object version) and ``_size``
(logical size) are written with every sub-op so any shard can answer
stat and staleness checks (ref: EC objects carry identical xattrs on
every shard).
"""

from __future__ import annotations

import asyncio

import numpy as np

from ceph_tpu.ec import crc as ec_crc
from ceph_tpu.ec.registry import factory as ec_factory
from ceph_tpu.os_.objectstore import StoreError, Transaction
from ceph_tpu.osd.ecutil import StripeInfo
from ceph_tpu.osd.messages import (
    MOSDECSubOpRead, MOSDECSubOpReadReply, MOSDECSubOpWrite,
    MOSDECSubOpWriteReply, MOSDOp, OSD_OP_DELETE, OSD_OP_GETXATTR,
    OSD_OP_OMAP_GET, OSD_OP_OMAP_RM, OSD_OP_OMAP_SET, OSD_OP_PGLS,
    OSD_OP_READ,
    OSD_OP_SETXATTR, OSD_OP_STAT, OSD_OP_TRUNCATE, OSD_OP_WRITE,
    OSD_OP_WRITEFULL, OSD_OP_ZERO,
)
from ceph_tpu.osd.pg import PG, PGMETA
from ceph_tpu.osd.pg_log import OP_DELETE, OP_MODIFY, LogEntry, eversion
from ceph_tpu.utils.logging import get_logger

log = get_logger("osd")


def _vblob(v: eversion) -> bytes:
    return v.epoch.to_bytes(4, "little") + v.v.to_bytes(8, "little")


def _vparse(b: bytes | None) -> eversion:
    if not b:
        return eversion()
    return eversion(int.from_bytes(b[:4], "little"),
                    int.from_bytes(b[4:12], "little"))


class UnreadableNow(Exception):
    """The object exists but fewer than k fresh shards are reachable
    RIGHT NOW (a revived shard still recovering plus a down shard, mid-
    peering churn, ...). Transient by construction: recovery or the next
    map refills the shard set, so the op must be retried, never failed
    with a terminal errno (ref: PrimaryLogPG::wait_for_unreadable_object
    — upstream parks the op on the recovery queue)."""


class ECPG(PG):
    def __init__(self, osd, pool, pgid):
        super().__init__(osd, pool, pgid)
        prof = dict(pool.extra.get("profile") or
                    {"k": 2, "m": 1, "plugin": "jax"})
        prof.setdefault("plugin", "jax")
        self.ec = ec_factory(prof)
        self.k = self.ec.get_data_chunk_count()
        self.m = self.ec.get_coding_chunk_count()
        self.sinfo = StripeInfo(
            self.k, int(prof.get("stripe_unit", 4096)))
        self._subop_waiters: dict[
            int, tuple[set[int], asyncio.Future, set[int]]] = {}
        self._subread_waiters: dict[int, asyncio.Future] = {}
        self._posfix_task: asyncio.Task | None = None

    def advance(self, up, acting, primary, epoch) -> None:
        old_acting = list(self.acting)
        super().advance(up, acting, primary, epoch)
        if self.osd.whoami in acting and acting != old_acting:
            # the interval moved our position: any shard whose stored
            # _pos stamp no longer matches must be re-derived — its
            # bytes stay READABLE everywhere (gather files by stamp),
            # but redundancy is degraded until this slot holds its own
            # position's bytes again. Cancel-and-respawn: a sweep
            # started in a PRIOR interval exits at its guard and must
            # not gate this interval's sweep.
            if self._posfix_task is not None:
                self._posfix_task.cancel()
            self._posfix_task = asyncio.ensure_future(
                self._fix_shard_positions())

    async def _fix_shard_positions(self) -> None:
        """Best-effort self-heal of position-mismatched shards after
        an acting shuffle (e.g. auto-out remap reverted on revive).
        Bounded retries: sources may only become decodable once the
        primary's own recovery lands."""
        interval = self.interval_start
        await asyncio.sleep(0.5)            # let peering settle
        myshard = self.my_shard()
        if myshard < 0:
            return
        # round-based, never gives up silently: a stale shard's
        # sources may only become decodable once the primary's
        # recovery pushes land on other holders — keep sweeping (with
        # a growing pause, loudly) until clean or the interval moves;
        # stale-position shards are degraded redundancy and must not
        # be abandoned while this interval lives
        _round = 0
        while True:
            if self.interval_start != interval or \
                    self.my_shard() != myshard:
                return                  # interval moved on: its own
                #                         advance re-triggers the fix
            try:
                oids = [o for o in
                        self.osd.store.list_objects(self.cid)
                        if o != PGMETA]
            except StoreError:
                return
            stale = [o for o in oids
                     if 0 <= self._stored_pos(o) != myshard]
            if not stale:
                return
            for oid in stale:
                if self.interval_start != interval:
                    return
                try:
                    await self._reconstruct_local(oid)
                    log.dout(1, f"pg {self.pgid} osd."
                                f"{self.osd.whoami} re-derived {oid} "
                                f"for position {myshard}")
                except Exception as e:
                    # sources not decodable yet (e.g. the primary's
                    # push to another holder hasn't landed): the next
                    # round retries
                    log.dout(10, f"pg {self.pgid} posfix {oid} "
                                 f"round {_round}: {e!r}")
            _round += 1
            if _round % 60 == 0:
                log.error(f"pg {self.pgid} osd.{self.osd.whoami}: "
                          f"{len(stale)} position-stale shard(s) "
                          f"still unhealed after {_round} rounds "
                          f"(redundancy degraded)")
            await asyncio.sleep(min(0.5 + 0.1 * _round, 5.0))

    # -- shard helpers -----------------------------------------------------
    def my_shard(self) -> int:
        try:
            return self.acting.index(self.osd.whoami)
        except ValueError:
            return -1

    def _local_shard_state(self, oid: str):
        """(exists, shard bytes, version, logical size)."""
        try:
            data = self.osd.store.read(self.cid, oid)
            attrs = self.osd.store.getattrs(self.cid, oid)
        except StoreError:
            return False, b"", eversion(), 0
        return True, data, _vparse(attrs.get("_v")), \
            int.from_bytes(attrs.get("_size", b"\0" * 8), "little")

    def _stored_pos(self, oid: str, default: int = -1) -> int:
        """The acting POSITION this store's shard bytes were encoded
        for (the write-time ``_pos`` stamp); ``default`` when the
        stamp is absent (legacy shard — assume it matches)."""
        try:
            attrs = self.osd.store.getattrs(self.cid, oid)
        except StoreError:
            return default
        blob = attrs.get("_pos")
        if not blob:
            return default
        return int.from_bytes(blob, "little", signed=True)

    @staticmethod
    def _pos_attr(pos: int) -> bytes:
        return int(pos).to_bytes(4, "little", signed=True)

    def _obj_version(self, oid: str) -> eversion:
        return self._local_shard_state(oid)[2]

    def _obj_size(self, oid: str) -> int:
        exists, _, _, size = self._local_shard_state(oid)
        if not exists:
            raise StoreError(f"no object {oid}")
        return size

    # -- chunk gathering (the ReadPipeline) --------------------------------
    async def _subread(self, osd_id: int, oid: str, chunk_off: int,
                       chunk_len: int):
        tid = self.osd.next_tid()
        fut = asyncio.get_event_loop().create_future()
        self._subread_waiters[tid] = fut
        try:
            await self.osd.send_osd(osd_id, MOSDECSubOpRead(
                tid=tid, epoch=self.epoch, pgid=self.cid, oid=oid,
                chunk_off=chunk_off, chunk_len=chunk_len,
                from_osd=self.osd.whoami))
            return await asyncio.wait_for(fut, timeout=5.0)
        except (asyncio.TimeoutError, ConnectionError, OSError):
            return None
        finally:
            self._subread_waiters.pop(tid, None)

    async def _gather(self, oid: str, first: int, count: int,
                      version: eversion,
                      exclude_osds: frozenset = frozenset(),
                      repair: bool = False):
        """Collect this stripe range's chunks from live, fresh shards
        and reconstruct data chunks 0..k-1 -> (count, k, C) uint8.

        Shards whose object version differs (missed writes / stale
        after outage) are excluded; decode fills the gaps
        (ref: ECCommon::ReadPipeline get_remaining_shards).

        Chunks are filed under the POSITION the shard's bytes encode
        (the write-time ``_pos`` stamp), NOT the holder's current
        acting slot: an interval shuffle (e.g. an auto-out remap
        while a peer was down, reverted on revive) can leave a
        surviving OSD at a different slot than the one its stored
        bytes were encoded for — treating those bytes positionally-
        by-slot silently decodes garbage. Stamps are authoritative;
        a shard without one (legacy) is assumed to match its slot.

        ``exclude_osds``: OSDs never used as sources — a holder whose
        shard is being rebuilt (missing, stale, scrub-flagged) must
        not contribute to its own reconstruction.

        ``repair``: this gather feeds a shard REBUILD (recovery /
        backfill), not a client read — its decode pays a recovery-
        class QoS grant inside the read aggregator (client reads were
        already cost-tagged at admission).

        Hot-shard residency (round 19): when the OSD carries a
        DeviceShardCache, the gathered batch is pinned device-side
        keyed by (pg, oid, range, VERSION) — a repeat gather of the
        same generation skips the subreads, the decode and the H2D
        stage entirely. Never consulted or fed under ``exclude_osds``
        (a rebuild's source constraints are not the cache's)."""
        C = self.sinfo.chunk_size
        off, ln = first * C, count * C
        cache = getattr(self.osd, "ec_resident", None)
        ckey = None
        if cache is not None and not exclude_osds:
            ckey = (str(self.cid), oid, int(first), int(count),
                    _vblob(version))
            hit = cache.get(ckey)
            if hit is not None:
                return np.asarray(hit)
        avail: dict[int, np.ndarray] = {}
        for slot, osd_id in enumerate(self.acting):
            # stop once decodable: all data positions in hand, or any
            # k positions once every data SLOT has been tried (MDS
            # property — same early-stop the pre-stamp code had)
            if set(range(self.k)) <= set(avail) or \
                    (slot >= self.k and len(avail) >= self.k):
                break
            if osd_id < 0 or osd_id in exclude_osds or \
                    not self.osd.osd_is_up(osd_id):
                continue
            if osd_id == self.osd.whoami:
                exists, data, ver, _size = self._local_shard_state(oid)
                if not exists or ver != version:
                    continue
                pos = self._stored_pos(oid, default=slot)
                chunk = np.zeros(ln, dtype=np.uint8)
                piece = data[off:off + ln]
                chunk[:len(piece)] = np.frombuffer(piece, dtype=np.uint8)
            else:
                reply = await self._subread(osd_id, oid, off, ln)
                if reply is None or not reply.exists:
                    continue
                if eversion(reply.version_epoch,
                            reply.version_v) != version:
                    continue
                pos = reply.shard_pos if reply.shard_pos >= 0 else slot
                chunk = np.zeros(ln, dtype=np.uint8)
                piece = reply.data[:ln]
                chunk[:len(piece)] = np.frombuffer(piece, dtype=np.uint8)
            if pos < 0 or pos >= self.k + self.m or pos in avail:
                continue
            avail[pos] = chunk.reshape(count, C)
        want = set(range(self.k))
        if want <= set(avail):
            out = np.stack([avail[c] for c in range(self.k)], axis=1)
            if ckey is not None:
                cache.put(ckey, out)
            return out
        # degraded: decode missing data chunks from what we have —
        # routed through the OSD's cross-op read aggregator, which
        # coalesces concurrent decodes from every PG on this OSD into
        # one padded batched launch per flush window (per-op path
        # behind osd_ec_read_agg=off)
        try:
            need = self.ec.minimum_to_decode(want, list(avail))
        except ValueError:
            need = None
        if need is None or not set(need) <= set(avail):
            raise UnreadableNow(
                f"{oid}: {len(avail)} fresh shards < k={self.k} "
                f"(have {sorted(avail)})")
        use = sorted(need)
        stacked = np.stack([avail[c] for c in use], axis=1)
        missing = sorted(want - set(avail))
        decoded = await self._agg_decode(missing, use, stacked,
                                         repair=repair)
        out = np.zeros((count, self.k, C), dtype=np.uint8)
        for c in range(self.k):
            if c in avail:
                out[:, c] = avail[c]
            else:
                out[:, c] = np.asarray(decoded[:, missing.index(c)])
        if ckey is not None:
            cache.put(ckey, out)
        return out

    # -- client op execution ----------------------------------------------
    async def _execute(self, m: MOSDOp) -> None:
        reqid = (m.src, getattr(m.conn, "peer_session", 0), m.tid)
        store = self.osd.store
        oid = m.oid
        ec_mutating = {OSD_OP_WRITE, OSD_OP_WRITEFULL,
                       OSD_OP_TRUNCATE, OSD_OP_ZERO, OSD_OP_DELETE,
                       OSD_OP_SETXATTR, OSD_OP_OMAP_SET,
                       OSD_OP_OMAP_RM}
        if self._backfill_blocked(
                oid, any(c in ec_mutating for c in m.op_codes)):
            # same degraded-object gate as the replicated path: ops on
            # objects above this primary's own watermark park; READS
            # inside the in-flight scan range stay served (they never
            # mutate, so they cannot race the watermark advance)
            await self._reply(m, -11, b"", {})
            return
        if oid in self.my_missing:
            # this primary's own shard of the object is still being
            # recovered: the op must neither see -ENOENT nor mutate
            # around the missing state (ref: PrimaryLogPG::
            # wait_for_unreadable_object); the objecter retries -EAGAIN
            await self._reply(m, -11, b"", {})
            return
        data_out = b""
        extra: dict = {}
        # edits: (offset, bytes) merges; specials for truncate/delete
        edits: list[tuple[int, bytes]] = []
        new_size: int | None = None
        attrs_delta: dict[str, bytes] = {}
        omap_delta: dict[str, bytes] = {}
        omap_rm: list[str] = []
        deleted = False
        write_full = None
        for code, off, length, name, data in m.unpack_ops():
            if code == OSD_OP_READ:
                try:
                    data_out = await self._read_range(oid, off, length)
                except UnreadableNow as e:
                    log.dout(5, f"pg {self.pgid} read parks: {e}")
                    await self._reply(m, -11, b"", {})  # retry later
                    return
                except StoreError:
                    await self._reply(m, -2, b"", {})
                    return
            elif code == OSD_OP_STAT:
                try:
                    extra["size"] = self._obj_size(oid)
                except StoreError:
                    await self._reply(m, -2, b"", {})
                    return
            elif code == OSD_OP_GETXATTR:
                try:
                    attrs = store.getattrs(self.cid, oid)
                except StoreError:
                    await self._reply(m, -2, b"", {})
                    return
                if name not in attrs:
                    await self._reply(m, -61, b"", {})
                    return
                data_out = attrs[name]
            elif code == OSD_OP_OMAP_GET:
                try:
                    omap = store.omap_get(self.cid, oid)
                except StoreError:
                    await self._reply(m, -2, b"", {})
                    return
                extra["omap"] = {k: v.hex() for k, v in omap.items()
                                 if not k.startswith("_")}
            elif code == OSD_OP_PGLS:
                extra["objects"] = [o for o in
                                    store.list_objects(self.cid)
                                    if o != PGMETA]
            elif code == OSD_OP_WRITE:
                # keep the frame view: the bytes land in np.frombuffer
                # at the RMW carve, no host staging copy in between
                edits.append((off, data))
            elif code == OSD_OP_WRITEFULL:
                write_full = data
            elif code == OSD_OP_ZERO:
                edits.append((off, b"\x00" * length))
            elif code == OSD_OP_TRUNCATE:
                new_size = off
            elif code == OSD_OP_DELETE:
                deleted = True
            elif code == OSD_OP_SETXATTR:
                attrs_delta[name] = bytes(data)
            elif code == OSD_OP_OMAP_SET:
                omap_delta[name] = bytes(data)
            elif code == OSD_OP_OMAP_RM:
                omap_rm.append(name)
            else:
                await self._reply(m, -95, b"", {})
                return
        mutated = bool(edits or attrs_delta or omap_delta or omap_rm or
                       deleted or write_full is not None or
                       new_size is not None)
        if not mutated:
            await self._reply(m, 0, data_out, extra)
            return
        if reqid in self._reqid_results:
            result, rextra = self._reqid_results[reqid]
            await self._reply(m, result, b"", rextra)
            return
        if (deleted or (omap_rm and not (edits or attrs_delta or
                                         omap_delta or
                                         write_full is not None or
                                         new_size is not None))) and \
                not self.osd.store.exists(self.cid, oid):
            # delete / bare omap-rm of a nonexistent object: -ENOENT,
            # never materialize a ghost object
            await self._reply(m, -2, b"", {})
            return
        result = await self._submit_ec_write(
            oid, edits, write_full, new_size, deleted, attrs_delta,
            omap_delta, omap_rm)
        extra["version"] = str(self.pg_log.head)
        if result != -11:
            # -11 (-EAGAIN) here means the min_size gate rejected the op
            # BEFORE anything was applied: recording it would make every
            # future resend of this reqid replay -EAGAIN forever, even
            # after the PG heals (r4 review finding). Re-execution is
            # safe — nothing was logged. A -5 (< k shards committed) IS
            # recorded: the entry is in the pg log, so a replay would
            # double-log; the dup honestly reports the partial failure.
            self._reqid_results[reqid] = (result, extra)
        if len(self._reqid_results) > 2000:
            for k in list(self._reqid_results)[:1000]:
                self._reqid_results.pop(k, None)
        await self._reply(m, result, data_out, extra)

    async def _read_range(self, oid: str, off: int,
                          length: int) -> bytes:
        size = self._obj_size(oid)          # raises if absent
        end = size if not length else min(off + length, size)
        if off >= end:
            return b""
        version = self._obj_version(oid)
        first, count = self.sinfo.stripe_range(off, end - off)
        stripes = await self._gather(oid, first, count, version)
        flat = stripes.reshape(-1).tobytes()
        W = self.sinfo.stripe_width
        lo = off - first * W
        return flat[lo:lo + (end - off)]

    # -- the RMW + sub-op write pipeline -----------------------------------
    async def _submit_ec_write(self, oid, edits, write_full, new_size,
                               deleted, attrs_delta, omap_delta,
                               omap_rm=()) -> int:
        live = self.live_acting()
        if len(live) < self.pool.min_size:
            return -11
        exists, _, old_version, old_size = self._local_shard_state(oid)
        old = None
        if not deleted and write_full is None:
            size = old_size if exists else 0
            hi = max([off + len(b) for off, b in edits], default=0)
            size = max(size, hi)
            if new_size is not None:
                size = new_size
            span_lo = min([off for off, _ in edits], default=0)
            span_hi = max(hi, size if new_size is not None else 0)
            if new_size is not None and exists:
                span_lo = 0 if not edits else min(span_lo, new_size)
                span_hi = max(span_hi, old_size)
            first, count = self.sinfo.stripe_range(
                span_lo, max(span_hi - span_lo, 1))
            # RMW: read the touched stripes' old contents BEFORE the
            # log append — a transiently unreadable object (fewer than
            # k fresh shards mid-recovery) must EAGAIN with no side
            # effects, not log an entry it then cannot apply
            if exists:
                try:
                    old = await self._gather(oid, first, count,
                                             old_version)
                except UnreadableNow as e:
                    log.dout(5, f"pg {self.pgid} rmw parks: {e}")
                    return -11
            else:
                old = np.zeros((count, self.k, self.sinfo.chunk_size),
                               dtype=np.uint8)
        self.last_user_version += 1
        version = eversion(self.epoch, self.last_user_version)
        entry = self.pg_log.add(
            version, oid, OP_DELETE if deleted else OP_MODIFY)
        self.pg_log.trim(keep=self._trim_keep())
        self._meta_txn_store()
        if deleted:
            return await self._fan_out_delete(oid, entry)
        if write_full is not None:
            logical = write_full
            size = len(logical)
            first, count = 0, self.sinfo.object_stripes(size) or 1
            buf = np.zeros(count * self.sinfo.stripe_width,
                           dtype=np.uint8)
            buf[:size] = np.frombuffer(logical, dtype=np.uint8)
            trunc_stripes = count
        else:
            buf = old.reshape(-1).copy()
            W = self.sinfo.stripe_width
            base = first * W
            for off, data in edits:
                lo = off - base
                buf[lo:lo + len(data)] = np.frombuffer(data,
                                                       dtype=np.uint8)
            if new_size is not None and new_size < old_size:
                # zero everything past the new size within the range
                lo = max(new_size - base, 0)
                buf[lo:] = 0
            trunc_stripes = self.sinfo.object_stripes(size)
        # encode the touched range in one device call — routed through
        # the OSD's cross-op aggregator, which coalesces concurrent
        # encodes from every PG on this OSD into one padded batched
        # launch per flush window (per-op path behind osd_ec_agg=off).
        # A whole-object write also wants per-shard _hcrc stamps, so
        # the flush runs the FUSED checksum+encode program and this op
        # gets its shards' row CRCs back alongside the parity.
        C = self.sinfo.chunk_size
        data_chunks = buf.reshape(count, self.k, C)
        whole = write_full is not None
        parity, row_crcs = await self._agg_encode(data_chunks,
                                                  with_crc=whole)
        attrs_delta = dict(attrs_delta)
        attrs_delta["_v"] = _vblob(version)
        attrs_delta["_size"] = size.to_bytes(8, "little")
        # fan the per-shard sub-ops out (ref: ECBackend sub writes)
        tid = self.osd.next_tid()
        entry_blob = entry.encode()
        per_osd: dict[int, MOSDECSubOpWrite] = {}
        for pos, osd_id in enumerate(self.acting):
            if osd_id < 0 or not self.osd.osd_is_up(osd_id):
                continue                   # hole: recovery rebuilds it
            if not self._should_send_repop(osd_id, oid):
                continue    # backfill target above its watermark: the
                #             scan rebuilds this shard; a sub-op now
                #             would materialize a partial object
            shard = data_chunks[:, pos, :] if pos < self.k else \
                parity[:, pos - self.k, :]
            shard_bytes = shard.tobytes()
            attrs = dict(attrs_delta)
            # position stamp: these bytes encode THIS acting position
            # — readers/rebuilders trust the stamp over the holder's
            # (shuffle-prone) slot
            attrs["_pos"] = self._pos_attr(pos)
            # per-shard write-time checksum (ref: ECBackend hinfo):
            # valid only when this write covers the WHOLE object (a
            # partial overwrite can't know the full-shard crc without
            # reading the rest, so it invalidates it — exactly the
            # reference's append-only hinfo discipline). Scrub repair
            # uses it to LOCATE a corrupt shard, which the code alone
            # cannot do at m=1. The value comes from the fused
            # checksum+encode pass when it ran (hcrc_attr combines the
            # device row CRCs; zlib fallback otherwise — pinned equal).
            attrs["_hcrc"] = ec_crc.hcrc_attr(
                shard_bytes,
                row_crcs=row_crcs[:, pos]
                if row_crcs is not None else None,
                chunk_size=C) if whole else b""
            per_osd[osd_id] = MOSDECSubOpWrite(
                tid=tid, epoch=self.epoch, pgid=self.cid, oid=oid,
                first_stripe=first, data=shard_bytes,
                truncate_stripes=trunc_stripes, size=size,
                remove=False, attrs=attrs, omap=omap_delta,
                omap_rm=list(omap_rm), log_entry=entry_blob)
        committed = await self._fan_out_subops(tid, per_osd)
        if committed < self.k:
            # fewer than k durable shards: the object would be
            # unreadable — fail the op loudly (ref: EC writes require
            # a decodable shard set)
            log.error(f"pg {self.pgid} ec write {oid}: only "
                      f"{committed} shards committed (< k={self.k})")
            return -5                                 # -EIO
        return 0

    async def _fan_out_delete(self, oid: str, entry: LogEntry) -> int:
        tid = self.osd.next_tid()
        per_osd = {}
        for osd_id in set(o for o in self.acting if o >= 0):
            if self.osd.osd_is_up(osd_id) and \
                    self._should_send_repop(osd_id, oid):
                per_osd[osd_id] = MOSDECSubOpWrite(
                    tid=tid, epoch=self.epoch, pgid=self.cid, oid=oid,
                    first_stripe=0, data=b"", truncate_stripes=0,
                    size=0, remove=True, attrs={}, omap={},
                    omap_rm=[], log_entry=entry.encode())
        await self._fan_out_subops(tid, per_osd)
        return 0

    async def _fan_out_subops(self, tid: int,
                              per_osd: dict[int, "MOSDECSubOpWrite"]
                              ) -> int:
        """Apply locally + send to peers + await acks. Returns how many
        shards actually committed (local apply counts as one)."""
        committed = 0
        pending: set[int] = set()
        waiter = asyncio.get_event_loop().create_future()
        remote = []
        # EC fan-out trace phase (ref: the repop_wait analog for
        # MOSDECSubOpWrite): sub-writes carry this span's context so
        # each shard's apply becomes its child
        op_span = getattr(self, "_active_span", None)
        sub_span = op_span.child(
            "ec_subop_wait",
            tags={"shards": sorted(per_osd)}) if op_span else None
        for osd_id, msg in per_osd.items():
            if osd_id == self.osd.whoami:
                store_span = op_span.child(
                    "objectstore_commit",
                    tags={"osd": self.osd.whoami}) if op_span else None
                if self._apply_sub_write(msg, local=True) == 0:
                    committed += 1
                if store_span is not None:
                    store_span.finish()
            else:
                pending.add(osd_id)
                msg.set_trace(sub_span)
                remote.append((osd_id, msg))
        failed: set[int] = set()
        self._subop_waiters[tid] = (pending, waiter, failed)
        sent = set()
        for osd_id, msg in remote:
            try:
                await self.osd.send_osd(osd_id, msg)
                sent.add(osd_id)
            except Exception:
                pending.discard(osd_id)
        if pending:
            try:
                await asyncio.wait_for(waiter, timeout=5.0)
            except asyncio.TimeoutError:
                log.dout(1, f"pg {self.pgid} ec sub-op {tid} timed out")
        if sub_span is not None:
            sub_span.finish()
        remaining, _, failed = self._subop_waiters.pop(
            tid, (set(), None, set()))
        # A shard that replied with a non-zero result did NOT durably
        # apply — it must not count toward the >=k durability check, or
        # the client could be acked with fewer than k live shards.
        committed += len((sent - remaining) - failed)
        return committed

    def _meta_txn_store(self) -> None:
        self.osd.store.queue_transaction(self._meta_txn(Transaction()))

    # -- sub-op handling (shard side) --------------------------------------
    def _apply_sub_write(self, m: MOSDECSubOpWrite,
                         local: bool = False) -> int:
        # hot-shard residency: this object's cached generations are
        # already unreachable (version-keyed), reclaim their bytes now
        cache = getattr(self.osd, "ec_resident", None)
        if cache is not None:
            cache.invalidate(str(self.cid), m.oid)
        t = Transaction()
        C = self.sinfo.chunk_size
        if m.remove:
            t.remove(self.cid, m.oid)
        else:
            t.touch(self.cid, m.oid)
            if m.data:
                t.write(self.cid, m.oid, m.first_stripe * C, m.data)
            t.truncate(self.cid, m.oid, m.truncate_stripes * C)
            if m.attrs:
                t.setattrs(self.cid, m.oid, m.attrs)
            if m.omap:
                t.omap_setkeys(self.cid, m.oid, m.omap)
            if m.omap_rm:
                t.omap_rmkeys(self.cid, m.oid, list(m.omap_rm))
        if not local:
            entry = LogEntry.decode(m.log_entry)
            self.pg_log.append(entry)
            self.pg_log.trim(keep=self._trim_keep())
            self.last_user_version = max(self.last_user_version,
                                         entry.version.v)
        self._meta_txn(t)
        try:
            self.osd.store.queue_transaction(t)
        except StoreError as e:
            log.error(f"pg {self.pgid} ec sub-write failed: {e}")
            return -5                                   # -EIO
        return 0

    def handle_ec_sub_write(self, m: MOSDECSubOpWrite) -> None:
        span = self.osd.tracer.from_msg(
            "ec_sub_write", m, tags={"osd": self.osd.whoami,
                                     "oid": m.oid})
        store_span = span.child(
            "objectstore_commit",
            tags={"osd": self.osd.whoami}) if span else None
        result = self._apply_sub_write(m)
        if store_span is not None:
            store_span.finish()
        if span is not None:
            if result != 0:
                span.tag("result", result)
            span.finish()

        async def _ack():
            try:
                await m.conn.send_message(MOSDECSubOpWriteReply(
                    tid=m.tid, result=result, pgid=self.cid,
                    from_osd=self.osd.whoami))
            except Exception:
                pass
        asyncio.ensure_future(_ack())

    def handle_ec_sub_write_reply(self, m: MOSDECSubOpWriteReply) -> None:
        ent = self._subop_waiters.get(m.tid)
        if ent is None:
            return
        pending, fut, failed = ent
        if m.result != 0:
            failed.add(m.from_osd)
        pending.discard(m.from_osd)
        if not pending and not fut.done():
            fut.set_result(True)

    def handle_ec_sub_read(self, m: MOSDECSubOpRead) -> None:
        exists, data, ver, size = self._local_shard_state(m.oid)
        piece = data[m.chunk_off:m.chunk_off + m.chunk_len] if exists \
            else b""
        pos = self._stored_pos(m.oid) if exists else -1

        async def _reply():
            try:
                await m.conn.send_message(MOSDECSubOpReadReply(
                    tid=m.tid, pgid=self.cid, oid=m.oid, exists=exists,
                    data=piece, version_epoch=ver.epoch,
                    version_v=ver.v, size=size,
                    from_osd=self.osd.whoami, shard_pos=pos))
            except Exception:
                pass
        asyncio.ensure_future(_reply())

    def handle_ec_sub_read_reply(self, m: MOSDECSubOpReadReply) -> None:
        fut = self._subread_waiters.get(m.tid)
        if fut and not fut.done():
            fut.set_result(m)

    # -- recovery -----------------------------------------------------------
    async def _pull(self, from_osd: int, oid: str) -> None:
        """EC primary reconstructs its OWN shard from live peers
        instead of pulling a byte-identical copy."""
        entry = self.my_missing.get(oid)
        try:
            await self._reconstruct_local(
                oid, want=None if entry is None else entry.version)
            self.my_missing.pop(oid, None)
        except (StoreError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            log.dout(1, f"pg {self.pgid} ec self-recover {oid}: {e}")

    async def _reconstruct_local(self, oid: str,
                                 want: eversion | None = None) -> None:
        ver, size = await self._authoritative_meta(oid, want=want)
        if size is None:
            # deleted everywhere / never existed — or the only copy at
            # a usable version is gone (a reverted divergent create):
            # drop local
            t = Transaction().remove(self.cid, oid)
            self.osd.store.queue_transaction(t)
            return
        await self._rebuild_shard(
            oid, self.my_shard(), ver, size, apply_local=True,
            exclude_osds=frozenset({self.osd.whoami}))

    async def _authoritative_meta(self, oid: str,
                                  want: eversion | None = None):
        """(version, size) of the newest live shard copy. With
        ``want`` set (a divergent-entry revert: the peering election
        queued a pull back to the authoritative log's version), copies
        NEWER than it are ignored — the local shard may carry an
        uncommitted divergent write whose version outranks every
        surviving peer's, and trusting it would faithfully restore the
        very write peering just rolled back."""
        best = (eversion(), None)
        for osd_id in set(o for o in self.acting if o >= 0):
            if not self.osd.osd_is_up(osd_id):
                continue
            if osd_id == self.osd.whoami:
                exists, _, ver, size = self._local_shard_state(oid)
            else:
                reply = await self._subread(osd_id, oid, 0, 0)
                if reply is None:
                    continue
                exists = reply.exists
                ver = eversion(reply.version_epoch, reply.version_v)
                size = reply.size
            if want is not None and ver > want:
                continue
            if exists and (best[1] is None or ver > best[0]):
                best = (ver, size)
        return best

    async def _agg_encode(self, data_chunks, with_crc: bool = False):
        """Every ECPG encode routes through the OSD's cross-op
        aggregator (osd/ec_aggregator.py); the per-op launch survives
        behind ``osd_ec_agg=off`` inside it. Bare harnesses without a
        daemon aggregator take a direct (still fused) call. Returns
        ``(parity np(B, m, C), row_crcs np(B, k+m) | None)``."""
        agg = getattr(self.osd, "ec_agg", None)
        if agg is not None:
            return await agg.encode(self.ec, data_chunks,
                                    with_crc=with_crc)
        if with_crc:
            parity, crcs = self.ec.encode_batch_with_crc(data_chunks)
            return np.asarray(parity), \
                (None if crcs is None else np.asarray(crcs))
        return np.asarray(self.ec.encode_batch(data_chunks)), None

    async def _agg_decode(self, want, avail, chunks,
                          repair: bool = False):
        """Every ECPG decode routes through the OSD's cross-op read
        aggregator (osd/ec_read_aggregator.py); the per-op launch
        survives behind ``osd_ec_read_agg=off`` inside it. Bare
        harnesses without a daemon aggregator take a direct call.
        ``repair`` decodes charge a recovery-class size-scaled QoS
        grant inside the aggregator — client degraded reads pass
        False (their cost tag was paid at admission). Returns
        np (B, len(want), C)."""
        agg = getattr(self.osd, "ec_read_agg", None)
        if agg is not None:
            return await agg.decode(
                self.ec, want, avail, chunks,
                charge_bytes=int(chunks.nbytes) if repair else 0)
        return np.asarray(self.ec.decode_batch(want, avail, chunks))

    async def _rebuild_shard(self, oid: str, shard: int, ver: eversion,
                             size: int, apply_local: bool = False,
                             exclude_osds: frozenset = frozenset()
                             ) -> tuple[bytes, bytes]:
        """Regenerate position ``shard``'s bytes from k live shards.
        Returns ``(shard_bytes, hcrc)`` — the write-time checksum
        comes from the fused checksum+encode pass when an encode ran
        (parity shards), and the hcrc_attr zlib fallback otherwise."""
        count = self.sinfo.object_stripes(size) or 1
        # never source the holder being rebuilt: its stored bytes are
        # missing, stale, or corrupt — rebuilding FROM them would
        # faithfully reproduce the damage. (Exclusion is by OSD, not
        # position: after an interval shuffle another holder may
        # legitimately carry this position's bytes.)
        data_chunks = await self._gather(oid, 0, count, ver,
                                         exclude_osds=exclude_osds,
                                         repair=True)
        if shard < self.k:
            shard_bytes = data_chunks[:, shard, :].tobytes()
            hcrc = ec_crc.hcrc_attr(shard_bytes)
        else:
            parity, row_crcs = await self._agg_encode(data_chunks,
                                                      with_crc=True)
            shard_bytes = parity[:, shard - self.k, :].tobytes()
            hcrc = ec_crc.hcrc_attr(
                shard_bytes,
                row_crcs=row_crcs[:, shard]
                if row_crcs is not None else None,
                chunk_size=self.sinfo.chunk_size)
        if apply_local:
            t = Transaction()
            t.remove(self.cid, oid)
            t.write(self.cid, oid, 0, shard_bytes)
            attrs = {"_v": _vblob(ver),
                     "_size": size.to_bytes(8, "little"),
                     "_pos": self._pos_attr(shard),
                     "_hcrc": hcrc}
            t.setattrs(self.cid, oid, attrs)
            self.osd.store.queue_transaction(t)
        return shard_bytes, hcrc

    def make_push(self, oid: str, target: int | None = None):
        raise NotImplementedError("EC pushes are built asynchronously")

    async def _build_backfill_push(self, oid: str, target: int):
        """EC recovery/backfill push: the target POSITION's shard,
        regenerated from any k live fresh shards (ref: ECBackend
        handle_recovery_read_complete). exists=False when the object
        is gone everywhere (the target reaps its stale shard)."""
        from ceph_tpu.osd.messages import MOSDPGPush
        try:
            pos = self.acting.index(target)
        except ValueError:
            return None
        try:
            ver, size = await self._authoritative_meta(oid)
            if size is None:
                return MOSDPGPush(
                    pgid=self.cid, epoch=self.epoch, oid=oid,
                    version_epoch=0, version_v=0, exists=False,
                    data=b"", attrs={}, omap={},
                    from_osd=self.osd.whoami)
            shard_bytes, hcrc = await self._rebuild_shard(
                oid, pos, ver, size,
                exclude_osds=frozenset({target}))
            omap = {}
            try:
                omap = dict(self.osd.store.omap_get(self.cid, oid))
            except StoreError:
                pass
            return MOSDPGPush(
                pgid=self.cid, epoch=self.epoch, oid=oid,
                version_epoch=ver.epoch, version_v=ver.v,
                exists=True, data=shard_bytes,
                attrs={"_v": _vblob(ver),
                       "_size": size.to_bytes(8, "little"),
                       "_pos": self._pos_attr(pos),
                       "_hcrc": hcrc},
                omap=omap, from_osd=self.osd.whoami)
        except Exception as e:
            log.dout(1, f"pg {self.pgid} ec push {oid}->osd.{target} "
                        f"build failed: {e}")
            return None

    async def _recover(self) -> None:
        """Regenerate each missing peer shard from k live shards
        (ref: ECBackend recovery reads + pushes)."""
        if not self.is_primary():
            return
        if any(self.peer_missing.values()):
            self.state = "recovering"
        sends: list = []
        for o, missing in list(self.peer_missing.items()):
            if not self.osd.osd_is_up(o):
                continue
            if o not in self.acting:
                missing.clear()
                continue
            for oid in list(missing):
                push = await self._build_backfill_push(oid, o)
                if push is not None:
                    sends.append((o, oid, push))
        # a shard only counts as recovered once ACKED — the gate is
        # shared with the replicated path (PG._send_gated_pushes)
        if await self._send_gated_pushes(sends):
            return
        if not any(self.peer_missing.values()) and \
                self.state in ("active", "recovering"):
            if self._maybe_start_backfill():
                return          # clean is decided when backfill ends
            if len(self.live_acting()) >= self.pool.size:
                self._mark_clean()
            else:
                self.state = "active"

    # -- stats --------------------------------------------------------------
    def stats(self) -> dict:
        base = super().stats()
        # logical bytes: shard bytes are size/k each
        try:
            objs = [o for o in self.osd.store.list_objects(self.cid)
                    if o != PGMETA]
            base["num_bytes"] = sum(
                self._obj_size(o) for o in objs
                if self.osd.store.exists(self.cid, o))
        except StoreError:
            pass
        return base
