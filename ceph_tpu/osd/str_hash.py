"""Object-name hashes — the object->PG step's randomness source.

ref: src/common/ceph_hash.cc (ceph_str_hash_rjenkins, ceph_str_hash_linux)
and src/include/ceph_fs.h (CEPH_STR_HASH_* ids). rjenkins here is the
*byte-stream* variant (lookup2 style, golden-ratio init) — distinct from
the fixed-arity crush_hash32_* mixes in ceph_tpu.crush.hash, though both
share the same 96-bit mix rounds.

Two shapes:
- ``str_hash``: one bytestring -> uint32 (client-side single-op path);
- ``str_hash_batch``: (N, L) padded uint8 matrix + lengths -> (N,) uint32,
  vectorized for batched op mapping (runs under numpy or jax.numpy).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.crush.hash import _mix, _quiet

CEPH_STR_HASH_LINUX = 0x1
CEPH_STR_HASH_RJENKINS = 0x2

_GOLDEN = 0x9E3779B9


def _word(k, o, xp):
    """Little-endian uint32 from 4 consecutive byte lanes at offset o."""
    u = k[..., o].astype(xp.uint32)
    u = u | (k[..., o + 1].astype(xp.uint32) << xp.uint32(8))
    u = u | (k[..., o + 2].astype(xp.uint32) << xp.uint32(16))
    u = u | (k[..., o + 3].astype(xp.uint32) << xp.uint32(24))
    return u


def str_hash_rjenkins(data: bytes) -> int:
    """ref: ceph_hash.cc ceph_str_hash_rjenkins (12-byte blocks + tail)."""
    out = str_hash_batch_rjenkins(
        np.frombuffer(data, dtype=np.uint8)[None, :],
        np.array([len(data)]), xp=np)
    return int(out[0])


def str_hash_batch_rjenkins(padded, lengths, xp=np):
    """(N, L) uint8 zero-padded names + (N,) lengths -> (N,) uint32.

    Vectorized port of the scalar block loop: lanes shorter than the
    current block are masked out; the tail "switch fallthrough" becomes
    per-byte masks on the tail length.
    """
    with _quiet(xp):
        padded = xp.asarray(padded, dtype=xp.uint8)
        lengths = xp.asarray(lengths, dtype=xp.uint32)
        n, cap = padded.shape
        # Room for the widest full-block read the longest lane performs
        # (and at least one block so tail gathers have somewhere to clip).
        target = max(12, -(-cap // 12) * 12)
        if cap < target:
            pad = xp.zeros((n, target - cap), dtype=xp.uint8)
            padded = xp.concatenate([padded, pad], axis=1)
        a = xp.full((n,), _GOLDEN, dtype=xp.uint32)
        b = xp.full((n,), _GOLDEN, dtype=xp.uint32)
        c = xp.zeros((n,), dtype=xp.uint32)
        nblocks = int(cap) // 12
        remaining = lengths
        for blk in range(nblocks):
            active = remaining >= 12
            o = blk * 12
            a2 = a + _word(padded, o, xp)
            b2 = b + _word(padded, o + 4, xp)
            c2 = c + _word(padded, o + 8, xp)
            a2, b2, c2 = _mix(a2, b2, c2, xp)
            a = xp.where(active, a2, a)
            b = xp.where(active, b2, b)
            c = xp.where(active, c2, c)
            remaining = xp.where(active, remaining - 12, remaining)
        # Tail: base offset of the final partial block per lane.
        base = (lengths - remaining).astype(xp.int64)
        tail = remaining.astype(xp.int64)  # 0..11
        c = c + lengths
        idx = xp.arange(padded.shape[1], dtype=xp.int64)

        def byte_at(off):
            pos = xp.clip(base + off, 0, padded.shape[1] - 1)
            return xp.take_along_axis(padded, pos[:, None],
                                      axis=1)[:, 0].astype(xp.uint32)

        del idx
        # switch(len) fallthrough: byte j contributes iff tail > j.
        shifts_c = {10: 24, 9: 16, 8: 8}
        shifts_b = {7: 24, 6: 16, 5: 8, 4: 0}
        shifts_a = {3: 24, 2: 16, 1: 8, 0: 0}
        for j, sh in shifts_c.items():
            c = xp.where(tail > j, c + (byte_at(j) << xp.uint32(sh)), c)
        for j, sh in shifts_b.items():
            b = xp.where(tail > j, b + (byte_at(j) << xp.uint32(sh)), b)
        for j, sh in shifts_a.items():
            a = xp.where(tail > j, a + (byte_at(j) << xp.uint32(sh)), a)
        a, b, c = _mix(a, b, c, xp)
        return c


def str_hash_linux(data: bytes) -> int:
    """ref: ceph_hash.cc ceph_str_hash_linux (dcache-style)."""
    h = 0
    for ch in data:
        h = (h + (ch << 4) + (ch >> 4)) * 11
        h &= 0xFFFFFFFF
    return h


def str_hash_batch_linux(padded, lengths, xp=np):
    with _quiet(xp):
        padded = xp.asarray(padded, dtype=xp.uint8)
        lengths = xp.asarray(lengths, dtype=xp.uint32)
        h = xp.zeros(padded.shape[0], dtype=xp.uint32)
        for j in range(padded.shape[1]):
            ch = padded[:, j].astype(xp.uint32)
            h2 = (h + (ch << xp.uint32(4)) + (ch >> xp.uint32(4))) \
                * xp.uint32(11)
            h = xp.where(lengths > j, h2, h)
        return h


def str_hash(algo: int, data: bytes) -> int:
    """ref: ceph_hash.cc ceph_str_hash dispatch."""
    if algo == CEPH_STR_HASH_LINUX:
        return str_hash_linux(data)
    if algo == CEPH_STR_HASH_RJENKINS:
        return str_hash_rjenkins(data)
    raise ValueError(f"unknown str hash algo {algo}")


def str_hash_batch(algo: int, padded, lengths, xp=np):
    if algo == CEPH_STR_HASH_LINUX:
        return str_hash_batch_linux(padded, lengths, xp=xp)
    if algo == CEPH_STR_HASH_RJENKINS:
        return str_hash_batch_rjenkins(padded, lengths, xp=xp)
    raise ValueError(f"unknown str hash algo {algo}")


def pack_names(names: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """Pad a list of names into the (N, L) matrix str_hash_batch wants."""
    cap = max((len(s) for s in names), default=1) or 1
    out = np.zeros((len(names), cap), dtype=np.uint8)
    lens = np.zeros(len(names), dtype=np.uint32)
    for i, s in enumerate(names):
        out[i, :len(s)] = np.frombuffer(s, dtype=np.uint8)
        lens[i] = len(s)
    return out, lens
