"""ECBackend-lite: striped EC object I/O with RMW, recovery and scrub.

The object-path logic of the reference's ECBackend/ECCommon, rebuilt
TPU-first (ref: src/osd/ECBackend.cc ECBackend;
src/osd/ECCommon.h ReadPipeline / RMWPipeline;
src/osd/ECTransaction.cc generate_transactions):

- objects are striped per StripeInfo (ECUtil::stripe_info_t);
- a partial write is widened to whole stripes: old stripes are read,
  new bytes merged, and the WHOLE touched range re-encoded in one
  batched device call (the reference's read-modify-write pipeline,
  sub-op'd per shard; here shard writes are array slices);
- recovery reconstructs lost shards via minimum_to_decode +
  decode_chunks, batched over every stripe of an object in one device
  program (ref: ECBackend::handle_recovery_read_complete);
- scrub re-encodes data shards and byte-compares stored parity
  (the deep-scrub shard-consistency check,
  ref: src/osd/scrubber and ECBackend::scrub_supported).

Shard storage here is an in-memory dict per shard id — the ObjectStore
seam; the cluster layer (osd daemon-lite) plugs a real store in.
"""

from __future__ import annotations

from typing import Iterable, Mapping

import numpy as np

from ceph_tpu.ec.interface import ErasureCodeInterface
from ceph_tpu.osd.ecutil import StripeInfo
from ceph_tpu.utils.logging import get_logger
from ceph_tpu.utils.perf_counters import PerfCountersBuilder

log = get_logger("osd")


class ShardMissing(Exception):
    pass


class ECBackendLite:
    """Striped EC object store over one PG's shard set."""

    def __init__(self, ec: ErasureCodeInterface, chunk_size: int = 4096,
                 name: str = "ec_backend", config: dict | None = None):
        self.ec = ec
        self.k = ec.get_data_chunk_count()
        self.m = ec.get_coding_chunk_count()
        self.n = ec.get_chunk_count()
        self.sinfo = StripeInfo(self.k, chunk_size)
        # shard id -> oid -> (n_stripes, chunk_size) uint8
        self.shards: dict[int, dict[str, np.ndarray]] = {
            s: {} for s in range(self.n)}
        self.sizes: dict[str, int] = {}     # logical object sizes
        # hot-shard residency (round 19): gathered stripe ranges pin
        # device-side under osd_ec_resident_bytes, keyed by a per-oid
        # generation the mutators bump — RMW and repeated reads skip
        # the re-gather + H2D leg
        self.resident = None
        self._gen: dict[str, int] = {}
        if config is not None and \
                int(config.get("osd_ec_resident_bytes", 0)) > 0:
            from ceph_tpu.ec.jax_plugin import DeviceShardCache
            self.resident = DeviceShardCache(config)
        self.perf = (PerfCountersBuilder(name)
                     .add_u64_counter("write_bytes", "logical bytes written")
                     .add_u64_counter("rmw_stripes", "stripes read-modified")
                     .add_u64_counter("encode_stripes", "stripes encoded")
                     .add_u64_counter("recover_chunks",
                                      "chunks reconstructed")
                     .add_u64_counter("scrub_errors", "scrub mismatches")
                     .create_perf_counters())

    # -- internals ---------------------------------------------------------
    def _shard_array(self, shard: int, oid: str, n_stripes: int) -> np.ndarray:
        cur = self.shards[shard].get(oid)
        if cur is None:
            cur = np.zeros((0, self.sinfo.chunk_size), dtype=np.uint8)
        if cur.shape[0] < n_stripes:
            pad = np.zeros((n_stripes - cur.shape[0], self.sinfo.chunk_size),
                           dtype=np.uint8)
            cur = np.concatenate([cur, pad])
            self.shards[shard][oid] = cur
        return cur

    def _read_stripes(self, oid: str, first: int, count: int) -> np.ndarray:
        """(count, k, chunk) data-shard contents (zero-filled past EOF).
        Raises ShardMissing if a needed data shard is gone (caller must
        recover first — the reference's ReadPipeline would issue recovery
        reads instead)."""
        out = np.zeros((count, self.k, self.sinfo.chunk_size),
                       dtype=np.uint8)
        for c in range(self.k):
            store = self.shards[c].get(oid)
            if store is None:
                if self.sizes.get(oid, 0) > 0 and oid in self._any_shard():
                    raise ShardMissing(f"{oid} data shard {c} missing")
                continue
            hi = min(store.shape[0], first + count)
            if hi > first:
                out[:hi - first, c] = store[first:hi]
        return out

    def _resident_read(self, oid: str, first: int,
                       count: int) -> np.ndarray:
        """_read_stripes through the residency cache: a hit returns
        the device-pinned batch (no shard walk); a miss gathers and
        pins. Generation-keyed, so every mutator's bump makes stale
        entries unreachable."""
        if self.resident is None:
            return self._read_stripes(oid, first, count)
        key = (oid, int(first), int(count), self._gen.get(oid, 0))
        hit = self.resident.get(key)
        if hit is not None:
            return np.asarray(hit)
        out = self._read_stripes(oid, first, count)
        self.resident.put(key, out)
        return out

    def _bump_gen(self, oid: str) -> None:
        self._gen[oid] = self._gen.get(oid, 0) + 1
        if self.resident is not None:
            self.resident.invalidate(oid)

    def _any_shard(self) -> set[str]:
        names: set[str] = set()
        for s in range(self.n):
            names.update(self.shards[s])
        return names

    # -- client ops --------------------------------------------------------
    def write(self, oid: str, offset: int, data: bytes) -> None:
        """Partial-write RMW: widen to stripes, read-merge-reencode-write.

        ref: ECCommon::RMWPipeline — reads the touched stripes' old
        contents, merges the new bytes, re-encodes, and writes every
        shard of the touched stripe range.
        """
        if not data:
            return
        first, count = self.sinfo.stripe_range(offset, len(data))
        W = self.sinfo.stripe_width
        stripes = self._resident_read(oid, first, count)     # old contents
        partial_head = offset % W != 0
        partial_tail = (offset + len(data)) % W != 0
        if partial_head or partial_tail:
            self.perf.inc("rmw_stripes", count)
        # merge new bytes into the logical view (own copy: a resident
        # hit's array is immutable by the cache contract)
        flat = np.array(stripes, dtype=np.uint8).reshape(
            count, self.k * self.sinfo.chunk_size)
        lo = offset - first * W
        flat.reshape(-1)[lo:lo + len(data)] = np.frombuffer(data, np.uint8)
        merged = flat.reshape(count, self.k, self.sinfo.chunk_size)
        parity = np.asarray(self.ec.encode_batch(merged))
        self.perf.inc("encode_stripes", count)
        self.perf.inc("write_bytes", len(data))
        n_stripes_total = max(self.sinfo.object_stripes(
            self.sizes.get(oid, 0)), first + count)
        for c in range(self.k):
            arr = self._shard_array(c, oid, n_stripes_total)
            arr[first:first + count] = merged[:, c]
        for p in range(self.m):
            arr = self._shard_array(self.k + p, oid, n_stripes_total)
            arr[first:first + count] = parity[:, p]
        self.sizes[oid] = max(self.sizes.get(oid, 0), offset + len(data))
        self._bump_gen(oid)

    def read(self, oid: str, offset: int, length: int) -> bytes:
        """ref: ECBackend::objects_read_sync (aligned read + trim)."""
        size = self.sizes.get(oid, 0)
        length = max(0, min(length, size - offset))
        if length <= 0:
            return b""
        first, count = self.sinfo.stripe_range(offset, length)
        stripes = self._resident_read(oid, first, count)
        flat = stripes.reshape(-1)
        lo = offset - first * self.sinfo.stripe_width
        return flat[lo:lo + length].tobytes()

    # -- failure / recovery ------------------------------------------------
    def lose_shard(self, shard: int, oid: str | None = None) -> None:
        """Failure injection: drop one object's shard (or the whole
        shard's contents)."""
        if oid is None:
            for o in list(self.shards[shard]):
                self._bump_gen(o)
            self.shards[shard].clear()
        else:
            self.shards[shard].pop(oid, None)
            self._bump_gen(oid)

    def missing_shards(self, oid: str) -> set[int]:
        return {s for s in range(self.n) if oid not in self.shards[s]}

    def recovery_plan(self, oid: str) -> tuple[set[int], set[int]]:
        """(lost, to_read): the minimal chunk set that reconstructs the
        lost shards, via the plugin's minimum_to_decode — LRC/SHEC/CLAY
        plugins return cheaper local sets than 'any k'.
        ref: ECBackend::get_min_avail_to_read_shards."""
        lost = self.missing_shards(oid)
        avail = set(range(self.n)) - lost
        to_read = set(self.ec.minimum_to_decode(lost, avail))
        return lost, to_read

    def recover(self, oid: str) -> set[int]:
        """Reconstruct every missing shard of oid in ONE batched decode
        over all its stripes (ref: ECBackend recovery:
        ReadPipeline reads minimum_to_decode chunks, decode_chunks
        rebuilds, pushed to the new shard)."""
        lost, to_read = self.recovery_plan(oid)
        if not lost:
            return set()
        n_stripes = self.sinfo.object_stripes(self.sizes.get(oid, 0))
        reads = sorted(to_read)
        chunks = np.stack([self._shard_array(s, oid, n_stripes)
                           for s in reads], axis=1)  # (S, len(reads), C)
        want = sorted(lost)
        out = np.asarray(self.ec.decode_batch(want, reads, chunks))
        for i, s in enumerate(want):
            self.shards[s][oid] = out[:, i].copy()
        self._bump_gen(oid)
        self.perf.inc("recover_chunks", len(want) * n_stripes)
        log.dout(5, "recovered", oid=oid, lost=want, read=reads)
        return lost

    def recover_all(self) -> dict[str, set[int]]:
        """PG-wide recovery: every object with missing shards."""
        out = {}
        for oid in sorted(self._any_shard()):
            lost = self.recover(oid)
            if lost:
                out[oid] = lost
        return out

    # -- scrub -------------------------------------------------------------
    def _consistent_excluding(self, oid: str, n_stripes: int,
                              excluded: set[int]) -> bool:
        """True when the stored shards minus `excluded` form one
        consistent codeword: decode the data from k of the remainder,
        re-encode, and byte-compare every remaining stored shard."""
        remaining = [s for s in range(self.n)
                     if s not in excluded and oid in self.shards[s]]
        if len(remaining) < self.k:
            return False
        reads = sorted(self.ec.minimum_to_decode(set(range(self.k)),
                                                 set(remaining)))
        chunks = np.stack([self._shard_array(s, oid, n_stripes)
                           for s in reads], axis=1)
        data = np.asarray(self.ec.decode_batch(list(range(self.k)),
                                               reads, chunks))
        parity = np.asarray(self.ec.encode_batch(data))
        word = np.concatenate([data, parity], axis=1)   # (S, n, C)
        for s in remaining:
            if not np.array_equal(self._shard_array(s, oid, n_stripes),
                                  word[:, s]):
                return False
        return True

    def scrub(self, oid: str) -> list[int]:
        """Deep-scrub shard consistency (ref: ECBackend be_deep_scrub /
        scrub digest comparison on the primary).

        Returns [] when every stored shard belongs to one codeword;
        otherwise localizes a single corrupted shard by exclusion (the
        unique shard whose removal restores consistency), or returns all
        shard ids when corruption exceeds single-shard localization."""
        n_stripes = self.sinfo.object_stripes(self.sizes.get(oid, 0))
        if not n_stripes:
            return []
        missing = self.missing_shards(oid)
        if missing:
            return sorted(missing)
        if self._consistent_excluding(oid, n_stripes, set()):
            return []
        candidates = [s for s in range(self.n)
                      if self._consistent_excluding(oid, n_stripes, {s})]
        bad = candidates if len(candidates) == 1 else list(range(self.n))
        self.perf.inc("scrub_errors", len(bad))
        return bad
