"""OSD wire messages: client ops, replication sub-ops, peering,
recovery, heartbeats.

ref: src/messages/MOSDOp.h, MOSDOpReply.h, MOSDRepOp.h,
MOSDRepOpReply.h, MOSDPing.h, MOSDPGQuery/Info/Log/Push (peering +
recovery), narrowed to the op surface this framework's PG implements.
"""

from __future__ import annotations

from ceph_tpu.msg.message import Message, register

# client op codes (ref: include/rados.h CEPH_OSD_OP_*)
OSD_OP_READ = 1
OSD_OP_WRITE = 2
OSD_OP_WRITEFULL = 3
OSD_OP_DELETE = 4
OSD_OP_STAT = 5
OSD_OP_TRUNCATE = 6
OSD_OP_ZERO = 7
OSD_OP_GETXATTR = 8
OSD_OP_SETXATTR = 9
OSD_OP_OMAP_GET = 10
OSD_OP_OMAP_SET = 11
OSD_OP_PGLS = 12           # list objects in pg (rados ls building block)
OSD_OP_OMAP_RM = 13
OSD_OP_WATCH = 14          # register a watcher (cookie in `offset`)
OSD_OP_UNWATCH = 15
OSD_OP_NOTIFY = 16         # fan payload out to watchers, await acks
OSD_OP_NOTIFY_ACK = 17     # watcher -> primary (notify_id in `offset`)
OSD_OP_SNAPTRIM = 18       # drop a snap id from the object's clones

# heartbeat ops (ref: MOSDPing::PING / PING_REPLY)
PING = 1
PING_REPLY = 2

# op codes that mutate object state — the write class pausewr/FULL
# gating and the OSD failsafe apply to (ref: MOSDOp::may_write()).
MUTATING_OPS = frozenset((
    OSD_OP_WRITE, OSD_OP_WRITEFULL, OSD_OP_TRUNCATE, OSD_OP_ZERO,
    OSD_OP_DELETE, OSD_OP_SETXATTR, OSD_OP_OMAP_SET, OSD_OP_OMAP_RM,
    OSD_OP_SNAPTRIM,
))

# MOSDOp.flags bits (ref: include/rados.h CEPH_OSD_FLAG_FULL_TRY):
# FULL_TRY makes a write to a FULL cluster / full pool fail fast with
# -ENOSPC / -EDQUOT instead of parking on the objecter's wait queue.
OSD_FLAG_FULL_TRY = 1 << 20


@register
class MOSDOp(Message):
    """One client op bundle on one object (ref: MOSDOp).

    ops: list of encoded (op u8, offset u64, length u64, name str,
    data blob) tuples — flattened here as parallel lists for the
    declarative codec."""

    TYPE = 160
    FIELDS = [
        ("tid", "u64"), ("attempt", "u32"), ("epoch", "u32"),
        ("pool", "s64"), ("seed", "u32"), ("oid", "str"),
        ("op_codes", "list:u32"), ("op_offs", "list:u64"),
        ("op_lens", "list:u64"), ("op_names", "list:str"),
        # zero-copy decode: write payloads arrive as memoryviews over
        # the wire frame and ride into np.frombuffer / the EC encode
        # carve without a host staging copy (encode side unchanged)
        ("op_datas", "list:blob_view"),
        # self-managed snap context (ref: SnapContext in MOSDOp):
        # writes carry (snap_seq, snaps) for clone-on-write; reads
        # carry snap_id (0 = head)
        ("snap_seq", "u64"), ("snaps", "list:u64"), ("snap_id", "u64"),
        # op flags (ref: MOSDOp::flags — FULL_TRY et al)
        ("flags", "u32"),
    ]

    def unpack_ops(self):
        return list(zip(self.op_codes, self.op_offs, self.op_lens,
                        self.op_names, self.op_datas))


def make_osd_op(tid: int, epoch: int, pool: int, seed: int, oid: str,
                ops: list[tuple], attempt: int = 0,
                snapc: tuple | None = None, snap_id: int = 0,
                flags: int = 0) -> MOSDOp:
    """ops: (code, offset, length, name, data) tuples.

    ``attempt`` distinguishes objecter resends of one logical op (same
    tid): the OSD echoes it so a late reply from a timed-out earlier
    attempt cannot resolve a newer attempt's waiter with a stale read
    (ref: MOSDOp::get_retry_attempt). ``snapc`` = (seq, [snap ids])
    write snap context; ``snap_id`` = read-at-snap (0 = head)."""
    seq, snaps = snapc if snapc else (0, [])
    return MOSDOp(
        tid=tid, attempt=attempt, epoch=epoch, pool=pool, seed=seed,
        oid=oid,
        op_codes=[o[0] for o in ops], op_offs=[o[1] for o in ops],
        op_lens=[o[2] for o in ops], op_names=[o[3] for o in ops],
        op_datas=[o[4] for o in ops],
        snap_seq=seq, snaps=list(snaps), snap_id=snap_id, flags=flags)


@register
class MOSDOpReply(Message):
    TYPE = 161
    FIELDS = [("tid", "u64"), ("attempt", "u32"), ("result", "s32"),
              ("epoch", "u32"),
              ("data", "blob"), ("extra", "str")]   # extra: json


@register
class MOSDRepOp(Message):
    """Primary -> replica shard write (ref: MOSDRepOp): the encoded
    ObjectStore transaction plus the pg log entry it commits."""

    TYPE = 162
    FIELDS = [("tid", "u64"), ("epoch", "u32"), ("pgid", "str"),
              ("txn", "blob"), ("log_entry", "blob"),
              # snap-clone entries committed by the same txn (kept
              # separate from log_entry for compatibility with the
              # single-entry fast path)
              ("extra_log", "list:blob")]


@register
class MOSDRepOpReply(Message):
    TYPE = 163
    FIELDS = [("tid", "u64"), ("result", "s32"), ("pgid", "str"),
              ("from_osd", "s32")]


@register
class MWatchNotify(Message):
    """Primary -> watching client: a notify fired on a watched object
    (ref: src/messages/MWatchNotify.h). The client acks with an
    OSD_OP_NOTIFY_ACK op so the notifier can collect completions."""

    TYPE = 177
    FIELDS = [("oid", "str"), ("pgid", "str"), ("notify_id", "u64"),
              ("cookie", "u64"), ("payload", "blob")]


@register
class MOSDPing(Message):
    TYPE = 180
    FIELDS = [("op", "u8"), ("from_osd", "s32"), ("epoch", "u32"),
              ("stamp", "f64")]


# -- EC sub-ops ------------------------------------------------------------

@register
class MOSDECSubOpWrite(Message):
    """Primary -> shard: this shard's chunk bytes for a stripe range
    plus object metadata (ref: MOSDECSubOpWrite / ECSubWrite)."""

    TYPE = 164
    FIELDS = [("tid", "u64"), ("epoch", "u32"), ("pgid", "str"),
              ("oid", "str"), ("first_stripe", "u64"),
              ("data", "blob"),             # n_stripes*chunk_size bytes
              ("truncate_stripes", "u64"),  # shard truncated to this
              ("size", "u64"),              # logical object size
              ("remove", "bool"),
              ("attrs", "map:str:blob"), ("omap", "map:str:blob"),
              ("omap_rm", "list:str"),
              ("log_entry", "blob")]


@register
class MOSDECSubOpWriteReply(Message):
    TYPE = 165
    FIELDS = [("tid", "u64"), ("result", "s32"), ("pgid", "str"),
              ("from_osd", "s32")]


@register
class MOSDECSubOpRead(Message):
    """Primary -> shard: read chunk bytes (ref: MOSDECSubOpRead)."""

    TYPE = 166
    FIELDS = [("tid", "u64"), ("epoch", "u32"), ("pgid", "str"),
              ("oid", "str"), ("chunk_off", "u64"),
              ("chunk_len", "u64"), ("from_osd", "s32")]


@register
class MOSDECSubOpReadReply(Message):
    # ``shard_pos``: the acting position the stored shard's bytes
    # were encoded for (the write-time _pos stamp; -1 = unstamped).
    # Readers must file the chunk under THIS position, not the
    # holder's current slot — interval shuffles can move a holder.
    TYPE = 167
    FIELDS = [("tid", "u64"), ("pgid", "str"), ("oid", "str"),
              ("exists", "bool"), ("data", "blob"),
              ("version_epoch", "u32"), ("version_v", "u64"),
              ("size", "u64"), ("from_osd", "s32"),
              ("shard_pos", "s32")]


# -- peering ---------------------------------------------------------------

@register
class MOSDPGQuery(Message):
    """Primary asks a peer for its pg info+log (ref: MOSDPGQuery →
    peer replies MOSDPGInfo)."""

    TYPE = 170
    FIELDS = [("pgid", "str"), ("epoch", "u32"), ("from_osd", "s32")]


@register
class MOSDPGInfo(Message):
    """Peer's view: last_update + full log blob (ref: MOSDPGInfo/
    MOSDPGLog merged — logs here are small enough to ship whole).
    ``notify=1`` marks an UNSOLICITED stray announcement (ref:
    MOSDPGNotify): a map change moved the PG off this OSD, and the new
    primary — possibly a fresh instance with no history — must learn
    this stray exists before activating empty. ``intervals`` ships the
    sender's past_intervals (JSON) for the primary's coverage gate.
    ``last_backfill`` is the sender's persisted backfill watermark
    (ref: pg_info_t.last_backfill) — MAX_OID on every complete
    replica; anything lower marks the sender a mid-backfill target
    whose store only holds objects <= the watermark."""

    TYPE = 171
    FIELDS = [("pgid", "str"), ("epoch", "u32"), ("from_osd", "s32"),
              ("log", "blob"), ("notify", "u8"), ("intervals", "str"),
              ("last_backfill", "str"),
              # authoritative log head at the sender's last persisted
              # watermark advance: the resume-safety token (see
              # MOSDPGBackfill)
              ("backfill_at_epoch", "u32"), ("backfill_at_v", "u64"),
              # appended (zero-fill): the sender's persisted
              # last_epoch_started (ref: pg_info_t.last_epoch_started).
              # find_best_info orders candidates by (les, head) — a
              # revived pre-failover primary whose log carries a
              # divergent entry (logged but never committed on enough
              # shards) has a HIGHER head but a LOWER les than the
              # interval that peered without it, so it can never win
              # authority back and resurrect the uncommitted write.
              ("les", "u32"),
              # appended: primary -> acting replicas at activation —
              # adopt ``les`` so a future election hears the newer
              # interval from ANY survivor, not just the old primary
              ("activate", "u8")]


@register
class MOSDPGPull(Message):
    """Primary requests a whole-object push from a peer holding the
    authoritative copy (ref: MOSDPGPull PullOp)."""

    TYPE = 174
    FIELDS = [("pgid", "str"), ("epoch", "u32"), ("oid", "str"),
              ("from_osd", "s32")]


@register
class MOSDPGPush(Message):
    """Recovery push: whole-object state at a version
    (ref: MOSDPGPush PushOp)."""

    TYPE = 172
    FIELDS = [("pgid", "str"), ("epoch", "u32"), ("oid", "str"),
              ("version_epoch", "u32"), ("version_v", "u64"),
              ("exists", "bool"), ("data", "blob"),
              ("attrs", "map:str:blob"), ("omap", "map:str:blob"),
              ("from_osd", "s32")]


@register
class MOSDPGPushReply(Message):
    TYPE = 173
    FIELDS = [("pgid", "str"), ("oid", "str"), ("from_osd", "s32")]


@register
class MOSDRepScrub(Message):
    """Primary -> replica: send your scrub map for this PG
    (ref: MOSDRepScrub)."""

    TYPE = 175
    FIELDS = [("pgid", "str"), ("tid", "u64"), ("epoch", "u32"),
              ("from_osd", "s32")]


@register
class MOSDRepScrubMap(Message):
    """Replica's scrub map: oid -> json{size, digest, omap_digest,
    version} (ref: ScrubMap)."""

    TYPE = 176
    FIELDS = [("pgid", "str"), ("tid", "u64"), ("from_osd", "s32"),
              ("scrub_map", "map:str:blob")]


@register
class MPGCleanNotice(Message):
    """Primary -> every OSD that hosted the PG since its last clean:
    the PG is clean at ``epoch``, so past intervals up to it are
    subsumed — trim them (the stray/replica half of last_epoch_clean;
    ref: the purge_strays/pg-notify machinery's role). Best-effort: a
    missed notice leaves the conservative blocking behavior."""

    TYPE = 178
    FIELDS = [("pgid", "str"), ("epoch", "u32"), ("from_osd", "s32")]


@register
class MOSDMapPing(Message):
    """Client -> OSD: which osdmap epoch do you hold? The probe behind
    the Objecter's osdmap epoch barrier (ref: upstream eviction's
    wait-for-blocklist-epoch via Objecter::wait_for_map + the OSD's
    map gate): the caller needs proof a specific OSD has OBSERVED an
    epoch, not just that the mon committed it."""

    TYPE = 181
    FIELDS = [("tid", "u64"), ("epoch", "u32")]


@register
class MOSDMapPingReply(Message):
    """OSD -> client: the osdmap epoch this OSD currently serves."""

    TYPE = 182
    FIELDS = [("tid", "u64"), ("epoch", "u32"), ("from_osd", "s32")]


# -- backfill (ref: src/messages/MOSDPGScan.h + MOSDPGBackfill.h) ----------

@register
class MOSDPGScan(Message):
    """Backfill collection scan request (ref: MOSDPGScan GET_DIGEST):
    list your sorted object names in (begin, end] with their versions.
    ``end`` == MAX_OID means unbounded; ``limit`` > 0 pages the reply
    (the sender advances ``begin`` to the reply's ``up_to``)."""

    TYPE = 183
    FIELDS = [("pgid", "str"), ("epoch", "u32"), ("tid", "u64"),
              ("begin", "str"), ("end", "str"), ("limit", "u32"),
              ("from_osd", "s32")]


@register
class MOSDPGScanReply(Message):
    """Scan digest (ref: MOSDPGScan DIGEST / BackfillInterval):
    oid -> 12-byte version blob (epoch u32le + v u64le, the _v xattr
    layout). ``up_to`` is the exclusive-upper bound actually covered:
    every object the sender holds in (begin, up_to] is listed — MAX_OID
    when the collection is exhausted."""

    TYPE = 184
    FIELDS = [("pgid", "str"), ("tid", "u64"), ("from_osd", "s32"),
              ("objects", "map:str:blob"), ("up_to", "str")]


BACKFILL_OP_RESET = 1      # primary -> target: you are a backfill
#                            target; persist last_backfill = MIN
BACKFILL_OP_PROGRESS = 2   # primary -> target: watermark advanced
BACKFILL_OP_FINISH = 3     # primary -> target: complete; adopt the
#                            shipped log, persist last_backfill = MAX


@register
class MOSDPGBackfill(Message):
    """Backfill watermark control (ref: MOSDPGBackfill PROGRESS/
    FINISH): the target persists ``last_backfill`` so a restart
    resumes the scan instead of starting over. FINISH additionally
    carries the primary's pg log so the target's log is continuous
    with the authoritative history it now fully holds."""

    TYPE = 185
    FIELDS = [("pgid", "str"), ("epoch", "u32"), ("tid", "u64"),
              ("op", "u8"), ("last_backfill", "str"), ("log", "blob"),
              # the authoritative head this watermark is valid AT: on
              # rejoin, resuming from the watermark is only sound if
              # the authoritative log is still continuous with this
              # point (everything that changed below the watermark
              # since is then derivable from the retained log); else
              # the target must rescan from MIN
              ("at_epoch", "u32"), ("at_v", "u64"),
              ("from_osd", "s32")]


@register
class MOSDPGBackfillReply(Message):
    TYPE = 186
    FIELDS = [("pgid", "str"), ("tid", "u64"), ("op", "u8"),
              ("result", "s32"), ("from_osd", "s32")]


RESERVE_REQUEST = 1
RESERVE_GRANT = 2
RESERVE_REJECT = 3         # no free slot: retry later (backfill_wait)
RESERVE_TOOFULL = 4        # target past its full ratio (backfill_toofull)
RESERVE_RELEASE = 5


@register
class MBackfillReserve(Message):
    """Remote backfill reservation (ref: MBackfillReserve + the OSD's
    AsyncReserver): the primary holds a LOCAL slot and asks each
    target for a REMOTE slot before scanning, capping concurrent
    backfills per OSD at osd_max_backfills."""

    TYPE = 187
    FIELDS = [("pgid", "str"), ("epoch", "u32"), ("tid", "u64"),
              ("op", "u8"), ("from_osd", "s32")]


@register
class MOSDPGRepair(Message):
    """Mon -> acting primary: run a repair scrub on this PG (ref: the
    mon's `ceph pg repair` -> MOSDScrub(repair=true) path)."""

    TYPE = 188
    FIELDS = [("pgid", "str"), ("epoch", "u32"), ("from_osd", "s32")]


# -- client backoff (ref: src/messages/MOSDBackoff.h) ----------------------

BACKOFF_OP_BLOCK = 1       # osd -> client: stop sending ops for range
BACKOFF_OP_ACK_BLOCK = 2   # client -> osd: block acknowledged
BACKOFF_OP_UNBLOCK = 3     # osd -> client: resume (client resends)


@register
class MOSDBackoff(Message):
    """OSD -> client flow control (ref: MOSDBackoff + the PG Backoff
    machinery): when a PG is not yet active (peering) or its op queue
    is saturated, the primary BLOCKs the [begin, end) object-name
    range of that PG instead of queueing unboundedly. The Objecter
    parks matching ops and resumes on UNBLOCK — re-asserted across
    interval changes, released on activation. ``id`` pairs an UNBLOCK
    with its BLOCK."""

    TYPE = 189
    FIELDS = [("op", "u8"), ("id", "u64"), ("pool", "s64"),
              ("seed", "u32"), ("begin", "str"), ("end", "str"),
              ("epoch", "u32"), ("from_osd", "s32")]
