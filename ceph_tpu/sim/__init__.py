"""Cluster fault simulation: static churn analysis + live thrashing.

Two tiers (see README.md in this package):

- **static** (churn.py): replay OSD add/remove/reweight events over an
  OSDMap and measure, per epoch, how much data CRUSH remaps — all
  placements computed batch-wise on the accelerator (ref:
  src/tools/osdmaptool.cc --test-map-pgs).
- **live** (faults.py + thrasher.py): a runtime-installable messenger
  fault layer (partitions, one-way drops, delay, duplication,
  reorder — named, composable per peer-pair) and a seeded Thrasher
  that drives it against a running vstart cluster under continuing
  client writes (ref: qa/tasks/ceph_manager.py Thrasher +
  `ms inject socket failures`).
"""

from ceph_tpu.sim.churn import ChurnSim, ChurnEvent, StepReport  # noqa: F401
from ceph_tpu.sim.faults import (                                # noqa: F401
    FaultInjector, FaultRule, delay, drop, duplicate, partition, reorder,
)
from ceph_tpu.sim.thrasher import Thrasher                       # noqa: F401
