"""Cluster-churn simulation (failure/recovery rebalance analysis).

The TPU-shaped stand-in for the reference's thrashing suites
(ref: qa/tasks/ceph_manager.py Thrasher; src/tools/osdmaptool.cc
--test-map-pgs): replay OSD add/remove/reweight events over an OSDMap and
measure, for every epoch, how much data CRUSH remaps — all placements
computed batch-wise on the accelerator.
"""

from ceph_tpu.sim.churn import ChurnSim, ChurnEvent, StepReport  # noqa: F401
