"""Unified messenger-level fault injection: named, composable fault
sets per peer-pair.

ref: the `ms inject socket failures` / `ms inject delay` config knobs
plus qa/tasks/ceph_manager.py's blackhole helpers — generalized into
one runtime-installable layer. A ``FaultInjector`` hangs off any
number of ``Messenger``s (``msgr.faults = injector``); the messenger
consults it at three choke points:

- **connect** (``Messenger._client_handshake``): a partitioned or
  fully-dropped pair refuses new TCP sessions (the SYN never lands);
- **frame send** (``Connection._send_frame``): partitions abort the
  connection like an injected socket failure (both ends observe
  resets and retry), one-way drops are silent blackholes (the sender
  believes the frame left);
- **message send** (``Connection.send_message``): delay, duplication
  and reorder act *before* the sequence number is assigned, so the
  receiver's in-order dedup machinery sees a consistent stream and
  the upper layers (objecter resend, PG reqid dedup, lossless
  replay) are what absorbs the chaos — exactly the property the
  thrash suites exist to prove.

Fault semantics per kind:

- ``partition(a, b)`` — bidirectional: every frame between entities
  matching patterns ``a`` and ``b`` (either direction) aborts its
  connection; new connections are refused. Heals when cleared.
- ``drop(src, dst, prob)`` — one-way blackhole with probability
  ``prob``: the frame is swallowed, the sender is not told. On
  lossless sessions swallowed frames sit in the replay queue until
  the next reconnect; ``prob=1.0`` also refuses src->dst connects.
- ``delay(src, dst, min_s, max_s)`` — each message sleeps a fixed
  (min==max) or uniform-random time before the send lock, so later
  messages may overtake it (a mild reorder in itself).
- ``duplicate(src, dst, prob)`` — the message is sent twice with
  distinct seqs; end-to-end dedup (PG reqid table, waiter pop) must
  make it exactly-once.
- ``reorder(src, dst, prob, hold_s)`` — the message is held until
  the next message to the same peer overtakes it (or ``hold_s``
  elapses, so a lone message is never lost).

Device fault kinds (round 16) reuse the same rule table but fire at a
different choke point: ``utils.devmon.jit_call``, the one entry every
jit-backed device call (CRUSH map/sweep kernels, EC encode/decode)
already passes through. For these kinds ``a`` is an fnmatch pattern
over the call's ``fn_name`` (``crush_map_pgs``, ``crush_sweep``,
``ec_encode``, ``ec_encode_crc``, ``ec_decode``,
``ec_stream_encode``) and ``b`` is a pattern over ``str(key)`` — the
jit cache key, whose kernel-path form starts with ``('kern', ...)``,
so ``b="*'kern'*"`` targets only kernel-path launches and leaves the
XLA serving path alone:

- ``jit_fail(fn, key, prob, count)`` — the call raises RuntimeError
  before dispatch (a failed compile/launch as the caller sees it).
- ``jit_stall(fn, min_s, max_s, key, prob, count)`` — the call sleeps
  before dispatch (a recompile storm / contended-device stall).
- ``bad_result(fn, key, prob, count)`` — the call completes but its
  returned array comes back corrupted (one flipped element — the
  silent-wrong-answer case bit-exact probes must catch).

``count`` bounds total firings per rule (0 = unlimited); a spent rule
stops firing but stays installed until its set is cleared. Device
kinds never match the messenger hooks, and messenger kinds never
match ``jit_call``.

Rules compose: every matching rule applies. Sets are named and can be
installed/cleared at runtime on a served cluster (the vstart --serve
admin socket exposes ``fault install/clear/ls``); the Thrasher
(ceph_tpu/sim/thrasher.py) drives the same API from a seeded
schedule. Determinism: a seeded injector draws all probabilities from
its own ``random.Random``, so a fixed seed and a fixed message
sequence reproduce the same fault decisions.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from fnmatch import fnmatch

# kinds consulted by utils.devmon.jit_call instead of the messenger
DEVICE_KINDS = ("jit_fail", "jit_stall", "bad_result")


@dataclass(frozen=True)
class FaultRule:
    """One fault between entity-name patterns (fnmatch syntax, e.g.
    ``osd.1``, ``osd.*``, ``client.*``). ``a``/``b`` are src/dst for
    one-way kinds and unordered endpoints for ``partition``. For
    device kinds ``a`` matches the jit ``fn_name`` and ``b`` matches
    ``str(key)`` (the jit cache key)."""

    kind: str       # partition|drop|delay|duplicate|reorder|DEVICE_KINDS
    a: str
    b: str
    prob: float = 1.0
    min_s: float = 0.0
    max_s: float = 0.0
    hold_s: float = 0.05
    count: int = 0             # max firings, 0 = unlimited

    def matches(self, src: str, dst: str) -> bool:
        if self.kind in DEVICE_KINDS:
            return False
        if self.kind == "partition":
            return (fnmatch(src, self.a) and fnmatch(dst, self.b)) or \
                   (fnmatch(src, self.b) and fnmatch(dst, self.a))
        return fnmatch(src, self.a) and fnmatch(dst, self.b)

    def matches_device(self, fn_name: str, key_s: str) -> bool:
        return self.kind in DEVICE_KINDS and \
            fnmatch(fn_name, self.a) and fnmatch(key_s, self.b)

    def describe(self) -> dict:
        d = {"kind": self.kind, "a": self.a, "b": self.b}
        if self.kind in ("drop", "duplicate", "reorder") or \
                self.kind in DEVICE_KINDS:
            d["prob"] = self.prob
        if self.kind in ("delay", "jit_stall"):
            d["min_s"], d["max_s"] = self.min_s, self.max_s
        if self.kind == "reorder":
            d["hold_s"] = self.hold_s
        if self.kind in DEVICE_KINDS and self.count:
            d["count"] = self.count
        return d


def partition(a: str, b: str) -> FaultRule:
    """Bidirectional partition between entities matching a and b."""
    return FaultRule("partition", a, b)


def drop(src: str, dst: str, prob: float = 1.0) -> FaultRule:
    """One-way silent frame blackhole src -> dst."""
    return FaultRule("drop", src, dst, prob=prob)


def delay(src: str, dst: str, min_s: float,
          max_s: float | None = None) -> FaultRule:
    """Fixed (max_s=None) or uniform-random per-message delay."""
    return FaultRule("delay", src, dst, min_s=min_s,
                     max_s=min_s if max_s is None else max_s)


def duplicate(src: str, dst: str, prob: float = 1.0) -> FaultRule:
    """Send matching messages twice (distinct seqs)."""
    return FaultRule("duplicate", src, dst, prob=prob)


def reorder(src: str, dst: str, prob: float = 1.0,
            hold_s: float = 0.05) -> FaultRule:
    """Hold a message until the next one to the same peer overtakes
    it (bounded by hold_s so a lone message is never lost)."""
    return FaultRule("reorder", src, dst, prob=prob, hold_s=hold_s)


def jit_fail(fn: str, key: str = "*", prob: float = 1.0,
             count: int = 0) -> FaultRule:
    """Device calls matching (fn_name, key) patterns raise before
    dispatch — a failed compile/launch as the caller observes it."""
    return FaultRule("jit_fail", fn, key, prob=prob, count=count)


def jit_stall(fn: str, min_s: float, max_s: float | None = None,
              key: str = "*", prob: float = 1.0,
              count: int = 0) -> FaultRule:
    """Device calls matching the patterns sleep a fixed (max_s=None)
    or uniform-random time before dispatch."""
    return FaultRule("jit_stall", fn, key, prob=prob, min_s=min_s,
                     max_s=min_s if max_s is None else max_s,
                     count=count)


def bad_result(fn: str, key: str = "*", prob: float = 1.0,
               count: int = 0) -> FaultRule:
    """Device calls matching the patterns complete, but the returned
    array has one element flipped — the silent-corruption case."""
    return FaultRule("bad_result", fn, key, prob=prob, count=count)


_BUILDERS = {"partition": partition, "drop": drop, "delay": delay,
             "duplicate": duplicate, "reorder": reorder,
             "jit_fail": jit_fail, "jit_stall": jit_stall,
             "bad_result": bad_result}


def rule_from_dict(d: dict) -> FaultRule:
    """Build a rule from its ``describe()`` form (the admin-socket /
    CLI install path)."""
    kind = d.get("kind")
    if kind not in _BUILDERS:
        raise ValueError(f"unknown fault kind {kind!r}")
    kw = {k: d[k] for k in ("prob", "min_s", "max_s", "hold_s", "count")
          if k in d}
    return FaultRule(kind, d["a"], d["b"], **kw)


@dataclass
class _FaultSet:
    name: str
    rules: list[FaultRule] = field(default_factory=list)


class FaultInjector:
    """The runtime fault table. Install on messengers via
    ``msgr.faults = injector`` (the Cluster helper does every daemon);
    install/clear named sets at any time — messengers observe the new
    table on their next send."""

    def __init__(self, seed: int | None = None):
        self._rng = random.Random(seed)
        self._sets: dict[str, _FaultSet] = {}
        # (src, dst) -> event used by reorder: a held message waits on
        # it; the next message through the pair sets it
        self._holds: dict[tuple[str, str], asyncio.Event] = {}
        # per-rule firing counts for count-bounded rules
        self._spent: dict[int, int] = {}
        # device-rule fast path: jit_call (a hot chokepoint) only pays
        # for str(key) + rule iteration when a device rule is installed
        self._n_device = 0

    # -- set management ----------------------------------------------------
    def _recount(self) -> None:
        live = set()
        n = 0
        for s in self._sets.values():
            for r in s.rules:
                live.add(id(r))
                if r.kind in DEVICE_KINDS:
                    n += 1
        self._n_device = n
        self._spent = {k: v for k, v in self._spent.items() if k in live}

    def install(self, name: str, rules: list[FaultRule]) -> None:
        """Install (or replace) a named fault set."""
        self._sets[name] = _FaultSet(name, list(rules))
        self._recount()

    def clear(self, name: str) -> bool:
        """Remove one named set (heal those faults)."""
        hit = self._sets.pop(name, None) is not None
        if hit:
            self._recount()
        return hit

    def clear_all(self) -> None:
        self._sets.clear()
        self._recount()
        # release any held reorder messages immediately
        for ev in self._holds.values():
            ev.set()
        self._holds.clear()

    def describe(self) -> dict:
        """Admin-socket / CLI view of the installed table."""
        return {name: [r.describe() for r in s.rules]
                for name, s in sorted(self._sets.items())}

    def _rules(self, src: str, dst: str):
        for s in self._sets.values():
            for r in s.rules:
                if r.matches(src, dst):
                    yield r

    # -- messenger hooks ---------------------------------------------------
    def blocks_connect(self, src: str, dst: str) -> bool:
        """New-session gate (client handshake)."""
        for r in self._rules(src, dst):
            if r.kind == "partition":
                return True
            if r.kind == "drop" and r.prob >= 1.0:
                return True
        return False

    def on_frame(self, src: str, dst: str) -> str:
        """Frame-send verdict: 'ok' | 'drop' (silent blackhole) |
        'cut' (abort the connection, both ends see a reset)."""
        verdict = "ok"
        for r in self._rules(src, dst):
            if r.kind == "partition":
                return "cut"
            if r.kind == "drop" and self._rng.random() < r.prob:
                verdict = "drop"
        return verdict

    async def on_message(self, src: str, dst: str) -> bool:
        """Message-send shaping (delay/reorder), run BEFORE the seq is
        assigned. Returns True when the message should additionally be
        sent a second time (duplication)."""
        dup = False
        total_delay = 0.0
        held = None
        for r in self._rules(src, dst):
            if r.kind == "delay":
                total_delay += (r.min_s if r.max_s <= r.min_s else
                                self._rng.uniform(r.min_s, r.max_s))
            elif r.kind == "duplicate":
                dup = dup or self._rng.random() < r.prob
            elif r.kind == "reorder" and held is None and \
                    self._rng.random() < r.prob:
                held = r.hold_s
        if total_delay > 0:
            await asyncio.sleep(total_delay)
        key = (src, dst)
        if held is not None and key not in self._holds:
            # hold until the NEXT message to this peer passes (or the
            # bound elapses) — the later message overtakes this one
            ev = self._holds[key] = asyncio.Event()
            try:
                await asyncio.wait_for(ev.wait(), timeout=held)
            except asyncio.TimeoutError:
                pass
            finally:
                if self._holds.get(key) is ev:
                    del self._holds[key]
        else:
            ev = self._holds.get(key)
            if ev is not None:
                ev.set()
        return dup

    # -- device hooks (utils.devmon.jit_call) ------------------------------
    def has_device_rules(self) -> bool:
        """Cheap gate jit_call checks before paying for str(key)."""
        return self._n_device > 0

    def _fires(self, r: FaultRule) -> bool:
        """Probability + count gate; a firing consumes budget."""
        if r.count > 0 and self._spent.get(id(r), 0) >= r.count:
            return False
        if r.prob < 1.0 and self._rng.random() >= r.prob:
            return False
        if r.count > 0:
            self._spent[id(r)] = self._spent.get(id(r), 0) + 1
        return True

    def device_verdicts(self, fn_name: str,
                        key_s: str) -> tuple[float, bool, bool]:
        """The jit_call verdict for one device call: (stall seconds,
        raise-before-dispatch, corrupt-the-result). Every matching
        rule applies; stalls add."""
        stall, fail, corrupt = 0.0, False, False
        for s in self._sets.values():
            for r in s.rules:
                if not r.matches_device(fn_name, key_s) or \
                        not self._fires(r):
                    continue
                if r.kind == "jit_stall":
                    stall += (r.min_s if r.max_s <= r.min_s else
                              self._rng.uniform(r.min_s, r.max_s))
                elif r.kind == "jit_fail":
                    fail = True
                elif r.kind == "bad_result":
                    corrupt = True
        return stall, fail, corrupt
