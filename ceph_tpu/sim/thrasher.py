"""Live-cluster Thrasher: a seeded random fault schedule under
continuing client writes.

ref: qa/tasks/ceph_manager.py Thrasher — the qa machinery that makes
Ceph's "handles whatever happens" claim testable: while a client keeps
writing, daemons are killed/revived, the network is partitioned and
degraded, and afterwards the cluster must (a) return to clean, (b)
still serve every acknowledged write, (c) pass a full store fsck.
This module drives a ``ceph_tpu.cluster.vstart.Cluster`` through the
same storm using the fault layer in ``ceph_tpu.sim.faults``.

Determinism: the whole action schedule is a **pure function of the
seed** (``Thrasher.plan``) — the run log records which scheduled
actions were applied or skipped (an action can be infeasible at
execution time, e.g. a revive with nothing down). Re-running with the
same seed replays the same schedule.

Actions (weights roughly follow the qa thrasher):

- ``kill_osd`` / ``revive_osd`` — hard-stop a random live OSD; revive
  a random downed one. When a ``store_factory`` is provided the
  revive REMOUNTS the victim's store from disk (fresh BlueStore
  instance: deferred replay + allocator rebuild — the real restart
  path, the discipline ``tests/test_bluestore.py`` established).
- ``partition`` / ``heal`` — install a bidirectional partition
  between two live OSDs (cuts both the cluster and heartbeat
  messengers); heal clears a random installed set.
- ``degrade`` — install a lossy-link set (delay + duplication +
  reorder) between the client and the OSDs for a while.
- ``kill_mon`` — kill the lead monitor (only while a majority
  survives).
- ``pause`` — let the storm breathe (recovery/elections make
  progress).

Invariants checked by ``settle_and_verify`` (the same ones the
one-off thrash tests assert):

1. the cluster converges to every-PG-clean after all faults heal;
2. every acknowledged write is readable and bit-identical;
3. every store whose backend supports ``fsck`` fscks clean;
4. the mon cluster still answers commands (quorum survived).
"""

from __future__ import annotations

import asyncio
import json
import random

from ceph_tpu.sim import faults as F
from ceph_tpu.utils.logging import get_logger

log = get_logger("thrasher")

_WEIGHTED_ACTIONS = (
    ("kill_osd", 3), ("revive_osd", 3), ("partition", 2), ("heal", 2),
    ("degrade", 1), ("kill_mon", 1), ("pause", 3),
)


class Thrasher:
    def __init__(self, cluster, seed: int = 0,
                 store_factory=None, min_live_osds: int = 3,
                 pause_s: tuple[float, float] = (0.2, 0.8),
                 max_active_sets: int = 2,
                 write_timeout: float = 5.0):
        """``store_factory(osd_id) -> ObjectStore`` remounts a downed
        OSD's store from disk for revive-with-remount; None revives
        with the in-process store object. ``max_active_sets`` bounds
        concurrently-installed fault sets: a fully partitioned pair is
        never marked down (each end is the other's only accuser), so
        unbounded partitions would wedge every PG spanning one and
        starve the writer. ``write_timeout`` keeps storm writes short
        so the writer keeps attempting through wedged PGs."""
        self.c = cluster
        self.seed = seed
        self.store_factory = store_factory
        self.min_live_osds = min_live_osds
        self.pause_s = pause_s
        self.max_active_sets = max_active_sets
        self.write_timeout = write_timeout
        self.injector = F.FaultInjector(seed=seed)
        # the proc backend has no in-process daemons to hook: its
        # fault injection is wire-delivered per child (`ceph daemon
        # <asok> fault install`), and kill/revive are SIGNALS
        self.proc = getattr(cluster, "backend", "inproc") == "proc"
        if not self.proc:
            cluster.install_faults(self.injector)
        self.downed: list[int] = []
        self.active_sets: list[str] = []
        self.killed_mons = 0
        self.actions_log: list[str] = []   # what actually happened
        self.acked: dict[str, bytes] = {}
        self._writer_task: asyncio.Task | None = None
        self._write_seq = 0
        self._write_errors = 0

    # -- schedule (pure) ---------------------------------------------------
    @staticmethod
    def plan(seed: int, steps: int) -> list[dict]:
        """The seeded schedule: a pure function of (seed, steps) — no
        cluster state consulted, so two runs with one seed thrash
        identically. Each entry carries a raw ``pick`` the executor
        maps onto the live/downed sets at apply time."""
        rng = random.Random(seed)
        kinds = [k for k, w in _WEIGHTED_ACTIONS for _ in range(w)]
        out = []
        for _ in range(steps):
            kind = rng.choice(kinds)
            out.append({
                "op": kind,
                "pick": rng.randrange(1 << 30),
                "pick2": rng.randrange(1 << 30),
                "t": round(rng.uniform(0.0, 1.0), 4),
            })
        return out

    # -- background writer -------------------------------------------------
    async def _writer(self, io, parallel: int = 4) -> None:
        """Continuous unique-oid writes with bounded concurrency; only
        acknowledged writes are recorded (a timed-out/canceled write
        on a unique oid can't invalidate earlier acked data).
        Failures are EXPECTED mid-storm — the objecter's bounded retry
        turns them into clean errors — and parallelism keeps healthy
        PGs acking while a wedged PG waits out its timeout."""
        rng = random.Random(self.seed ^ 0x5EED)
        pending: set[asyncio.Task] = set()
        try:
            while True:
                oid = f"thrash-{self._write_seq}"
                data = bytes([self._write_seq % 256]) * \
                    rng.randint(1, 4096)
                self._write_seq += 1
                t = asyncio.ensure_future(
                    self._one_write(io, oid, data))
                pending.add(t)
                t.add_done_callback(pending.discard)
                if len(pending) >= parallel:
                    await asyncio.wait(
                        pending, return_when=asyncio.FIRST_COMPLETED)
                await asyncio.sleep(0.02)
        finally:
            for t in pending:
                t.cancel()
            await asyncio.gather(*pending, return_exceptions=True)

    async def _one_write(self, io, oid: str, data: bytes) -> None:
        try:
            await io.write_full(oid, data,
                                timeout=self.write_timeout)
            self.acked[oid] = data
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._write_errors += 1
            log.dout(5, f"storm write {oid} failed: {e!r}")

    # -- execution ---------------------------------------------------------
    def _live_osds(self) -> list[int]:
        return [o.whoami for o in self.c.osds
                if not o._stopped and o.whoami not in self.downed]

    def _log(self, line: str) -> None:
        self.actions_log.append(line)
        log.dout(1, f"thrash: {line}")

    async def _apply(self, a: dict) -> None:
        op, pick, pick2 = a["op"], a["pick"], a["pick2"]
        if op == "pause":
            lo, hi = self.pause_s
            self._log(f"pause {lo + (hi - lo) * a['t']:.2f}s")
            await asyncio.sleep(lo + (hi - lo) * a["t"])
            return
        if op == "kill_osd":
            live = self._live_osds()
            if len(live) <= self.min_live_osds:
                self._log("kill_osd skipped (at min live)")
                return
            victim = live[pick % len(live)]
            await self.c.kill_osd(victim)
            store = self.c.osds[victim].store
            if self.store_factory is not None and \
                    hasattr(store, "umount"):
                store.umount()
            self.downed.append(victim)
            self._log(f"kill osd.{victim}")
            try:
                await self.c.wait_for_osd_down(victim, timeout=60)
            except TimeoutError:
                self._log(f"osd.{victim} not marked down in time")
            return
        if op == "revive_osd":
            if not self.downed:
                self._log("revive_osd skipped (none down)")
                return
            victim = self.downed.pop(pick % len(self.downed))
            store = None
            if self.store_factory is not None:
                store = self.store_factory(victim)
            await self.c.revive_osd(victim, store=store)
            self._log(f"revive osd.{victim}"
                      f"{' (remounted)' if store is not None else ''}")
            return
        if op == "partition":
            live = self._live_osds()
            if len(live) < 2:
                self._log("partition skipped (<2 live)")
                return
            if len(self.active_sets) >= self.max_active_sets:
                self._log("partition skipped (at max active sets)")
                return
            x = live[pick % len(live)]
            y = live[pick2 % (len(live) - 1)]
            y = y if y != x else live[-1]
            if x == y:
                self._log("partition skipped (one live)")
                return
            name = f"part-{x}-{y}-{len(self.actions_log)}"
            self.injector.install(
                name, [F.partition(f"osd.{x}", f"osd.{y}")])
            self.active_sets.append(name)
            self._log(f"partition osd.{x} <-> osd.{y} [{name}]")
            return
        if op == "heal":
            if not self.active_sets:
                self._log("heal skipped (no active sets)")
                return
            name = self.active_sets.pop(pick % len(self.active_sets))
            self.injector.clear(name)
            self._log(f"heal [{name}]")
            return
        if op == "degrade":
            if len(self.active_sets) >= self.max_active_sets:
                self._log("degrade skipped (at max active sets)")
                return
            name = f"lossy-{len(self.actions_log)}"
            self.injector.install(name, [
                F.delay("client.*", "osd.*", 0.005, 0.03),
                F.duplicate("client.*", "osd.*", prob=0.2),
                F.reorder("osd.*", "client.*", prob=0.2),
            ])
            self.active_sets.append(name)
            self._log(f"degrade client<->osd links [{name}]")
            return
        if op == "kill_mon":
            killed = await self.c.kill_mon_leader()
            if killed is None:
                self._log("kill_mon skipped (no leader / quorum)")
            else:
                self.killed_mons += 1
                self.c.mons.remove(killed)
                self._log(f"kill mon.{killed.name} (leader)")
            return
        raise ValueError(f"unknown thrash op {op!r}")     # pragma: no cover

    async def thrash(self, io, steps: int) -> list[str]:
        """Run the seeded schedule while writing through ``io``.
        Returns the action log. Call ``settle_and_verify`` after."""
        schedule = self.plan(self.seed, steps)
        self._writer_task = asyncio.ensure_future(self._writer(io))
        try:
            for a in schedule:
                await self._apply(a)
        finally:
            self._writer_task.cancel()
            try:
                await self._writer_task
            except asyncio.CancelledError:
                pass
            # anything else is a WRITER crash, not a storm casualty
            # (per-write failures are caught in _one_write): swallow
            # it and every invariant below verifies vacuously against
            # an empty acked set
        return self.actions_log

    async def backfill_storm(self, io, writes: int = 60,
                             partitions: int = 0,
                             fresh_store: bool = False) -> dict:
        """The horizon-crossing storm (the backfill acceptance shape):
        kill one OSD, write PAST the pg-log trim horizon (the cluster
        must run with a small ``osd_min_pg_log_entries`` for ``writes``
        to cross it), then revive the victim — with its old store
        (stale rejoin) or a fresh one (``fresh_store=True``, the
        replace-an-OSD case) — optionally under concurrent partitions.
        The revived OSD's logs are beyond log-delta reach, so only
        backfill can converge it. Finish with ``settle_and_verify``:
        every acked write must survive on a CLEAN cluster, which
        (given the trimmed logs) proves the backfill path moved the
        history. Returns {victim, acked_writes, horizon_writes}."""
        rng = random.Random(self.seed ^ 0xBACF111)
        live = self._live_osds()
        if len(live) <= self.min_live_osds:
            raise RuntimeError("not enough live osds for a backfill "
                               "storm")
        victim = live[rng.randrange(len(live))]
        await self.c.kill_osd(victim)
        store = self.c.osds[victim].store
        if self.store_factory is not None and hasattr(store, "umount"):
            store.umount()
        self.downed.append(victim)
        self._log(f"backfill storm: kill osd.{victim}")
        try:
            await self.c.wait_for_osd_down(victim, timeout=60)
        except TimeoutError:
            self._log(f"osd.{victim} not marked down in time")
        for i in range(partitions):
            live = self._live_osds()
            if len(live) < 2 or \
                    len(self.active_sets) >= self.max_active_sets:
                break
            x, y = rng.sample(live, 2)
            name = f"bf-part-{x}-{y}-{i}"
            self.injector.install(
                name, [F.partition(f"osd.{x}", f"osd.{y}")])
            self.active_sets.append(name)
            self._log(f"backfill storm: partition osd.{x}<->osd.{y}")
        written = 0
        for i in range(writes):
            oid = f"bf-{self.seed}-{i:05d}"
            data = bytes([i % 256]) * rng.randint(1, 2048)
            try:
                await io.write_full(oid, data,
                                    timeout=self.write_timeout)
                self.acked[oid] = data
                written += 1
            except Exception as e:
                self._write_errors += 1
                log.dout(5, f"backfill-storm write {oid} failed: "
                            f"{e!r}")
        self._log(f"backfill storm: {written}/{writes} writes past "
                  f"the horizon")
        for name in list(self.active_sets):
            self.injector.clear(name)
            self.active_sets.remove(name)
            self._log(f"backfill storm: heal [{name}]")
        self.downed.remove(victim)
        new_store = None
        if fresh_store:
            from ceph_tpu.os_.objectstore import MemStore
            new_store = MemStore()        # a REPLACED osd: empty disk
        elif self.store_factory is not None:
            new_store = self.store_factory(victim)
        await self.c.revive_osd(victim, store=new_store)
        self._log(f"backfill storm: revive osd.{victim}"
                  f"{' (fresh store)' if fresh_store else ''}")
        return {"victim": victim, "acked_writes": written,
                "horizon_writes": writes}

    async def snap_storm(self, io, writes: int = 24, snaps: int = 3,
                         image_kb: int = 32,
                         settle_timeout: float = 240.0) -> dict:
        """The point-in-time honesty storm (the snapshot acceptance
        shape): an RBD image takes a continuous overwrite storm while
        snapshots are cut mid-stream and a background writer keeps
        racing the head; after the first snapshot one OSD is killed
        and the storm keeps writing, then the victim revives. Each
        snapshot's full readback is captured right after creation —
        the deterministic main region must already equal the tracked
        head — and at the end every capture must re-read
        byte-identical: the OSD's shared-blob COW clones have to
        freeze the past while the head moves across an acting-set
        change and recovery replays history onto the revived OSD.
        Writers are quiesced around each snap cut (the librbd
        flush-before-snap discipline): an in-flight write stamped
        with the pre-snap snapc would legitimately land inside the
        new snapshot. Call ``settle_and_verify`` after for the
        fsck/shared-blob-refcount cross-check. Returns {victim,
        snaps_verified, acked_writes, image}."""
        from ceph_tpu.rbd import RBD
        rng = random.Random(self.seed ^ 0x54A905)
        name = f"snapstorm-{self.seed}"
        size = image_kb * 1024
        rbd = RBD(io)
        await rbd.create(name, size, order=12)
        img = await rbd.open(name)
        # main region: deterministic, tracked in ``expected``; tail
        # quarter: the background writer's racetrack (frozen-from-
        # capture only, never compared against a model)
        main_len = size * 3 // 4
        base = bytes(rng.randrange(256) for _ in range(size))
        await img.write(0, base)
        expected = bytearray(base)
        captures: dict[str, bytes] = {}
        snap_lock = asyncio.Lock()
        bg_stop = asyncio.Event()

        async def bg_writer():
            i = 0
            lanes = max(1, (size - main_len) // 512 - 1)
            while not bg_stop.is_set():
                off = main_len + (i % lanes) * 512
                try:
                    async with snap_lock:
                        await img.write(off, bytes([i % 256]) * 512)
                except Exception as e:
                    self._write_errors += 1
                    log.dout(5, f"snap-storm bg write failed: {e!r}")
                i += 1
                await asyncio.sleep(0.01)

        bg = asyncio.ensure_future(bg_writer())
        victim = None
        written = 0
        snap_every = max(1, writes // snaps)
        try:
            for i in range(writes):
                off = rng.randrange(0, main_len - 1)
                n = rng.randint(1, min(2048, main_len - off))
                data = bytes([rng.randrange(256)]) * n
                try:
                    async with snap_lock:
                        await img.write(off, data)
                    expected[off:off + n] = data
                    written += 1
                except Exception as e:
                    self._write_errors += 1
                    log.dout(5, f"snap-storm write failed: {e!r}")
                if (i + 1) % snap_every == 0 and len(captures) < snaps:
                    sname = f"storm-{len(captures)}"
                    async with snap_lock:
                        await img.snap_create(sname)
                        view = await rbd.open(name, snapshot=sname)
                        cap = await view.read(0, size)
                    assert cap[:main_len] == bytes(expected[:main_len]), \
                        f"snapshot {sname} differs from the head it froze"
                    captures[sname] = cap
                    self._log(f"snap storm: cut+captured {sname}")
                    if victim is None:
                        live = self._live_osds()
                        if len(live) > self.min_live_osds:
                            victim = live[rng.randrange(len(live))]
                            await self.c.kill_osd(victim)
                            st = self.c.osds[victim].store
                            if self.store_factory is not None and \
                                    hasattr(st, "umount"):
                                st.umount()
                            self.downed.append(victim)
                            self._log(f"snap storm: kill osd.{victim}")
                            try:
                                await self.c.wait_for_osd_down(
                                    victim, timeout=60)
                            except TimeoutError:
                                self._log(f"osd.{victim} not marked "
                                          f"down in time")
        finally:
            bg_stop.set()
            bg.cancel()
            try:
                await bg
            except asyncio.CancelledError:
                pass
        if victim is not None:
            self.downed.remove(victim)
            new_store = self.store_factory(victim) \
                if self.store_factory is not None else None
            await self.c.revive_osd(victim, store=new_store)
            self._log(f"snap storm: revive osd.{victim}")
        await self.c.wait_for_clean(timeout=settle_timeout)
        verified = 0
        for sname, cap in captures.items():
            view = await rbd.open(name, snapshot=sname)
            got = await view.read(0, size)
            assert got == cap, \
                f"snapshot {sname} drifted after the storm"
            verified += 1
        head = await (await rbd.open(name)).read(0, size)
        assert head[:main_len] == bytes(expected[:main_len]), \
            "head lost acked writes after the storm"
        self._log(f"snap storm: {verified} snapshots byte-identical, "
                  f"head intact")
        return {"victim": victim, "snaps_verified": verified,
                "acked_writes": written, "image": name}

    async def overload_storm(self, io, writers: int = 4,
                             write_bytes: int = 1024,
                             prefill: int = 24,
                             fill_margin: float = 0.5,
                             full_timeout: float = 30.0,
                             hold_s: float = 1.0,
                             drain_timeout: float = 60.0) -> dict:
        """The resource-exhaustion storm (the overload acceptance
        shape): prefill, then shrink ``osd_capacity_bytes`` so the
        cluster sits at ~``fill_margin`` of capacity, and keep
        writing until the mon's fullness sweep trips the cluster FULL
        flag. The invariant under test: concurrent writers PARK on
        the objecter's flag wait-queue — no unhandled ENOSPC from the
        store, no write acked and later lost. After ``hold_s`` the
        capacity is restored; every parked write must then drain to
        success and the cluster converge clean with all acked data
        readable (finish with ``settle_and_verify``).

        Capacity rides the SHARED cluster config dict, so every OSD
        (statfs report) and the mon (ratios) see the change at once —
        the runtime-shrinkable capacity knob the storm needs.
        Returns {capacity, acked_writes, parked_at_full, errors}."""
        cfg = self.c.cfg
        old_cap = cfg.get("osd_capacity_bytes", 0)
        rng = random.Random(self.seed ^ 0x0F111)
        for i in range(prefill):
            oid = f"ov-pre-{self.seed}-{i:04d}"
            data = bytes([i % 256]) * write_bytes
            # prefill rides the generous drain deadline: a slow host
            # must not fail the storm before it even starts
            await io.write_full(oid, data, timeout=drain_timeout)
            self.acked[oid] = data
        # per-OSD usage ~= total * size / n_osds; capacity chosen so
        # each OSD starts near fill_margin of it
        live = [o for o in self.c.osds if not o._stopped]
        per_osd = max(o.store_used_bytes() for o in live)
        capacity = max(int(per_osd / fill_margin), 4096)
        cfg["osd_capacity_bytes"] = capacity
        self._log(f"overload storm: capacity -> {capacity}B "
                  f"(~{per_osd}B used per osd)")
        errors: list = []
        stop = asyncio.Event()
        seqs = [0]

        async def writer(w):
            while not stop.is_set():
                oid = f"ov-{self.seed}-{w}-{seqs[0]:05d}"
                seqs[0] += 1
                data = bytes([seqs[0] % 256]) * \
                    rng.randint(1, write_bytes)
                try:
                    # generous deadline: a FULL-parked write must
                    # outlive the storm's hold window, not time out
                    await io.write_full(oid, data,
                                        timeout=drain_timeout)
                    self.acked[oid] = data
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    errors.append((oid, repr(e)))
                await asyncio.sleep(0.01)
        tasks = [asyncio.ensure_future(writer(w))
                 for w in range(writers)]
        try:
            deadline = asyncio.get_event_loop().time() + full_timeout
            while True:
                status = await self.c.client.status()
                flags = status["osdmap"].get("flags", "")
                if "full" in flags.split(","):
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(
                        f"FULL flag never tripped (flags={flags!r}, "
                        f"util={status['osdmap'].get('osd_utilization')})")
                await asyncio.sleep(0.1)
            self._log("overload storm: cluster FULL tripped")
            acked_at_full = len(self.acked)
            await asyncio.sleep(hold_s)
            # parked, not erroring: while FULL, writers must neither
            # fail nor leak ENOSPC from BlueStoreLite — and no NEW
            # writes complete (only ops already in flight when the
            # flag tripped may still land)
            assert not errors, f"writers errored under FULL: {errors}"
            parked = sum(1 for t in tasks if not t.done())
            assert parked == writers, \
                f"only {parked}/{writers} writers still running"
            grew = len(self.acked) - acked_at_full
            assert grew <= writers, \
                f"{grew} writes completed against a FULL cluster"
            cfg["osd_capacity_bytes"] = old_cap
            self._log("overload storm: capacity restored")
            deadline = asyncio.get_event_loop().time() + drain_timeout
            while True:
                status = await self.c.client.status()
                flags = status["osdmap"].get("flags", "")
                if "full" not in flags.split(","):
                    break
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError("FULL flag never cleared")
                await asyncio.sleep(0.1)
            # drain: every write issued before/through FULL completes
            stop.set()
            done, pending = await asyncio.wait(
                tasks, timeout=drain_timeout)
            assert not pending, "writers failed to drain after unfull"
            assert not errors, \
                f"writes lost in the drain: {errors}"
        finally:
            stop.set()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            if cfg.get("osd_capacity_bytes") == capacity:
                cfg["osd_capacity_bytes"] = old_cap
        self._log(f"overload storm: drained; {len(self.acked)} acked, "
                  f"{len(errors)} errors")
        return {"capacity": capacity, "acked_writes": len(self.acked),
                "parked_at_full": parked, "errors": len(errors)}

    async def elastic_storm(self, io, writes: int = 30,
                            pool: str | None = None,
                            mon_cycle: bool = True,
                            auth_cycle: bool = True,
                            split_merge: bool = True,
                            phase_timeout: float = 60.0) -> dict:
        """The elastic-control-plane storm (the round-6 acceptance
        shape): while a background writer keeps acking unique-oid
        writes, the cluster is grown and shrunk at RUNTIME —

        1. mon membership: add a mon (quorum re-forms over 3), kill
           the leader (re-election among the 3-member map), then
           `mon rm` the corpse back to a 2-mon map — commands and
           writes keep flowing throughout;
        2. auth lifecycle: `auth get-or-create` provisions a fresh
           client that serves I/O, `auth rotate` re-keys the admin
           entity under live traffic, and `auth rm` fences the fresh
           client — its open session drops and new handshakes are
           refused while the rotated admin keeps writing;
        3. pg topology: the loaded pool splits (pg_num up + pgp ramp)
           and then MERGES back through the pg_num_pending readiness
           barrier, with the writer racing the quiesce window.

        ``writes`` caps the background writer's target (smoke budgets
        per tests/test_meta.py). Finish with ``settle_and_verify`` —
        every acked write must be readable bit-identical on a clean
        cluster afterwards.
        """
        c = self.c
        pool = pool or io.pool_name
        results: dict = {"phases": []}
        self._writer_task = asyncio.ensure_future(self._writer(io))
        try:
            if mon_cycle:
                n0 = len(c.monmap.mons)
                mon = await c.add_mon()
                await c.wait_for_quorum(n0 + 1,
                                        timeout=phase_timeout)
                self._log(f"elastic: mon.{mon.name} added; quorum "
                          f"{n0 + 1}")
                killed = await c.kill_mon_leader()
                assert killed is not None, \
                    "no killable leader with 3 mons"
                c.mons.remove(killed)
                self.killed_mons += 1
                await c.wait_for_quorum(n0, timeout=phase_timeout)
                self._log(f"elastic: leader mon.{killed.name} killed; "
                          f"re-elected among survivors")
                await c.rm_mon(killed.name, timeout=phase_timeout)
                self._log(f"elastic: mon.{killed.name} removed; "
                          f"monmap back to {len(c.monmap.mons)}")
                results["phases"].append("mon_cycle")
            if auth_cycle:
                import json as _json

                from ceph_tpu.msg import Keyring as _Keyring
                from ceph_tpu.rados import Rados as _Rados
                ret, rs, out = await c.client.mon_command(
                    {"prefix": "auth get-or-create",
                     "entity": "client.elastic"})
                assert ret == 0, rs
                key = bytes.fromhex(_json.loads(out)["key"])
                fresh = _Rados(c.monmap, name="client.elastic",
                               keyring=_Keyring(
                                   {"client.elastic": key}))
                await fresh.connect()
                fio = await fresh.open_ioctx(pool)
                await fio.write_full("elastic-fresh", b"provisioned",
                                     timeout=self.write_timeout)
                self.acked["elastic-fresh"] = b"provisioned"
                ret, rs, _ = await c.client.mon_command(
                    {"prefix": "auth rotate",
                     "entity": "client.admin"})
                assert ret == 0, rs
                # the admin's LIVE session must keep serving after its
                # key rotated (re-keyed in-band, not re-authed)
                await io.write_full("elastic-after-rotate", b"live",
                                    timeout=self.write_timeout)
                self.acked["elastic-after-rotate"] = b"live"
                ret, rs, _ = await c.client.mon_command(
                    {"prefix": "auth rm",
                     "entity": "client.elastic"})
                assert ret == 0, rs
                fenced = False
                try:
                    await fio.write_full("elastic-after-revoke",
                                         b"nope", timeout=4.0)
                except Exception:
                    fenced = True
                assert fenced, ("revoked client.elastic still "
                                "serves I/O")
                await fresh.shutdown()
                self._log("elastic: key provisioned, rotated (live "
                          "session survived), revoked (fenced)")
                results["phases"].append("auth_cycle")
            if split_merge:
                ret, _, out = await c.client.mon_command(
                    {"prefix": "osd dump"})
                import json as _json
                pinfo = next(p for p in _json.loads(out)["pools"]
                             if p["name"] == pool)
                pg0 = pinfo["pg_num"]
                await self._pool_set(pool, "pg_num", pg0 * 2)
                await c.wait_for_clean(timeout=phase_timeout * 2)
                await self._pool_set(pool, "pgp_num", pg0 * 2)
                await c.wait_for_clean(timeout=phase_timeout * 2)
                self._log(f"elastic: pool {pool} split "
                          f"{pg0} -> {pg0 * 2} + migrated")
                await self._pool_set(pool, "pg_num", pg0)
                deadline = asyncio.get_event_loop().time() + \
                    phase_timeout * 2
                while True:
                    ret, _, out = await c.client.mon_command(
                        {"prefix": "osd dump"})
                    pinfo = next(p for p in _json.loads(out)["pools"]
                                 if p["name"] == pool)
                    if pinfo["pg_num"] == pg0 and \
                            not pinfo["pg_num_pending"]:
                        break
                    assert asyncio.get_event_loop().time() < \
                        deadline, f"merge never committed: {pinfo}"
                    await asyncio.sleep(0.2)
                self._log(f"elastic: pool {pool} merged back to "
                          f"{pg0} under load")
                results["phases"].append("split_merge")
            # let the writer reach its budget so the storm proves
            # sustained I/O across every transition
            deadline = asyncio.get_event_loop().time() + phase_timeout
            while len(self.acked) < writes and \
                    asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.1)
        finally:
            self._writer_task.cancel()
            try:
                await self._writer_task
            except asyncio.CancelledError:
                pass
        results["acked_writes"] = len(self.acked)
        results["failed_writes"] = self._write_errors
        self._log(f"elastic: {len(self.acked)} acked, "
                  f"{self._write_errors} transient failures")
        return results

    async def qos_storm(self, io_cold, io_hot, writes: int = 24,
                        hot_parallel: int = 4, hot_burst: int = 16,
                        cold_think_s: float = 0.02,
                        write_bytes: int = 1024,
                        op_timeout: float = 30.0) -> dict:
        """The two-tenant QoS storm (the round-11 acceptance shape):
        a HOT tenant floods the cluster with ``hot_parallel`` writer
        tasks, each keeping ``hot_burst`` writes in flight at once
        (OPEN-loop inside the burst — a closed-loop writer would
        self-limit and never actually offer 10x), while a COLD tenant
        issues ``writes`` paced ops through its own client — the
        scheduler must keep the cold tenant's latency near its solo
        baseline while FIFO lets the hot queue bury it. This entry
        measures ONE configuration; the caller compares runs across
        the ``osd_op_queue`` knob (it rides the shared cluster cfg,
        so it flips at runtime).

        ``io_cold``/``io_hot`` must be IoCtxs of DIFFERENT client
        entities (the scheduler queues by entity). Returns
        {cold_p99_s, cold_p50_s, cold_ops_per_s, hot_ops, mode}."""
        import time as _time
        from ceph_tpu.sim.loadgen import percentile
        stop = asyncio.Event()
        hot_ops = [0]
        rng = random.Random(self.seed ^ 0x0A05)

        async def one_hot(w: int, i: int) -> None:
            oid = f"qos-hot-{self.seed}-{w}-{i % 64:03d}"
            data = bytes([i % 256]) * write_bytes
            try:
                await io_hot.write_full(oid, data,
                                        timeout=op_timeout)
                hot_ops[0] += 1
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.dout(5, f"qos storm hot write failed: {e!r}")

        async def hot_writer(w: int) -> None:
            i = 0
            while not stop.is_set():
                await asyncio.gather(*[
                    one_hot(w, i + k) for k in range(hot_burst)])
                i += hot_burst
        tasks = [asyncio.ensure_future(hot_writer(w))
                 for w in range(hot_parallel)]
        lat: list[float] = []
        errors = 0
        try:
            await asyncio.sleep(0.2)      # let the hot flood build up
            t0 = _time.perf_counter()
            for i in range(writes):
                oid = f"qos-cold-{self.seed}-{i:04d}"
                data = bytes([i % 256]) * rng.randint(1, write_bytes)
                s0 = _time.perf_counter()
                try:
                    await io_cold.write_full(oid, data,
                                             timeout=op_timeout)
                    lat.append(_time.perf_counter() - s0)
                    self.acked[oid] = data
                except asyncio.CancelledError:
                    raise
                except Exception:
                    errors += 1
                await asyncio.sleep(cold_think_s)
            wall = _time.perf_counter() - t0
        finally:
            stop.set()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
        lat.sort()
        self._log(f"qos storm: cold {len(lat)}/{writes} acked "
                  f"(p99 {percentile(lat, 0.99) * 1e3:.1f} ms), "
                  f"hot {hot_ops[0]} ops, {errors} errors")
        return {"mode": str(self.c.cfg.get("osd_op_queue", "mclock")),
                "cold_ops": len(lat),
                "cold_errors": errors,
                "cold_p50_s": percentile(lat, 0.50),
                # p95 alongside p99: with smoke-sized sample counts
                # p99 IS the max, which one GC/event-loop blip owns —
                # assertions compare p95 (structural delay), records
                # keep p99
                "cold_p95_s": percentile(lat, 0.95),
                "cold_p99_s": percentile(lat, 0.99),
                "cold_ops_per_s": round(len(lat) / wall, 1)
                if wall > 0 else 0.0,
                "hot_ops": hot_ops[0]}

    async def tuner_storm(self, io_cold, io_hot, writes: int = 24,
                          hot_parallel: int = 4, hot_burst: int = 16,
                          cold_think_s: float = 0.02,
                          write_bytes: int = 1024,
                          op_timeout: float = 30.0,
                          ramp_s: float = 0.5) -> dict:
        """The self-driving-tuner acceptance storm (round 17): the
        qos_storm's two-tenant shape split across TWO POOLS — the hot
        tenant floods its own pool open-loop while the cold tenant
        paces on another — so the mgr tuner's hot-pool protector has
        a per-pool op-rate signal to trip on (the hot pool starving
        the cold one), not just per-entity queues. ``ramp_s`` holds
        the hot flood before the cold measurement starts, giving the
        tuner's hysteresis window time to see the breach.

        ``io_cold``/``io_hot`` must be IoCtxs of DIFFERENT client
        entities over DIFFERENT pools. Returns the qos_storm report
        shape plus the mon's tuner ledger (committed/reverted/
        observed + mode) sampled after the storm — the caller diffs
        ledgers across runs to count actions this storm caused."""
        import time as _time
        from ceph_tpu.sim.loadgen import percentile
        stop = asyncio.Event()
        hot_ops = [0]
        rng = random.Random(self.seed ^ 0x70E5)

        async def one_hot(w: int, i: int) -> None:
            oid = f"tuner-hot-{self.seed}-{w}-{i % 64:03d}"
            data = bytes([i % 256]) * write_bytes
            try:
                await io_hot.write_full(oid, data,
                                        timeout=op_timeout)
                hot_ops[0] += 1
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.dout(5, f"tuner storm hot write failed: {e!r}")

        async def hot_writer(w: int) -> None:
            i = 0
            while not stop.is_set():
                await asyncio.gather(*[
                    one_hot(w, i + k) for k in range(hot_burst)])
                i += hot_burst
        tasks = [asyncio.ensure_future(hot_writer(w))
                 for w in range(hot_parallel)]
        lat: list[float] = []
        errors = 0
        t_start = _time.perf_counter()
        try:
            if hot_parallel:
                await asyncio.sleep(ramp_s)    # let the breach register
            t0 = _time.perf_counter()
            for i in range(writes):
                oid = f"tuner-cold-{self.seed}-{i:04d}"
                data = bytes([i % 256]) * rng.randint(1, write_bytes)
                s0 = _time.perf_counter()
                try:
                    await io_cold.write_full(oid, data,
                                             timeout=op_timeout)
                    lat.append(_time.perf_counter() - s0)
                    self.acked[oid] = data
                except asyncio.CancelledError:
                    raise
                except Exception:
                    errors += 1
                await asyncio.sleep(cold_think_s)
            wall = _time.perf_counter() - t0
        finally:
            stop.set()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
        wall_total = _time.perf_counter() - t_start
        lat.sort()
        tuner = {}
        ret, _, out = await self.c.client.mon_command(
            {"prefix": "tune status"})
        if ret == 0:
            st = json.loads(out)
            tuner = {k: st.get(k) for k in
                     ("mode", "committed", "reverted", "observed")}
        self._log(f"tuner storm: cold {len(lat)}/{writes} acked "
                  f"(p99 {percentile(lat, 0.99) * 1e3:.1f} ms), "
                  f"hot {hot_ops[0]} ops, tuner {tuner}")
        return {"mode": str(self.c.cfg.get("mgr_tuner_mode",
                                           "observe")),
                "cold_ops": len(lat),
                "cold_errors": errors,
                "cold_p50_s": percentile(lat, 0.50),
                "cold_p95_s": percentile(lat, 0.95),
                "cold_p99_s": percentile(lat, 0.99),
                "cold_ops_per_s": round(len(lat) / wall, 1)
                if wall > 0 else 0.0,
                "hot_ops": hot_ops[0],
                "wall_s": round(wall_total, 3),
                # both tenants over the storm's full window (incl the
                # ramp the hot flood runs alone) — the throughput side
                # of the protect-the-cold-tenant trade
                "agg_ops_per_s": round(
                    (len(lat) + hot_ops[0]) / wall_total, 1)
                if wall_total > 0 else 0.0,
                "tuner": tuner}

    async def _pool_set(self, pool: str, var: str, val: int) -> None:
        ret, rs, _ = await self.c.client.mon_command(
            {"prefix": "osd pool set", "pool": pool, "var": var,
             "val": str(val)})
        assert ret == 0, f"pool set {var}={val}: {rs}"

    async def mds_storm(self, fs_clients, writes: int = 24,
                        files_before_kill: int = 4,
                        kills: int = 1,
                        takeover_timeout: float = 30.0,
                        fence_timeout: float = 15.0,
                        kill_rank: int = 0,
                        writer_dirs: list | None = None,
                        survivor_writers: list | None = None) -> dict:
        """The metadata-plane failover storm (the MDS acceptance
        shape): while ``fs_clients`` hammer metadata I/O (unique-file
        writes through the MDS), ``kill -9`` the ACTIVE MDS and assert
        the mon-coordinated ladder delivers:

        1. a standby reaches ``active`` within ``takeover_timeout``;
        2. NO writer errors — every op issued across the failover
           completes (clients park, reconnect with cap replay, and
           op-replay unacked mutations; the successor's completed-
           request table dedups the ones that landed pre-crash);
        3. every acked write is readable and bit-identical afterwards;
        4. the fenced old incarnation's late JOURNAL write is refused
           by the OSDs (blocklist) — the no-split-brain invariant.

        Multi-active variant (round 7): ``kill_rank`` selects which
        rank's active dies; ``writer_dirs`` gives each writer its own
        base directory (pin them to ranks first via
        ``cluster.subtree_pin``) so writers exercise DISJOINT
        subtrees; ``survivor_writers`` lists writer indexes whose
        subtree lives on a surviving rank — the storm then also
        asserts those writers kept acking DURING the takeover window
        (the surviving-ranks-keep-serving half of the acceptance).

        Requires ``cluster.start_fs`` with at least ``kills`` + 1
        daemons. Returns {kills, acked_writes, errors, takeover_s}.
        """
        c = self.c
        assert c.mdss, "mds_storm needs cluster.start_fs() first"
        rng = random.Random(self.seed ^ 0x3D5)
        acked: dict[str, bytes] = {}
        errors: list = []
        prog = [0] * len(fs_clients)     # per-writer acked count

        async def writer(w: int, cl) -> None:
            base = writer_dirs[w] if writer_dirs else ""
            for i in range(writes):
                path = f"{base}/mds-storm-{self.seed}-{w}-{i:04d}"
                data = bytes([(w + i) % 256]) * rng.randint(1, 512)
                try:
                    await asyncio.wait_for(cl.write_file(path, data),
                                           timeout=45.0)
                    acked[path] = data
                    prog[w] += 1
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    errors.append((path, repr(e)))
                await asyncio.sleep(0.01)
        tasks = [asyncio.ensure_future(writer(w, cl))
                 for w, cl in enumerate(fs_clients)]
        takeover_s = []
        zombies = []
        survivor_stalls = []
        try:
            for k in range(kills):
                deadline = asyncio.get_event_loop().time() + 30.0
                while len(acked) < files_before_kill * (k + 1):
                    if asyncio.get_event_loop().time() > deadline:
                        raise AssertionError(
                            "writers made no progress before kill")
                    await asyncio.sleep(0.05)
                victim = c.mds_active_name(kill_rank)
                assert victim is not None, \
                    f"no active mds on rank {kill_rank} to kill"
                prog_at_kill = list(prog)
                zombies.append(await c.kill_mds(victim))
                self._log(f"mds storm: kill -9 active mds.{victim} "
                          f"(rank {kill_rank})")
                t0 = asyncio.get_event_loop().time()
                newa = await c.wait_for_mds_active(
                    not_name=victim, timeout=takeover_timeout,
                    rank=kill_rank)
                takeover_s.append(
                    round(asyncio.get_event_loop().time() - t0, 2))
                self._log(f"mds storm: mds.{newa} took over rank "
                          f"{kill_rank} ({takeover_s[-1]}s)")
                for w in (survivor_writers or []):
                    # a surviving rank's writer must have kept acking
                    # through the takeover window (unless it already
                    # finished its budget before the kill)
                    if prog_at_kill[w] >= writes:
                        continue
                    if prog[w] <= prog_at_kill[w]:
                        survivor_stalls.append(
                            (k, w, prog_at_kill[w], prog[w]))
            done, pending = await asyncio.wait(tasks, timeout=120.0)
            assert not pending, "writers wedged after mds failover"
        finally:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
        assert not errors, \
            f"writer ops lost across failover: {errors[:4]}"
        assert not survivor_stalls, \
            (f"surviving-rank writers stalled during takeover "
             f"(kill, writer, before, after): {survivor_stalls}")
        # every acked write readable and intact through a survivor
        reader = fs_clients[0]
        for path, data in acked.items():
            got = await reader.read_file(path)
            assert got == data, f"acked {path} corrupted by failover"
        # the fenced incarnations' late journal writes must bounce:
        # probe until the blocklist map reaches the serving OSD (the
        # promote already barriered, so this resolves fast)
        from ceph_tpu.rados import ObjectOperationError
        for z in zombies:
            deadline = asyncio.get_event_loop().time() + fence_timeout
            while True:
                try:
                    # underscore-prefixed key: journal readers iterate
                    # digit keys only, so a probe landing BEFORE the
                    # blocklist propagates can never poison a later
                    # replay/tail (z.journal_oid: the zombie's RANK's
                    # journal — the object its split-brain write would
                    # actually target)
                    await z.ioctx.set_omap(
                        z.journal_oid, "_zombie_probe", b"stale")
                except ObjectOperationError as e:
                    assert e.errno == -108, e    # -EBLOCKLISTED
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    (f"zombie mds.{z.name} ({z.ident}) journal write "
                     f"was never fenced")
                await asyncio.sleep(0.2)
            self._log(f"mds storm: zombie {z.ident} fenced")
        self._log(f"mds storm: {len(acked)} acked, 0 lost")
        return {"kills": kills, "acked_writes": len(acked),
                "errors": len(errors), "takeover_s": takeover_s}

    async def device_storm(self, io, io_ec=None, ec_writes: int = 8,
                           ec_fails: int = 3, stall_s: float = 0.02,
                           probe_hosts: int = 4,
                           probe_timeout: float = 120.0) -> dict:
        """The device-fault resilience storm (the round-16 acceptance
        shape): jit_fail / jit_stall / bad_result bursts at the devmon
        chokepoint while replicated AND erasure-coded client writes
        keep flowing — the acceptance is ZERO client-visible errors
        and the kernel path RE-PROMOTED (not merely degraded) once the
        faults clear.

        Three legs run concurrently:

        1. **EC degrade ladder** — ``jit_fail`` on ``ec_encode*``
           poisons ``ec_fails`` device encodes; the OSD aggregator
           must serve every one of ``ec_writes`` client writes
           through per-op retry / the host reference encoder.
        2. **Cluster latency** — ``jit_stall`` on every CRUSH device
           call adds ``stall_s`` of injected device latency under the
           background replicated writer (latency, never errors).
        3. **Kernel quarantine cycle** — a dedicated interpret-mode
           probe Mapper (the cluster daemons serve plain XLA on CPU;
           only this mapper HAS a kernel path to lose) rides the full
           state machine: ``jit_fail`` keyed ``*'kern'*`` quarantines
           it, a ``bad_result`` keyed the same way makes the first
           re-probe REFUSE promotion (corrupt kernel output never
           serves), and once the faults clear a clean probe promotes
           it back — bit-exact against the healthy output.

        The caller runs ``settle_and_verify`` afterwards as usual.
        Returns the counter evidence (quarantine entries/exits,
        probes, EC fallbacks, write errors)."""
        import os
        import time as _time

        import numpy as np

        from ceph_tpu.crush import builder as crush_builder
        from ceph_tpu.crush.mapper import Mapper
        from ceph_tpu.utils import devmon as devmon_mod

        knobs = {"crush_kernel_reprobe_base": 0.05,
                 "crush_kernel_reprobe_max": 0.2,
                 "crush_kernel_reprobe_disable_after": 8}
        cm, root = crush_builder.build_hierarchy(probe_hosts, 2)
        rid = crush_builder.add_simple_rule(
            cm, root, crush_builder.TYPE_HOST)
        prev = os.environ.get("CEPH_TPU_CRUSH_KERNEL")
        os.environ["CEPH_TPU_CRUSH_KERNEL"] = "interpret"
        try:
            probe = Mapper(cm, config=knobs)
        finally:
            if prev is None:
                os.environ.pop("CEPH_TPU_CRUSH_KERNEL", None)
            else:
                os.environ["CEPH_TPU_CRUSH_KERNEL"] = prev
        assert probe._kernel_mode == "interpret", \
            "probe mapper has no kernel path to quarantine"
        xs = np.arange(64, dtype=np.uint32)
        out0, path0 = probe.map_pgs_path(rid, xs, 2)
        out0 = np.asarray(out0)
        assert path0 == "pallas-interpret", path0

        dm = devmon_mod.devmon()
        q0 = dm.perf.dump()
        errs0 = self._write_errors
        wt = asyncio.ensure_future(self._writer(io))
        ec_acked = 0
        try:
            # one storm burst: fails bounded by count, stalls by prob
            self.injector.install("device_storm", [
                F.jit_fail("ec_encode*", count=ec_fails),
                F.jit_stall("crush_*", stall_s, prob=0.5, count=16),
                F.jit_fail("crush_map_pgs", key="*'kern'*", count=1),
            ])
            # leg 3a: the injected kernel failure quarantines the
            # probe mapper — the SAME call still answers (XLA serves)
            out_q, path_q = probe.map_pgs_path(rid, xs, 2)
            info = probe.kernel_quarantine_info()
            assert info is not None and path_q == "xla", (info, path_q)
            assert np.array_equal(np.asarray(out_q), out0), \
                "degraded serving path diverged from healthy output"
            # leg 1: EC writes through the poisoned encode path
            # (tracked separately from self.acked: settle_and_verify
            # reads acked oids through the REPLICATED ioctx)
            ec_data: dict[str, bytes] = {}
            if io_ec is not None:
                for i in range(ec_writes):
                    oid = f"devstorm-ec-{self.seed}-{i:03d}"
                    data = bytes([i % 256]) * (1024 + i)
                    await io_ec.write_full(
                        oid, data, timeout=self.write_timeout * 6)
                    ec_data[oid] = data
                    ec_acked += 1
            # let the stalled replicated writer breathe a little more
            await asyncio.sleep(0.3)
            # stop the writer BEFORE the probe legs: an interpret-mode
            # probe compile blocks the event loop for seconds, which
            # would spuriously time out in-flight storm writes that
            # made no progress while the loop was held
            wt.cancel()
            await asyncio.gather(wt, return_exceptions=True)
            # leg 3b: a corrupt probe must REFUSE promotion
            self.injector.install("device_storm_probe", [
                F.bad_result("crush_map_pgs", key="*'kern'*",
                             count=1)])
            fails_before = int(info["failures"])
            deadline = _time.monotonic() + probe_timeout
            while _time.monotonic() < deadline:
                probe.map_pgs_path(rid, xs, 2)   # probe when due
                info = probe.kernel_quarantine_info()
                if info is None or \
                        info["failures"] > fails_before:
                    break
                await asyncio.sleep(0.02)
            assert info is not None and \
                info["failures"] > fails_before, \
                "corrupt re-probe should have failed, not promoted"
            # heal the device plane; a clean probe must re-promote
            self.injector.clear("device_storm")
            self.injector.clear("device_storm_probe")
            out_h = None
            path_h = None
            deadline = _time.monotonic() + probe_timeout
            while _time.monotonic() < deadline:
                out_h, path_h = probe.map_pgs_path(rid, xs, 2)
                if probe.kernel_quarantine_info() is None:
                    break
                await asyncio.sleep(0.05)
            assert probe.kernel_quarantine_info() is None, \
                "kernel path never re-promoted after faults cleared"
            assert path_h == "pallas-interpret", path_h
            assert np.array_equal(np.asarray(out_h), out0), \
                "re-promoted kernel output diverged"
            # every EC write served through the degrade ladder reads
            # back bit-identical now that the device plane healed
            for oid, data in ec_data.items():
                got = await io_ec.read(oid)
                assert got == data, \
                    f"degraded-path EC write {oid} corrupted"
        finally:
            wt.cancel()
            await asyncio.gather(wt, return_exceptions=True)
            self.injector.clear("device_storm")
            self.injector.clear("device_storm_probe")
        q1 = dm.perf.dump()

        def _delta(key):
            return int(q1.get(key, 0)) - int(q0.get(key, 0))
        agg_fb = sum(
            int(o.ec_agg.perf.dump().get("fallback_ops", 0)) +
            int(o.ec_agg.perf.dump().get("per_op_retries", 0))
            for o in self.c.osds)
        storm_errors = self._write_errors - errs0
        assert storm_errors == 0, \
            f"{storm_errors} client-visible errors under device storm"
        self._log(f"device storm: {ec_acked} EC writes served "
                  f"degraded, quarantine "
                  f"{_delta('quarantine_entries')} in / "
                  f"{_delta('quarantine_exits')} out, "
                  f"{_delta('quarantine_probes')} probes "
                  f"({_delta('quarantine_probe_failures')} refused)")
        return {"write_errors": storm_errors,
                "ec_writes_acked": ec_acked,
                "quarantine_entries": _delta("quarantine_entries"),
                "quarantine_exits": _delta("quarantine_exits"),
                "probes": _delta("quarantine_probes"),
                "probe_failures": _delta("quarantine_probe_failures"),
                "faults_injected": _delta("faults_injected"),
                "ec_degraded_ops": agg_fb,
                "repromoted_path": path_h}

    # -- proc-backend crash storm (round 18) -------------------------------
    async def proc_storm(self, io, settle_timeout: float = 180.0,
                         gray: bool = True) -> dict:
        """SIGKILL honesty under load (proc backend only): with a
        continuous unique-oid writer running, crash — in sequence —
        one OSD, the lead mon (when a majority survives it), and the
        active mgr, each with a REAL SIGKILL (no goodbye on the wire);
        let the supervisor restart each; optionally run one
        SIGSTOP/SIGCONT gray-failure pass (the frozen OSD must trip
        OSD_SLOW and heal on resume); then settle and verify.

        Invariants enforced: ZERO writer errors (the closed loop plus
        objecter retry must ride out every crash window), every acked
        write reads back bit-identical, every victim observed
        restarting, the mgr telemetry plane re-populates after the
        active mgr dies. Returns the summary dict."""
        c = self.c
        assert self.proc, "proc_storm needs backend='proc'"
        self._writer_task = asyncio.ensure_future(self._writer(io))
        restarts: dict[str, int] = {}
        mgr_failover = None
        try:
            await asyncio.sleep(0.5)        # writer gets a head start
            # 1: crash an OSD; the supervisor must bring it back
            victim = f"osd.{c.n_osds - 1}"
            before = c.children[victim].restarts
            c.kill_osd(c.n_osds - 1)
            self._log(f"SIGKILL {victim}")
            await c.wait_for_restart(victim, before, timeout=60.0)
            # the fresh incarnation must actually BOOT (asok answers,
            # reports up): wait_for_osds_up alone passes trivially
            # when the grace outlives the respawn and the dead osd
            # was never marked down
            await c.wait_for_daemon_ready(victim, timeout=60.0)
            await c.wait_for_osds_up(c.n_osds, timeout=90.0)
            restarts[victim] = c.children[victim].restarts - before
            # 2: crash the lead mon (only when quorum survives it)
            before_mons = {n: ch.restarts
                           for n, ch in c.children.items()
                           if n.startswith("mon.")}
            name = await c.kill_mon_leader()
            if name is not None:
                self.killed_mons += 1
                self._log(f"SIGKILL {name} (lead mon)")
                await c.wait_for_restart(name, before_mons[name],
                                         timeout=60.0)
                await c.wait_for_daemon_ready(name, timeout=60.0)
                restarts[name] = \
                    c.children[name].restarts - before_mons[name]
                # the reborn mon must rejoin a WORKING quorum
                ret, _, _ = await c.client.mon_command(
                    {"prefix": "status"}, timeout=30.0)
                assert ret == 0
            # 3: crash the active mgr; a standby must take over and
            # the telemetry plane must re-populate from fresh reports
            old = await c.kill_active_mgr()
            if old is not None:
                before_m = c.children[old].restarts
                self._log(f"SIGKILL {old} (active mgr)")
                new = await c.wait_for_mgr_active(
                    not_name=old.split(".", 1)[1], timeout=60.0)
                mgr_failover = (old, f"mgr.{new}")
                self._log(f"mgr failover -> mgr.{new}")
                deadline = asyncio.get_event_loop().time() + 60.0
                while True:
                    try:
                        out = await c.daemon_command(
                            f"mgr.{new}", "metrics")
                        # ceph_daemon rows exist only once daemons
                        # have REPORTED to this (fresh) mgr — the
                        # re-population proof, not a map-derived row
                        if "ceph_daemon" in out.get("body", ""):
                            break
                    except Exception:
                        pass
                    assert asyncio.get_event_loop().time() < \
                        deadline, "mgr metrics never re-populated"
                    await asyncio.sleep(0.3)
                await c.wait_for_restart(old, before_m, timeout=60.0)
                restarts[old] = c.children[old].restarts - before_m
            # 4: gray failure — frozen, not dead
            if gray:
                gray_id = 0
                c.pause_osd(gray_id)
                self._log(f"SIGSTOP osd.{gray_id}")
                await c.wait_for_health("OSD_SLOW", present=True,
                                        timeout=60.0)
                c.resume_osd(gray_id)
                self._log(f"SIGCONT osd.{gray_id}")
                await c.wait_for_health("OSD_SLOW", present=False,
                                        timeout=90.0)
                await c.wait_for_osds_up(c.n_osds, timeout=90.0)
        finally:
            self._writer_task.cancel()
            await asyncio.gather(self._writer_task,
                                 return_exceptions=True)
        await c.wait_for_clean(timeout=settle_timeout)
        assert self._write_errors == 0, \
            f"{self._write_errors} writer errors during proc storm"
        for oid, data in self.acked.items():
            got = await io.read(oid)
            assert got == data, \
                f"acked write {oid} corrupted by proc storm"
        assert sum(restarts.values()) >= 2, \
            f"expected supervisor restarts, saw {restarts}"
        summary = {
            "seed": self.seed,
            "acked_writes": len(self.acked),
            "failed_writes": self._write_errors,
            "restarts": restarts,
            "killed_mons": self.killed_mons,
            "mgr_failover": mgr_failover,
        }
        self._log(f"proc storm done: {summary}")
        return summary

    async def settle_and_verify(self, io, timeout: float = 240.0,
                                fsck_stores=None) -> dict:
        """Heal everything, revive everything, converge, verify.
        Raises AssertionError on any invariant violation; returns a
        summary dict."""
        self.injector.clear_all()
        self.active_sets.clear()
        for victim in list(self.downed):
            store = self.store_factory(victim) \
                if self.store_factory is not None else None
            await self.c.revive_osd(victim, store=store)
            self._log(f"final revive osd.{victim}")
        self.downed.clear()
        await self.c.wait_for_clean(timeout=timeout)
        # 2: every acked write readable and intact
        for oid, data in self.acked.items():
            got = await io.read(oid)
            assert got == data, \
                f"acked write {oid} corrupted after thrash"
        # 3: stores fsck clean
        checked = 0
        for st in (fsck_stores if fsck_stores is not None
                   else [o.store for o in self.c.osds]):
            if hasattr(st, "fsck"):
                errs = st.fsck()
                assert errs == [], f"store fsck after thrash: {errs}"
                checked += 1
        # 4: the mon cluster answers
        status = await self.c.client.status()
        assert status["osdmap"]["num_up_osds"] == len(self.c.osds)
        return {
            "seed": self.seed,
            "actions": len(self.actions_log),
            "acked_writes": len(self.acked),
            "failed_writes": self._write_errors,
            "fscked_stores": checked,
            "killed_mons": self.killed_mons,
        }
