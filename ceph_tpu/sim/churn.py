"""Map-churn rebalance simulator.

Replays a sequence of cluster events (osd down/out/up/in/reweight/add)
against an OSDMap and measures the placement delta each epoch causes:
how many PGs remapped, how many shard-slots moved (the proxy for data
migration volume), and whether placement converges back to full sets.

ref: the thrash suites (qa/tasks/ceph_manager.py Thrasher) exercise this
live against daemons; src/tools/osdmaptool.cc --test-map-pgs measures the
static distribution. Here the whole cluster's placement is recomputed per
epoch as one batched CRUSH program, so a 100M-PG churn sweep is a handful
of device steps.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.crush.types import ITEM_NONE, WEIGHT_ONE
from ceph_tpu.osd.osdmap import OSDMap


@dataclass(frozen=True)
class ChurnEvent:
    """One cluster mutation. kind: down|up|out|in|reweight|add."""

    kind: str
    osd: int
    weight: int = WEIGHT_ONE
    bucket: int | None = None  # for `add`: CRUSH bucket to link under


@dataclass
class StepReport:
    """Placement delta produced by one event."""

    epoch: int
    event: ChurnEvent
    pgs_total: int
    pgs_remapped: int
    shards_moved: int
    shards_total: int
    degraded_pgs: int  # rows with at least one NONE slot
    primaries_changed: int

    @property
    def moved_fraction(self) -> float:
        return self.shards_moved / max(self.shards_total, 1)

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "event": f"{self.event.kind} osd.{self.event.osd}",
            "pgs_remapped": self.pgs_remapped,
            "shards_moved": self.shards_moved,
            "moved_fraction": round(self.moved_fraction, 6),
            "degraded_pgs": self.degraded_pgs,
            "primaries_changed": self.primaries_changed,
        }


def _delta(prev_up, prev_p, up, p, positional: bool) -> dict:
    if positional:
        moved = (prev_up != up) & ~((prev_up == ITEM_NONE) &
                                    (up == ITEM_NONE))
        shards_moved = int(moved.sum())
    else:
        # replicated sets are order-insensitive for data placement:
        # count shards now on osds that didn't hold the PG before
        fresh = ~(up[:, :, None] == prev_up[:, None, :]).any(axis=2)
        shards_moved = int((fresh & (up != ITEM_NONE)).sum())
    remapped = int(((prev_up != up).any(axis=1)).sum())
    return {
        "pgs_remapped": remapped,
        "shards_moved": shards_moved,
        "primaries_changed": int((prev_p != p).sum()),
    }


class ChurnSim:
    """Drive an OSDMap through events, recording per-epoch deltas."""

    def __init__(self, osdmap: OSDMap, pool_id: int):
        self.map = osdmap
        self.pool_id = pool_id
        self.pool = osdmap.pools[pool_id]
        self.history: list[StepReport] = []
        self._up, self._primary, _, _ = osdmap.map_pool(pool_id)

    def apply(self, ev: ChurnEvent) -> StepReport:
        m = self.map
        if ev.kind == "down":
            m.mark_down(ev.osd)
        elif ev.kind == "up":
            m.mark_up(ev.osd)
        elif ev.kind == "out":
            m.mark_out(ev.osd)
        elif ev.kind == "in":
            m.mark_in(ev.osd)
        elif ev.kind == "reweight":
            m.set_weight(ev.osd, ev.weight)
        elif ev.kind == "add":
            bucket = ev.bucket
            if bucket is None:
                # least-loaded host-type bucket (type of the leaf parents)
                hosts = [b for b in m.crush.buckets.values()
                         if b.items and all(i >= 0 for i in b.items)]
                bucket = min(hosts, key=lambda b: b.size).id
            m.insert_crush_item(ev.osd, ev.weight, bucket)
        elif ev.kind == "rm":
            m.remove_crush_item(ev.osd)
        else:
            raise ValueError(f"unknown event kind {ev.kind!r}")
        up, primary, _, _ = m.map_pool(self.pool_id)
        d = _delta(self._up, self._primary, up, primary,
                   positional=not self.pool.can_shift_osds())
        rep = StepReport(
            epoch=m.epoch, event=ev,
            pgs_total=up.shape[0],
            shards_total=up.size,
            degraded_pgs=int((up == ITEM_NONE).any(axis=1).sum()),
            **d)
        self._up, self._primary = up, primary
        self.history.append(rep)
        return rep

    def run(self, events: list[ChurnEvent]) -> list[StepReport]:
        return [self.apply(ev) for ev in events]

    def random_thrash(self, rng: np.random.Generator, steps: int,
                      revive: bool = True) -> list[StepReport]:
        """Thrasher-style chaos: random down/out with matching revives
        (ref: qa/tasks/ceph_manager.py Thrasher.thrash_while_going)."""
        reports = []
        downed: list[int] = []
        for _ in range(steps):
            if downed and (revive and rng.random() < 0.5):
                osd = downed.pop(rng.integers(len(downed)))
                reports.append(self.apply(ChurnEvent("up", osd)))
                reports.append(self.apply(ChurnEvent("in", osd)))
            else:
                alive = [o for o in range(self.map.max_osd)
                         if self.map.is_up(o) and o not in downed]
                if len(alive) <= self.pool.size:
                    continue
                osd = int(rng.choice(alive))
                downed.append(osd)
                reports.append(self.apply(ChurnEvent("down", osd)))
                reports.append(self.apply(ChurnEvent("out", osd)))
        return reports

    def summary(self) -> dict:
        tot_moved = sum(r.shards_moved for r in self.history)
        return {
            "events": len(self.history),
            "final_epoch": self.map.epoch,
            "total_shards_moved": tot_moved,
            "final_degraded_pgs": (self.history[-1].degraded_pgs
                                   if self.history else 0),
        }
