"""Closed-loop client-session load harness (the 10k-session front end).

ref: the role qa's `rados bench`/cosbench rigs play upstream — drive a
vstart cluster with MANY concurrent client sessions and measure what
the front end actually delivers: aggregate ops/s, per-op latency
percentiles (p50/p99/max), and the error count (which must be ZERO on
a healthy cluster — the harness is closed-loop, so backpressure shows
up as latency, never as lost ops).

Session model: every **session** is a closed loop — issue one op,
await the reply, think, repeat (`ops_per_session` times). Sessions are
LOGICAL: they multiplex over a bounded pool of real `Rados` handles
(``clients``), exactly how production client libraries run thousands
of application streams over a few messenger sessions. That keeps one
process honest at 10k+ sessions (10k raw TCP pairs would exhaust fd
limits long before the cluster is the bottleneck) while still pushing
every shared layer — messenger frames, Objecter tid tables, mon
subscription fan-out, OSD admission — to session-scale traffic.

Scaling cliffs this harness exposed (fixed in round 11):

- the mon's map-publish loop was one SERIAL await per subscriber per
  commit (``Monitor._publish_maps``) — now a bounded-concurrency
  fan-out;
- messenger key events scanned the whole connection table per auth
  change (``_conns_of``) — now a per-peer index;
- OSD admission was a FIFO whose saturation check was global — the
  scheduler's per-tenant queues made both O(1) per op.

Usage::

    report = await LoadGen(cluster, "pool",
                           sessions=10000, clients=16,
                           ops_per_session=5).run()
    assert report["errors"] == 0

The tier-1 smoke runs <= 200 sessions (tests/test_meta.py budget
guard); the full 10k run is ``@pytest.mark.slow``.

Scenario schedules (round 17): ``SCENARIOS`` holds named multi-phase
workload shapes — each phase runs one LoadGen fleet per pool to
completion (optionally firing a cluster event first) — and
``run_scenario`` drives them. They exist to exercise the mgr
TunerModule's policies with realistic load TRANSITIONS: the diurnal
ramp (does a quiet trough commit anything? it must not), the hot-pool
burst (the hot-pool protector's trip/heal cycle), and an OSD outage
landing mid-rush (the recovery governor's backfill-vs-QoS trade).
"""

from __future__ import annotations

import asyncio
import random
import time

from ceph_tpu.utils.logging import get_logger

log = get_logger("loadgen")


def percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class LoadGen:
    """Closed-loop session fleet against one pool.

    ``sessions`` logical sessions multiplex over ``clients`` real
    Rados handles (round-robin). Each session performs
    ``ops_per_session`` ops — a ``read_fraction`` of them reads over a
    small shared object set, the rest writes of ``write_bytes`` to the
    session's own object — with ``think_s`` between ops.
    ``concurrency`` bounds how many sessions are in flight at once
    (0 = all of them; the closed loop per session still applies)."""

    def __init__(self, cluster, pool: str, sessions: int = 100,
                 clients: int = 8, ops_per_session: int = 5,
                 write_bytes: int = 512, read_fraction: float = 0.25,
                 think_s: float = 0.0, op_timeout: float = 30.0,
                 concurrency: int = 0, seed: int = 0):
        self.cluster = cluster
        self.pool = pool
        self.sessions = int(sessions)
        self.clients = max(1, int(clients))
        self.ops_per_session = int(ops_per_session)
        self.write_bytes = int(write_bytes)
        self.read_fraction = float(read_fraction)
        self.think_s = float(think_s)
        self.op_timeout = float(op_timeout)
        self.concurrency = int(concurrency)
        self.seed = seed
        self.latencies: list[float] = []
        self.errors: list[tuple[str, str]] = []
        self._own: list = []

    async def _open_clients(self) -> list:
        """A bounded pool of real client handles. The cluster's admin
        client is reused as handle 0 (it already holds the maps); the
        rest are fresh Rados sessions under the admin entity. Appends
        to ``self._own`` as it connects, so a mid-loop failure leaves
        the already-open handles where run()'s cleanup finds them."""
        from ceph_tpu.rados import Rados
        ios = [await self.cluster.client.open_ioctx(self.pool)]
        for _ in range(self.clients - 1):
            r = Rados(self.cluster.client.monc.monmap,
                      keyring=self.cluster.keyring,
                      config=self.cluster.cfg)
            await r.connect()
            self._own.append(r)
            ios.append(await r.open_ioctx(self.pool))
        return ios

    async def _session(self, sid: int, io, rng: random.Random,
                       sem: asyncio.Semaphore | None) -> None:
        if sem is not None:
            await sem.acquire()
        try:
            oid = f"lg-{self.seed}-{sid}"
            payload = bytes([sid % 256]) * self.write_bytes
            wrote = False
            for i in range(self.ops_per_session):
                do_read = wrote and rng.random() < self.read_fraction
                t0 = time.perf_counter()
                try:
                    if do_read:
                        await io.read(oid, timeout=self.op_timeout)
                    else:
                        await io.write_full(oid, payload,
                                            timeout=self.op_timeout)
                        wrote = True
                    self.latencies.append(time.perf_counter() - t0)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    self.errors.append((f"{oid}#{i}", repr(e)))
                if self.think_s:
                    await asyncio.sleep(self.think_s)
        finally:
            if sem is not None:
                sem.release()

    async def run(self) -> dict:
        """Run the whole fleet; returns the load report."""
        rng = random.Random(self.seed)
        sem = asyncio.Semaphore(self.concurrency) \
            if self.concurrency > 0 else None
        t0 = time.perf_counter()
        try:
            # inside the cleanup scope: a mid-loop connect failure
            # must still shut down the handles opened before it
            ios = await self._open_clients()
            await asyncio.gather(*[
                self._session(sid, ios[sid % len(ios)],
                              random.Random(rng.random()), sem)
                for sid in range(self.sessions)])
        finally:
            for r in self._own:
                await r.shutdown()
            self._own = []
        wall = time.perf_counter() - t0
        lats = sorted(self.latencies)
        ops = len(lats)
        report = {
            "sessions": self.sessions,
            "clients": len(ios),
            "ops": ops,
            "errors": len(self.errors),
            "error_samples": self.errors[:4],
            "wall_s": round(wall, 3),
            "ops_per_s": round(ops / wall, 1) if wall > 0 else 0.0,
            "p50_ms": round(percentile(lats, 0.50) * 1e3, 2),
            "p99_ms": round(percentile(lats, 0.99) * 1e3, 2),
            "max_ms": round(percentile(lats, 1.0) * 1e3, 2),
        }
        log.dout(1, f"loadgen: {report['sessions']} sessions, "
                    f"{report['ops']} ops, {report['errors']} errors, "
                    f"{report['ops_per_s']} ops/s, "
                    f"p99 {report['p99_ms']} ms")
        return report


# -- scenario schedules (round 17) ----------------------------------------
# Each scenario is an ordered list of phases; a phase optionally fires
# one cluster event ("osd_out:<id>" / "osd_in:<id>") and then runs one
# closed-loop LoadGen fleet PER POOL concurrently to completion. The
# pool names are roles — run_scenario maps them to real pools. Session
# counts are smoke-sized; ``scale`` multiplies them for bigger rigs.
SCENARIOS: dict[str, list[dict]] = {
    # a compressed day: quiet -> peak -> quiet. The steady shape the
    # tuner must NOT act on (zero-commit acceptance).
    "diurnal_ramp": [
        {"name": "trough", "load": {"a": dict(
            sessions=6, ops_per_session=4, think_s=0.03)}},
        {"name": "peak", "load": {"a": dict(
            sessions=20, ops_per_session=6)}},
        {"name": "evening", "load": {"a": dict(
            sessions=6, ops_per_session=4, think_s=0.03)}},
    ],
    # one tenant pool goes hot while a cold tenant keeps its paced
    # trickle — the hot-pool protector's trip (burst) and heal (after)
    "hot_pool_burst": [
        {"name": "steady", "load": {"cold": dict(
            sessions=6, ops_per_session=4, think_s=0.02)}},
        {"name": "burst", "load": {
            "cold": dict(sessions=6, ops_per_session=4,
                         think_s=0.02),
            "hot": dict(sessions=24, ops_per_session=10)}},
        {"name": "after", "load": {"cold": dict(
            sessions=6, ops_per_session=4, think_s=0.02)}},
    ],
    # an OSD drops out in the middle of the rush: backfill pressure
    # lands ON TOP of peak client load — the recovery governor's
    # QoS-floor-vs-backfill trade, then the drain after the OSD
    # returns
    "backfill_storm_mid_rush": [
        {"name": "rush", "load": {"a": dict(
            sessions=16, ops_per_session=6)}},
        {"name": "outage", "event": "osd_out:1", "load": {"a": dict(
            sessions=16, ops_per_session=6)}},
        {"name": "return", "event": "osd_in:1", "load": {"a": dict(
            sessions=8, ops_per_session=4, think_s=0.02)}},
    ],
}


# -- worker-process sharding (round 18) -----------------------------------
class _WorkerCluster:
    """The minimal cluster facade a LoadGen needs (client, keyring,
    cfg), rebuilt inside a forked worker from the conf document — the
    same document a proc-backend daemon child reads."""

    def __init__(self, client, keyring, cfg):
        self.client = client
        self.keyring = keyring
        self.cfg = cfg


async def run_sharded(cluster, pool: str, sessions: int = 1000,
                      workers: int = 1, clients: int = 8,
                      ops_per_session: int = 5, write_bytes: int = 512,
                      read_fraction: float = 0.25, think_s: float = 0.0,
                      op_timeout: float = 30.0, concurrency: int = 0,
                      seed: int = 0) -> dict:
    """Shard ``sessions`` across ``workers`` FORKED worker processes,
    each running its own LoadGen fleet over its own real client
    handles against the same cluster (in-process or proc backend —
    the wire doesn't care), and merge the reports: summed ops/errors,
    percentiles over the CONCATENATED latency population (a
    per-worker p99 average would hide a slow shard), wall = the
    slowest worker. One worker still exercises the whole path (conf
    hand-off, fork, merge) at tier-1 cost."""
    import json as _json
    import os
    import sys
    import tempfile

    from ceph_tpu.cluster.conf import write_conf
    workers = max(1, int(workers))
    sessions = int(sessions)
    conf_path = getattr(cluster, "conf_path", None)
    tmp = None
    if conf_path is None or not os.path.exists(conf_path):
        fd, tmp = tempfile.mkstemp(prefix="lg_conf_", suffix=".json")
        os.close(fd)
        write_conf(tmp, cluster.client.monc.monmap, cluster.keyring,
                   config=cluster.cfg)
        conf_path = tmp
    shard = [sessions // workers +
             (1 if w < sessions % workers else 0)
             for w in range(workers)]

    async def _one(w: int) -> dict:
        params = dict(conf=conf_path, pool=pool, sessions=shard[w],
                      clients=clients, ops_per_session=ops_per_session,
                      write_bytes=write_bytes,
                      read_fraction=read_fraction, think_s=think_s,
                      op_timeout=op_timeout, concurrency=concurrency,
                      seed=seed * 1000 + w + 1)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "ceph_tpu.sim.loadgen", "--worker",
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE, env=env)
        out, _ = await proc.communicate(_json.dumps(params).encode())
        if proc.returncode != 0:
            raise RuntimeError(
                f"loadgen worker {w} exited {proc.returncode}")
        # the report is the LAST stdout line; anything above is noise
        return _json.loads(out.decode().strip().splitlines()[-1])

    t0 = time.perf_counter()
    try:
        reports = await asyncio.gather(
            *[_one(w) for w in range(workers) if shard[w] > 0])
    finally:
        if tmp is not None:
            os.unlink(tmp)
    wall = time.perf_counter() - t0
    lats = sorted(x for r in reports for x in r.pop("lats"))
    ops = len(lats)
    merged = {
        "sessions": sessions,
        "workers": len(reports),
        "ops": ops,
        "errors": sum(r["errors"] for r in reports),
        "error_samples": [s for r in reports
                          for s in r["error_samples"]][:4],
        "wall_s": round(wall, 3),
        "ops_per_s": round(ops / wall, 1) if wall > 0 else 0.0,
        "p50_ms": round(percentile(lats, 0.50) * 1e3, 2),
        "p99_ms": round(percentile(lats, 0.99) * 1e3, 2),
        "max_ms": round(percentile(lats, 1.0) * 1e3, 2),
        "per_worker": reports,
    }
    log.dout(1, f"loadgen sharded: {sessions} sessions / "
                f"{len(reports)} workers, {ops} ops, "
                f"{merged['errors']} errors, "
                f"{merged['ops_per_s']} ops/s, "
                f"p99 {merged['p99_ms']} ms")
    return merged


async def _worker_main() -> None:
    """``python -m ceph_tpu.sim.loadgen --worker``: params JSON on
    stdin, merged-ready report JSON as the last stdout line."""
    import json as _json
    import sys

    from ceph_tpu.cluster.conf import (
        conf_keyring,
        conf_monmap,
        read_conf_doc,
    )
    from ceph_tpu.rados import Rados
    params = _json.loads(sys.stdin.read())
    doc = read_conf_doc(params["conf"])
    cfg = dict(doc.get("config") or {})
    client = Rados(conf_monmap(doc), keyring=conf_keyring(doc),
                   config=cfg)
    ret, rs, _ = await client.mon_command({"prefix": "status"},
                                          timeout=30.0)
    assert ret == 0, rs
    await client.connect()
    shim = _WorkerCluster(client, client.monc.msgr.keyring, cfg)
    lg = LoadGen(shim, params["pool"], sessions=params["sessions"],
                 clients=params["clients"],
                 ops_per_session=params["ops_per_session"],
                 write_bytes=params["write_bytes"],
                 read_fraction=params["read_fraction"],
                 think_s=params["think_s"],
                 op_timeout=params["op_timeout"],
                 concurrency=params["concurrency"],
                 seed=params["seed"])
    report = await lg.run()
    report["lats"] = [round(x, 6) for x in lg.latencies]
    await client.shutdown()
    sys.stdout.write("\n" + _json.dumps(report) + "\n")
    sys.stdout.flush()


async def run_scenario(cluster, name: str,
                       pools: dict[str, str] | None = None,
                       scale: float = 1.0, seed: int = 0,
                       clients: int = 4) -> dict:
    """Drive one named scenario: per phase, fire its event (if any)
    through the admin client, then run every pool's LoadGen fleet
    concurrently to completion. ``pools`` maps the scenario's role
    names to real pool names (identity when omitted — the pools must
    already exist). Returns per-phase reports keyed by role."""
    sched = SCENARIOS[name]
    pools = pools or {}
    phases = []
    for pi, phase in enumerate(sched):
        event = phase.get("event")
        if event:
            verb, _, arg = event.partition(":")
            prefix = {"osd_out": "osd out",
                      "osd_in": "osd in"}[verb]
            ret, rs, _ = await cluster.client.mon_command(
                {"prefix": prefix, "id": int(arg)})
            if ret != 0:
                raise RuntimeError(f"scenario event {event}: {rs}")
        gens = {
            role: LoadGen(cluster, pools.get(role, role),
                          clients=clients,
                          seed=seed * 1000 + pi,
                          **{**kw, "sessions": max(
                              1, int(kw["sessions"] * scale))})
            for role, kw in phase["load"].items()}
        reports = dict(zip(gens, await asyncio.gather(
            *[g.run() for g in gens.values()])))
        phases.append({"name": phase["name"], "event": event,
                       "reports": reports})
        log.dout(1, f"scenario {name}/{phase['name']}: " + ", ".join(
            f"{r}={reports[r]['ops_per_s']} ops/s "
            f"(p99 {reports[r]['p99_ms']} ms)" for r in reports))
    return {"scenario": name, "phases": phases}


if __name__ == "__main__":
    import sys as _sys

    import jax as _jax
    _jax.config.update("jax_platforms", "cpu")
    if "--worker" in _sys.argv:
        asyncio.run(_worker_main())
    else:
        raise SystemExit("usage: python -m ceph_tpu.sim.loadgen "
                         "--worker  (params JSON on stdin)")
