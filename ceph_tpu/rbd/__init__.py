"""librbd analog: block images striped over RADOS objects.

ref: src/librbd/ (librbd::RBD / librbd::Image) — an image is a header
object (``rbd_header.<name>``: size/order/features in omap) plus data
objects ``rbd_data.<name>.<N>`` of ``2^order`` bytes each; image I/O
maps byte extents onto those objects exactly like the reference's
Striper (ref: src/osdc/Striper.cc with stripe_count=1). The API keeps
the reference's names: RBD.create/list/remove, Image.read/write/
resize/size/stat.

This is also this framework's libradosstriper seat: large-object
striping over many RADOS objects, client-side.
"""

from __future__ import annotations

import json

from ceph_tpu.rados import IoCtx, ObjectOperationError

__all__ = ["RBD", "Image"]

RBD_DIRECTORY = "rbd_directory"


def _header(name: str) -> str:
    return f"rbd_header.{name}"


def _data(name: str, idx: int) -> str:
    return f"rbd_data.{name}.{idx:016x}"


class RBD:
    """ref: librbd::RBD — image management on one pool."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    async def create(self, name: str, size: int,
                     order: int = 22) -> None:
        """ref: RBD::create (order = log2 object size, default 4 MiB)."""
        if not (12 <= order <= 26):
            raise ValueError("order must be in [12, 26]")
        existing = await self.list()
        if name in existing:
            raise ObjectOperationError(-17, f"image {name} exists")
        io = self.ioctx
        await io.set_omap(_header(name), "meta", json.dumps(
            {"size": size, "order": order}).encode())
        await io.set_omap(RBD_DIRECTORY, name, b"1")

    async def list(self) -> list[str]:
        try:
            return sorted(await self.ioctx.get_omap_vals(RBD_DIRECTORY))
        except ObjectOperationError:
            return []

    async def remove(self, name: str) -> None:
        """ref: RBD::remove — data objects, header, directory entry."""
        img = await self.open(name)
        for idx in img._object_range(0, img.size_bytes):
            try:
                await self.ioctx.remove(_data(name, idx))
            except ObjectOperationError:
                pass
        await self.ioctx.remove(_header(name))
        try:
            await self.ioctx.rm_omap_key(RBD_DIRECTORY, name)
        except ObjectOperationError:
            pass

    async def open(self, name: str) -> "Image":
        io = self.ioctx
        try:
            omap = await io.get_omap_vals(_header(name))
        except ObjectOperationError:
            raise ObjectOperationError(-2, f"no image {name}") from None
        if "meta" not in omap:
            raise ObjectOperationError(-2, f"no image {name}")
        meta = json.loads(omap["meta"])
        return Image(io, name, meta["size"], meta["order"])


class Image:
    """ref: librbd::Image — byte-addressed I/O over the data objects."""

    def __init__(self, ioctx: IoCtx, name: str, size: int, order: int):
        self.ioctx = ioctx
        self.name = name
        self.size_bytes = size
        self.order = order
        self.obj_size = 1 << order

    def _object_range(self, offset: int, length: int) -> list[int]:
        if length <= 0:
            return []
        first = offset // self.obj_size
        last = (offset + length - 1) // self.obj_size
        return list(range(first, last + 1))

    async def size(self) -> int:
        return self.size_bytes

    async def write(self, offset: int, data: bytes) -> int:
        """ref: Image::write — extent-split across data objects."""
        if offset + len(data) > self.size_bytes:
            raise ObjectOperationError(-27, "write past image size")
        done = 0
        while done < len(data):
            abs_off = offset + done
            idx = abs_off // self.obj_size
            within = abs_off % self.obj_size
            n = min(self.obj_size - within, len(data) - done)
            await self.ioctx.write(_data(self.name, idx),
                                   data[done:done + n], offset=within)
            done += n
        return done

    async def read(self, offset: int, length: int) -> bytes:
        """ref: Image::read — absent data objects read as zeros."""
        length = min(length, max(self.size_bytes - offset, 0))
        out = bytearray(length)
        done = 0
        while done < length:
            abs_off = offset + done
            idx = abs_off // self.obj_size
            within = abs_off % self.obj_size
            n = min(self.obj_size - within, length - done)
            try:
                piece = await self.ioctx.read(
                    _data(self.name, idx), length=n, offset=within)
                out[done:done + len(piece)] = piece
            except ObjectOperationError:
                pass                       # sparse: zeros
            done += n
        return bytes(out)

    async def resize(self, new_size: int) -> None:
        """ref: Image::resize — shrink drops whole trailing objects."""
        if new_size < self.size_bytes:
            for idx in self._object_range(
                    new_size, self.size_bytes - new_size):
                if idx * self.obj_size >= new_size:
                    try:
                        await self.ioctx.remove(_data(self.name, idx))
                    except ObjectOperationError:
                        pass
                elif new_size % self.obj_size:
                    try:
                        await self.ioctx.truncate(
                            _data(self.name, idx),
                            new_size % self.obj_size)
                    except ObjectOperationError:
                        pass
        self.size_bytes = new_size
        await self.ioctx.set_omap(_header(self.name), "meta", json.dumps(
            {"size": new_size, "order": self.order}).encode())

    async def stat(self) -> dict:
        """ref: Image::stat (info_t)."""
        return {"size": self.size_bytes, "order": self.order,
                "obj_size": self.obj_size,
                "num_objs": -(-self.size_bytes // self.obj_size),
                "block_name_prefix": f"rbd_data.{self.name}"}
