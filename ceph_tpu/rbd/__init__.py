"""librbd analog: block images striped over RADOS objects.

ref: src/librbd/ (librbd::RBD / librbd::Image) — an image is a header
object (``rbd_header.<name>``: size/order/features in omap) plus data
objects ``rbd_data.<name>.<N>`` of ``2^order`` bytes each; image I/O
maps byte extents onto those objects exactly like the reference's
Striper (ref: src/osdc/Striper.cc with stripe_count=1). The API keeps
the reference's names: RBD.create/list/remove/clone, Image.read/write/
resize/size/stat/snap_*.

Snapshots (round 4) ride the RADOS self-managed snap machinery
(ref: librbd snapshots are selfmanaged snaps + the image snapc):
snap_create allocates a pool snap id and records it in the header;
writes carry the image snap context so the OSD clones-on-write; reads
of an Image opened at a snapshot pass the snap id down. Clones are
copy-on-write children referencing a PROTECTED parent snapshot with
client-side fallthrough reads and copy-up on first write, like the
reference's layering (ref: src/librbd/io/CopyupRequest).

Header note (round 20): the header omap's ``meta`` blob is the
image's whole control plane — ``size``/``order`` plus ``snaps``
(name -> {id, size-at-snap}), ``protected`` (snap names), ``parent``
({image, snap} for clone children) and ``children``
([(child, parent-snap)] on the PARENT). Every refusal decision
(snap_remove/unprotect/clone/remove) re-reads the header first
(``Image._refresh_meta``) instead of trusting open-time state:
upstream serializes these through cls_rbd on the header object, and
the re-read is this client's seat for that atomicity — deciding on a
stale ``children`` list is exactly the open-clone-child race the
errno-matrix test pins (-EBUSY on unprotect/rm with children, which
applies even to an UNprotected snap: a crash between clone and
protect must not strand the child).

Incremental replication (round 5): ``Image.export_diff`` /
``import_diff`` speak the ``rbd diff v1`` tagged stream
(from-snap/to-snap/size/write/zero records), so snapshots chain
between clusters the way ``rbd export-diff | rbd import-diff`` does.

This is also this framework's libradosstriper seat: large-object
striping over many RADOS objects, client-side.
"""

from __future__ import annotations

import json
import struct

from ceph_tpu.rados import IoCtx, ObjectOperationError

__all__ = ["RBD", "Image"]

RBD_DIRECTORY = "rbd_directory"


def _header(name: str) -> str:
    return f"rbd_header.{name}"


def _data(name: str, idx: int) -> str:
    return f"rbd_data.{name}.{idx:016x}"


class RBD:
    """ref: librbd::RBD — image management on one pool."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    async def create(self, name: str, size: int,
                     order: int = 22) -> None:
        """ref: RBD::create (order = log2 object size, default 4 MiB)."""
        if not (12 <= order <= 26):
            raise ValueError("order must be in [12, 26]")
        existing = await self.list()
        if name in existing:
            raise ObjectOperationError(-17, f"image {name} exists")
        io = self.ioctx
        await io.set_omap(_header(name), "meta", json.dumps(
            {"size": size, "order": order}).encode())
        await io.set_omap(RBD_DIRECTORY, name, b"1")

    async def list(self) -> list[str]:
        try:
            return sorted(await self.ioctx.get_omap_vals(RBD_DIRECTORY))
        except ObjectOperationError:
            return []

    async def remove(self, name: str) -> None:
        """ref: RBD::remove — data objects, header, directory entry.
        Refuses while snapshots exist (like the reference)."""
        img = await self.open(name)
        if img.snaps:
            raise ObjectOperationError(-39, "image has snapshots")
        for idx in img._object_range(0, img.size_bytes):
            try:
                await self.ioctx.remove(_data(name, idx))
            except ObjectOperationError:
                pass
        await self.ioctx.remove(_header(name))
        try:
            await self.ioctx.rm_omap_key(RBD_DIRECTORY, name)
        except ObjectOperationError:
            pass
        # a removed clone must drop off its parent's children list, or
        # the parent snap can never be unprotected/removed (ref:
        # librbd::image::RemoveRequest child detach)
        parent_ref = img.meta.get("parent")
        if parent_ref:
            try:
                parent = await self.open(parent_ref["image"])
                kids = parent.meta.get("children", [])
                kept = [c for c in kids if c[0] != name]
                if kept != kids:
                    parent.meta["children"] = kept
                    await parent._save_meta()
            except ObjectOperationError:
                pass                    # parent already gone

    async def open(self, name: str, snapshot: str | None = None) -> "Image":
        """ref: RBD::open / Image::snap_set — ``snapshot`` opens a
        read-only view at that snap."""
        io = self.ioctx
        try:
            omap = await io.get_omap_vals(_header(name))
        except ObjectOperationError:
            raise ObjectOperationError(-2, f"no image {name}") from None
        if "meta" not in omap:
            raise ObjectOperationError(-2, f"no image {name}")
        meta = json.loads(omap["meta"])
        img = Image(io, name, meta["size"], meta["order"], meta=meta,
                    rbd=self)
        if snapshot is not None:
            if snapshot not in img.snaps:
                raise ObjectOperationError(-2, f"no snap {snapshot}")
            img.snap_name = snapshot
            img.snap_id = img.snaps[snapshot]["id"]
            img.size_bytes = img.snaps[snapshot]["size"]
        return img

    async def clone(self, parent_name: str, snap_name: str,
                    child_name: str) -> None:
        """Copy-on-write child of a PROTECTED parent snapshot
        (ref: RBD::clone; parent must be protected first)."""
        parent = await self.open(parent_name)
        snap = parent.snaps.get(snap_name)
        if snap is None:
            raise ObjectOperationError(-2, f"no snap {snap_name}")
        if snap_name not in parent.meta.get("protected", []):
            raise ObjectOperationError(-22,
                                       f"snap {snap_name} not protected")
        existing = await self.list()
        if child_name in existing:
            raise ObjectOperationError(-17, f"image {child_name} exists")
        meta = {"size": snap["size"], "order": parent.order,
                "parent": {"image": parent_name, "snap": snap_name,
                           "snap_id": snap["id"]}}
        await self.ioctx.set_omap(_header(child_name), "meta",
                                  json.dumps(meta).encode())
        await self.ioctx.set_omap(RBD_DIRECTORY, child_name, b"1")
        # record the child on the parent so protected snaps with
        # children refuse removal (ref: rbd_children tracking)
        children = parent.meta.setdefault("children", [])
        if [child_name, snap_name] not in children:
            children.append([child_name, snap_name])
            await parent._save_meta()


class Image:
    """ref: librbd::Image — byte-addressed I/O over the data objects."""

    def __init__(self, ioctx: IoCtx, name: str, size: int, order: int,
                 meta: dict | None = None, rbd: "RBD | None" = None):
        self.ioctx = ioctx
        self.name = name
        self.size_bytes = size
        self.order = order
        self.obj_size = 1 << order
        self.meta = meta if meta is not None else {"size": size,
                                                   "order": order}
        self.rbd = rbd
        # snaps: name -> {"id": snapid, "size": size_at_snap}
        self.snaps: dict[str, dict] = self.meta.get("snaps", {})
        self.snap_name: str | None = None    # opened-at-snap view
        self.snap_id = 0
        self.parent = self.meta.get("parent")

    # -- snapshot plumbing -------------------------------------------------
    def _snapc(self) -> tuple | None:
        """The image's write snap context: (newest id, all ids desc)
        (ref: librbd ImageCtx::snapc)."""
        ids = sorted((s["id"] for s in self.snaps.values()),
                     reverse=True)
        return (ids[0], ids) if ids else None

    async def _save_meta(self) -> None:
        self.meta["size"] = self.size_bytes
        self.meta["order"] = self.order
        self.meta["snaps"] = self.snaps
        await self.ioctx.set_omap(_header(self.name), "meta",
                                  json.dumps(self.meta).encode())

    def _assert_writable(self) -> None:
        if self.snap_name is not None:
            raise ObjectOperationError(-30, "snapshot view is read-only")

    async def _refresh_meta(self) -> None:
        """Re-read the header before a refusal decision. The children
        list lives in the parent's header omap; another handle's
        clone()/remove() mutates it AFTER this Image was opened, so
        deciding unprotect/rm on the open-time snapshot of meta races
        an open clone child (ref: upstream serializes these through
        cls_rbd on the header object — the re-read is this client's
        seat for that atomicity)."""
        omap = await self.ioctx.get_omap_vals(_header(self.name))
        if "meta" not in omap:
            raise ObjectOperationError(-2, f"no image {self.name}")
        self.meta = json.loads(omap["meta"])
        self.snaps = self.meta.get("snaps", {})
        self.parent = self.meta.get("parent")

    async def snap_create(self, snap_name: str) -> int:
        """ref: Image::snap_create — allocate a self-managed snap id,
        record it; subsequent writes clone-on-write at the OSD."""
        self._assert_writable()
        if snap_name in self.snaps:
            raise ObjectOperationError(-17, f"snap {snap_name} exists")
        sid = await self.ioctx.selfmanaged_snap_create()
        self.snaps[snap_name] = {"id": sid, "size": self.size_bytes}
        await self._save_meta()
        return sid

    async def snap_list(self) -> list[dict]:
        return [{"name": n, "id": s["id"], "size": s["size"]}
                for n, s in sorted(self.snaps.items(),
                                   key=lambda kv: kv[1]["id"])]

    async def snap_protect(self, snap_name: str) -> None:
        """ref: Image::snap_protect — -EBUSY when already protected
        (the reference's errno, pinned by the snap matrix test)."""
        await self._refresh_meta()
        if snap_name not in self.snaps:
            raise ObjectOperationError(-2, f"no snap {snap_name}")
        prot = self.meta.setdefault("protected", [])
        if snap_name in prot:
            raise ObjectOperationError(
                -16, f"snap {snap_name} already protected")
        prot.append(snap_name)
        await self._save_meta()

    async def snap_unprotect(self, snap_name: str) -> None:
        """ref: Image::snap_unprotect — -ENOENT for a missing snap,
        -EINVAL when not protected, -EBUSY while clone children
        reference it. Decides on a FRESH header read: a clone created
        through another handle after this one opened must still
        refuse (the open-child race in the children list)."""
        await self._refresh_meta()
        if snap_name not in self.snaps:
            raise ObjectOperationError(-2, f"no snap {snap_name}")
        if snap_name not in self.meta.get("protected", []):
            raise ObjectOperationError(
                -22, f"snap {snap_name} is not protected")
        children = [c for c in self.meta.get("children", [])
                    if c[1] == snap_name]
        if children:
            raise ObjectOperationError(-16, "snap has clone children")
        self.meta["protected"].remove(snap_name)
        await self._save_meta()

    async def snap_remove(self, snap_name: str) -> None:
        """ref: Image::snap_remove — trims the snap from every data
        object's clones, then drops it from the header and pool.
        Children are checked independently of protection: the
        protect flag and the children list are written in separate
        header updates, so a crash can strand children on an
        unprotected snap — their parent data must still refuse to
        die (-EBUSY, same as the reference's list_children gate)."""
        await self._refresh_meta()
        snap = self.snaps.get(snap_name)
        if snap is None:
            raise ObjectOperationError(-2, f"no snap {snap_name}")
        if snap_name in self.meta.get("protected", []):
            raise ObjectOperationError(-16, f"snap {snap_name} protected")
        if any(c[1] == snap_name
               for c in self.meta.get("children", [])):
            raise ObjectOperationError(-16, "snap has clone children")
        top = max(self.size_bytes, snap["size"])
        for idx in self._object_range(0, top):
            try:
                await self.ioctx.snap_trim(_data(self.name, idx),
                                           snap["id"])
            except ObjectOperationError:
                pass
        await self.ioctx.selfmanaged_snap_remove(snap["id"])
        self.snaps.pop(snap_name, None)
        await self._save_meta()

    async def snap_rollback(self, snap_name: str) -> None:
        """ref: Image::snap_rollback — per-object restore of the snap
        state (itself snapc-protected, so newer snaps still see the
        pre-rollback data)."""
        self._assert_writable()
        snap = self.snaps.get(snap_name)
        if snap is None:
            raise ObjectOperationError(-2, f"no snap {snap_name}")
        sid = snap["id"]
        snapc = self._snapc()
        top = max(self.size_bytes, snap["size"])
        for idx in self._object_range(0, top):
            oid = _data(self.name, idx)
            try:
                old = await self.ioctx.read(oid, snap_id=sid)
            except ObjectOperationError:
                # object absent at snap time: drop the head too
                try:
                    await self.ioctx.remove(oid, snapc=snapc)
                except ObjectOperationError:
                    pass
                continue
            await self.ioctx.write_full(oid, old, snapc=snapc)
        self.size_bytes = snap["size"]
        await self._save_meta()

    def _object_range(self, offset: int, length: int) -> list[int]:
        if length <= 0:
            return []
        first = offset // self.obj_size
        last = (offset + length - 1) // self.obj_size
        return list(range(first, last + 1))

    async def size(self) -> int:
        return self.size_bytes

    async def _parent_image(self) -> "Image":
        if getattr(self, "_parent_img", None) is None:
            self._parent_img = await self.rbd.open(
                self.parent["image"], snapshot=self.parent["snap"])
        return self._parent_img

    async def _copyup(self, idx: int) -> None:
        """First write to a cloned object: materialize the parent
        snap's content in the child first (ref: io/CopyupRequest)."""
        oid = _data(self.name, idx)
        try:
            await self.ioctx.stat(oid)
            return                          # child object exists
        except ObjectOperationError as e:
            if e.errno != -2:
                # a timeout/transport error is NOT "absent": assuming
                # so would overwrite newer child data with the parent
                # snapshot's content (r4 review finding)
                raise
        parent = await self._parent_image()
        off = idx * self.obj_size
        if off >= parent.size_bytes:
            return
        data = await parent.read(off, self.obj_size)
        if data.rstrip(b"\x00"):
            await self.ioctx.write_full(oid, data, snapc=self._snapc())

    async def write(self, offset: int, data: bytes) -> int:
        """ref: Image::write — extent-split across data objects; the
        image snapc rides every object write (clone-on-write for
        snapshots); clone children copy-up before the first write."""
        self._assert_writable()
        if offset + len(data) > self.size_bytes:
            raise ObjectOperationError(-27, "write past image size")
        snapc = self._snapc()
        done = 0
        while done < len(data):
            abs_off = offset + done
            idx = abs_off // self.obj_size
            within = abs_off % self.obj_size
            n = min(self.obj_size - within, len(data) - done)
            if self.parent is not None:
                await self._copyup(idx)
            await self.ioctx.write(_data(self.name, idx),
                                   data[done:done + n], offset=within,
                                   snapc=snapc)
            done += n
        return done

    async def read(self, offset: int, length: int) -> bytes:
        """ref: Image::read — absent data objects read as zeros; clone
        children fall through to the parent snapshot (layering)."""
        length = min(length, max(self.size_bytes - offset, 0))
        out = bytearray(length)
        done = 0
        while done < length:
            abs_off = offset + done
            idx = abs_off // self.obj_size
            within = abs_off % self.obj_size
            n = min(self.obj_size - within, length - done)
            try:
                piece = await self.ioctx.read(
                    _data(self.name, idx), length=n, offset=within,
                    snap_id=self.snap_id)
                out[done:done + len(piece)] = piece
            except ObjectOperationError as e:
                if e.errno != -2:
                    raise   # timeout/transport error != sparse object
                if self.parent is not None:
                    parent = await self._parent_image()
                    if abs_off < parent.size_bytes:
                        piece = await parent.read(abs_off, n)
                        out[done:done + len(piece)] = piece
                # else sparse: zeros
            done += n
        return bytes(out)

    async def resize(self, new_size: int) -> None:
        """ref: Image::resize — shrink drops whole trailing objects
        (snapc-protected, so snapshots keep the dropped data)."""
        self._assert_writable()
        snapc = self._snapc()
        if new_size < self.size_bytes:
            for idx in self._object_range(
                    new_size, self.size_bytes - new_size):
                if idx * self.obj_size >= new_size:
                    try:
                        await self.ioctx.remove(_data(self.name, idx),
                                                snapc=snapc)
                    except ObjectOperationError:
                        pass
                elif new_size % self.obj_size:
                    try:
                        await self.ioctx.truncate(
                            _data(self.name, idx),
                            new_size % self.obj_size, snapc=snapc)
                    except ObjectOperationError:
                        pass
        self.size_bytes = new_size
        await self._save_meta()

    async def stat(self) -> dict:
        """ref: Image::stat (info_t)."""
        return {"size": self.size_bytes, "order": self.order,
                "obj_size": self.obj_size,
                "num_objs": -(-self.size_bytes // self.obj_size),
                "block_name_prefix": f"rbd_data.{self.name}"}

    # -- incremental export/import ----------------------------------------
    # ref: rbd export-diff / import-diff (src/tools/rbd/action/
    # ExportDiff.cc + ImportDiff.cc); stream format per
    # doc/dev/rbd-diff.rst "rbd diff v1": magic, then tagged records
    # f=from-snap, t=to-snap, s=size, w=offset/length/data,
    # z=offset/length (zeroed extent), e=end. Diffs chain: export-diff
    # from snap A at snap B, import-diff onto a copy holding A,
    # snap B appears — incremental replication without shipping the
    # whole image.

    DIFF_MAGIC = b"rbd diff v1\n"
    _DIFF_GRAIN = 4096

    async def export_diff(self, from_snap: str | None = None) -> bytes:
        """The v1 diff stream from ``from_snap`` to THIS view (open the
        image at a snapshot to export up to that snap; at head for
        up-to-now). ``from_snap=None`` exports the full view (every
        allocated extent)."""
        fv = None
        if from_snap is not None:
            s = self.snaps.get(from_snap)
            if s is None:
                raise ObjectOperationError(-2, f"no snap {from_snap}")
            if self.snap_name is not None and \
                    s["id"] >= self.snap_id:
                raise ObjectOperationError(
                    -22, "from_snap is not older than the exported view")
            fv = Image(self.ioctx, self.name, s["size"], self.order,
                       meta=self.meta, rbd=self.rbd)
            fv.snap_name = from_snap
            fv.snap_id = s["id"]
        out = [self.DIFF_MAGIC]
        if from_snap is not None:
            nb = from_snap.encode()
            out.append(b"f" + struct.pack("<I", len(nb)) + nb)
        if self.snap_name is not None:
            nb = self.snap_name.encode()
            out.append(b"t" + struct.pack("<I", len(nb)) + nb)
        out.append(b"s" + struct.pack("<Q", self.size_bytes))
        g = self._DIFF_GRAIN
        nobj = -(-self.size_bytes // self.obj_size)
        for idx in range(nobj):
            off0 = idx * self.obj_size
            blen = min(self.obj_size, self.size_bytes - off0)
            b = await self.read(off0, blen)
            if fv is not None and off0 < fv.size_bytes:
                a = await fv.read(off0,
                                  min(self.obj_size,
                                      fv.size_bytes - off0))
            else:
                a = b""
            a = a.ljust(len(b), b"\0")
            # classify per grain, merge adjacent same-kind runs. The
            # loop always takes one final kind="end" pass — even when
            # the object's length is not a grain multiple — so an open
            # run covering the tail ALWAYS flushes (a `while pos <=
            # len` guard silently dropped the last run of any object
            # with len % grain != 0).
            run_kind, run_start = None, 0
            pos = 0
            while True:
                if pos < len(b):
                    ca = a[pos:pos + g]
                    cb = b[pos:pos + g]
                    kind = None if ca == cb else \
                        ("z" if cb.strip(b"\0") == b"" else "w")
                else:
                    kind = "end"
                    pos = len(b)     # clamp: the closing flush must
                                     # not overstate a tail z-extent
                if kind != run_kind:
                    if run_kind == "w":
                        data = b[run_start:pos]
                        out.append(b"w" + struct.pack(
                            "<QQ", off0 + run_start, len(data)) + data)
                    elif run_kind == "z":
                        out.append(b"z" + struct.pack(
                            "<QQ", off0 + run_start, pos - run_start))
                    run_kind, run_start = kind, pos
                if kind == "end":
                    break
                pos += g
        out.append(b"e")
        return b"".join(out)

    async def import_diff(self, stream: bytes) -> None:
        """Apply a v1 diff stream to this (head, writable) image: the
        from-snap must exist here, the to-snap is created after the
        data lands (ref: ImportDiff.cc ordering). Every record read is
        bounds-checked: a stream truncated mid-record raises a clean
        ObjectOperationError(-22) instead of leaking struct.error to
        callers like rbd_cli."""
        self._assert_writable()
        if not stream.startswith(self.DIFF_MAGIC):
            raise ObjectOperationError(-22, "not an rbd diff v1 stream")
        pos = len(self.DIFF_MAGIC)
        end_snap = None
        ended = False

        def need(n: int) -> None:
            if pos + n > len(stream):
                raise ObjectOperationError(-22, "truncated diff stream")

        while pos < len(stream) and not ended:
            tag = stream[pos:pos + 1]
            pos += 1
            if tag == b"f":
                need(4)
                (n,) = struct.unpack_from("<I", stream, pos)
                need(4 + n)
                name = stream[pos + 4:pos + 4 + n].decode()
                pos += 4 + n
                if name not in self.snaps:
                    raise ObjectOperationError(
                        -22, f"start snapshot {name} not present")
            elif tag == b"t":
                need(4)
                (n,) = struct.unpack_from("<I", stream, pos)
                need(4 + n)
                end_snap = stream[pos + 4:pos + 4 + n].decode()
                pos += 4 + n
            elif tag == b"s":
                need(8)
                (size,) = struct.unpack_from("<Q", stream, pos)
                pos += 8
                await self.resize(size)
            elif tag == b"w":
                need(16)
                off, n = struct.unpack_from("<QQ", stream, pos)
                pos += 16
                need(n)
                await self.write(off, stream[pos:pos + n])
                pos += n
            elif tag == b"z":
                need(16)
                off, n = struct.unpack_from("<QQ", stream, pos)
                pos += 16
                while n:
                    step = min(n, self.obj_size)
                    await self.write(off, b"\0" * step)
                    off += step
                    n -= step
            elif tag == b"e":
                ended = True
            else:
                raise ObjectOperationError(
                    -22, f"unknown diff record {tag!r}")
        if not ended:
            raise ObjectOperationError(-22, "truncated diff stream")
        if end_snap is not None:
            await self.snap_create(end_snap)
