"""Mesh construction helpers.

One logical axis family:

- ``shard``: the data-parallel axis — stripes for EC, PG-id blocks for CRUSH.
  This is where Ceph's "every PG / every stripe is independent" parallelism
  (SURVEY.md §2.5) lands on the hardware: batches split over ICI.

A second axis (``lane``) can split the byte/lane dimension of very large
chunks across devices (the sequence-parallel slot, SURVEY.md §5.7) — EC
chunks are embarrassingly parallel along bytes, so this is a pure reshape,
no collectives on the forward path.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(devices=None, axes: tuple[str, ...] = ("shard",),
              shape: tuple[int, ...] | None = None) -> Mesh:
    """Build a Mesh over `devices` (default: all) with named `axes`.

    shape defaults to putting every device on the first axis.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = (n,) + (1,) * (len(axes) - 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axes)


def local_mesh(n: int | None = None) -> Mesh:
    """A 1-D ('shard',) mesh over the first n local devices."""
    devices = jax.devices()
    if n is not None:
        devices = devices[:n]
    return make_mesh(devices)
