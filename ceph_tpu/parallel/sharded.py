"""Sharded EC pipelines: stripe batches split over the device mesh.

The EC analog of the reference's primary->shard fan-out
(ref: src/osd/ECBackend.cc handle_sub_write fan-out over MOSDECSubOpWrite):
instead of sending k+m sub-ops over a messenger, the stripe batch is sharded
over ICI and every device encodes its stripes locally — zero collectives on
the hot path, which is exactly why EC striping maps so well onto SPMD.
"""

from __future__ import annotations

import functools

import jax
from ceph_tpu.utils.platform import enable_x64 as _enable_x64
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ceph_tpu.gf import ops


@functools.partial(jax.jit, static_argnames=("mesh", "backend"))
def sharded_encode(mesh: Mesh, bitmatrix: jax.Array, lo: jax.Array,
                   hi: jax.Array, data: jax.Array,
                   backend: str = "bitmatmul") -> jax.Array:
    """Encode (batch, k, C) with the batch axis sharded over mesh axis 0.

    Pure SPMD: in_specs shard the stripe batch; the tiny matrix/table
    operands are replicated. No collectives are needed — XLA partitions the
    matmul along the batch dim.
    """
    axis = mesh.axis_names[0]
    data = jax.lax.with_sharding_constraint(
        data, NamedSharding(mesh, P(axis, None, None)))
    out = ops.encode_stripes(bitmatrix, lo, hi, data, backend=backend)
    return jax.lax.with_sharding_constraint(
        out, NamedSharding(mesh, P(axis, None, None)))


# Reconstruction is the same sharded matrix application with a
# per-erasure-pattern decode matrix: chunks (batch, n_avail, C) ->
# (batch, n_want, C). Recovery reads in the reference gather k surviving
# shards to the primary (ref: src/osd/ECCommon.cc ReadPipeline); here the
# stripe batch is already device-local, so reconstruction is collective-free.
sharded_decode = sharded_encode


@functools.lru_cache(maxsize=64)
def _compiled_sharded_sweep(rule_key, firstn, nd, mesh, block, local_n,
                            result_max):
    """Compiled shard_map sweep step (bounded cache, mirroring the
    single-device _compiled_sweep's lru discipline)."""
    from ceph_tpu.crush.mapper import ITEM_NONE, _rule_body

    fn_body = _rule_body(*rule_key)
    axis = mesh.axis_names[0]

    def local(arrs, start_x):
        # per-shard iota: nothing of O(n) is ever materialized globally
        base = start_x + (jax.lax.axis_index(axis) *
                          jnp.uint32(local_n))
        counts = jnp.zeros(nd + 1, dtype=jnp.int64)
        bad = jnp.int64(0)
        for lo in range(0, local_n, block):      # static tile loop
            width = min(block, local_n - lo)
            xs = base + jnp.uint32(lo) + jnp.arange(block,
                                                    dtype=jnp.uint32)
            w = fn_body(arrs, xs)                # (block, rmax)
            live = w != ITEM_NONE
            if width < block:
                live = live & (jnp.arange(block) < width)[:, None]
            flat = jnp.where(live, w, nd)
            counts = counts.at[flat.reshape(-1)].add(jnp.int64(1))
            if firstn:
                short = live.sum(axis=1) < result_max
                if width < block:
                    short = short & (jnp.arange(block) < width)
                bad = bad + short.sum(dtype=jnp.int64)
        return (jax.lax.psum(counts[:nd], axis),
                jax.lax.psum(bad, axis))

    # check_vma off: the rule VM's while_loop carries start from
    # unvarying constants, which the varying-manual-axes checker
    # rejects even though the computation is correctly per-shard
    from ceph_tpu.utils.platform import shard_map as _shard_map
    return jax.jit(_shard_map(
        local, mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), P()),
        check_vma=False))


def sharded_crush_sweep(mesh: Mesh, mapper, ruleno: int, start_x: int,
                        n: int, result_max: int):
    """Aggregated CRUSH sweep with the PG range sharded over the mesh.

    Multi-chip analog of Mapper.sweep: each device maps its local PG
    range in mapper.block-sized tiles (bounding the straw2 int64 temps
    exactly like the single-device path — pure SPMD, the packed map
    tensors replicated, the x axis sharded) and accumulates local
    per-device placement counts; ONE ``psum`` over ICI merges the count
    vectors. This is the whole communication cost of scaling CRUSH: a
    (max_devices,) reduction per sweep (SURVEY.md §5.8 — map
    distribution is the only shared state).

    n must divide evenly by the mesh size (caller pads). Returns
    (counts (max_devices,), bad) replicated on every device.
    """
    if getattr(mapper, "_scalar_reason", None):
        raise ValueError(
            f"map uses legacy tunables ({mapper._scalar_reason}); the "
            f"scalar fallback cannot shard — use Mapper.sweep")
    ndev = mesh.devices.size
    if n % ndev:
        raise ValueError(f"n={n} must divide by {ndev} devices")
    local_n = n // ndev
    block = min(mapper.block, local_n)
    fn = _compiled_sharded_sweep(
        mapper._rule_key(ruleno, result_max),
        mapper.rule_is_firstn(ruleno), mapper.packed.max_devices,
        mesh, block, local_n, result_max)
    with _enable_x64(True):
        return fn(mapper.arrays, jnp.uint32(start_x))
