"""Sharded EC pipelines: stripe batches split over the device mesh.

The EC analog of the reference's primary->shard fan-out
(ref: src/osd/ECBackend.cc handle_sub_write fan-out over MOSDECSubOpWrite):
instead of sending k+m sub-ops over a messenger, the stripe batch is sharded
over ICI and every device encodes its stripes locally — zero collectives on
the hot path, which is exactly why EC striping maps so well onto SPMD.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ceph_tpu.gf import ops


@functools.partial(jax.jit, static_argnames=("mesh", "backend"))
def sharded_encode(mesh: Mesh, bitmatrix: jax.Array, lo: jax.Array,
                   hi: jax.Array, data: jax.Array,
                   backend: str = "bitmatmul") -> jax.Array:
    """Encode (batch, k, C) with the batch axis sharded over mesh axis 0.

    Pure SPMD: in_specs shard the stripe batch; the tiny matrix/table
    operands are replicated. No collectives are needed — XLA partitions the
    matmul along the batch dim.
    """
    axis = mesh.axis_names[0]
    data = jax.lax.with_sharding_constraint(
        data, NamedSharding(mesh, P(axis, None, None)))
    out = ops.encode_stripes(bitmatrix, lo, hi, data, backend=backend)
    return jax.lax.with_sharding_constraint(
        out, NamedSharding(mesh, P(axis, None, None)))


# Reconstruction is the same sharded matrix application with a
# per-erasure-pattern decode matrix: chunks (batch, n_avail, C) ->
# (batch, n_want, C). Recovery reads in the reference gather k surviving
# shards to the primary (ref: src/osd/ECCommon.cc ReadPipeline); here the
# stripe batch is already device-local, so reconstruction is collective-free.
sharded_decode = sharded_encode


def sharded_crush_sweep(mesh: Mesh, mapper, ruleno: int, start_x: int,
                        n: int, result_max: int):
    """Aggregated CRUSH sweep with the PG range sharded over the mesh.

    Round 10 promoted the embryonic implementation that lived here
    into the first-class ``ceph_tpu.crush.sharded_sweep`` module
    (kernel-body aware, padding for arbitrary n, plus the full-table
    ``sharded_map_pgs``); this wrapper keeps the original strict
    contract — n must divide evenly by the mesh size — for existing
    callers. New code should use ``crush.sharded_sweep`` directly or
    attach the mesh to the Mapper (``Mapper(mesh=...)``).
    """
    ndev = mesh.devices.size
    if n % ndev:
        raise ValueError(f"n={n} must divide by {ndev} devices")
    from ceph_tpu.crush.sharded_sweep import sharded_sweep
    return sharded_sweep(mesh, mapper, ruleno, start_x, n, result_max)
