"""Sharded EC pipelines: stripe batches split over the device mesh.

The EC analog of the reference's primary->shard fan-out
(ref: src/osd/ECBackend.cc handle_sub_write fan-out over MOSDECSubOpWrite):
instead of sending k+m sub-ops over a messenger, the stripe batch is sharded
over ICI and every device encodes its stripes locally — zero collectives on
the hot path, which is exactly why EC striping maps so well onto SPMD.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ceph_tpu.gf import ops


@functools.partial(jax.jit, static_argnames=("mesh", "backend"))
def sharded_encode(mesh: Mesh, bitmatrix: jax.Array, lo: jax.Array,
                   hi: jax.Array, data: jax.Array,
                   backend: str = "bitmatmul") -> jax.Array:
    """Encode (batch, k, C) with the batch axis sharded over mesh axis 0.

    Pure SPMD: in_specs shard the stripe batch; the tiny matrix/table
    operands are replicated. No collectives are needed — XLA partitions the
    matmul along the batch dim.
    """
    axis = mesh.axis_names[0]
    data = jax.lax.with_sharding_constraint(
        data, NamedSharding(mesh, P(axis, None, None)))
    out = ops.encode_stripes(bitmatrix, lo, hi, data, backend=backend)
    return jax.lax.with_sharding_constraint(
        out, NamedSharding(mesh, P(axis, None, None)))


# Reconstruction is the same sharded matrix application with a
# per-erasure-pattern decode matrix: chunks (batch, n_avail, C) ->
# (batch, n_want, C). Recovery reads in the reference gather k surviving
# shards to the primary (ref: src/osd/ECCommon.cc ReadPipeline); here the
# stripe batch is already device-local, so reconstruction is collective-free.
sharded_decode = sharded_encode


_SWEEP_CACHE: dict = {}


def sharded_crush_sweep(mesh: Mesh, mapper, ruleno: int, start_x: int,
                        n: int, result_max: int):
    """Aggregated CRUSH sweep with the PG range sharded over the mesh.

    Multi-chip analog of Mapper.sweep: each device maps its local PG
    range in mapper.block-sized tiles (bounding the straw2 int64 temps
    exactly like the single-device path — pure SPMD, the packed map
    tensors replicated, the x axis sharded) and accumulates local
    per-device placement counts; ONE ``psum`` over ICI merges the count
    vectors. This is the whole communication cost of scaling CRUSH: a
    (max_devices,) reduction per sweep (SURVEY.md §5.8 — map
    distribution is the only shared state).

    n must divide evenly by the mesh size (caller pads). Returns
    (counts (max_devices,), bad) replicated on every device.
    """
    from ceph_tpu.crush.mapper import ITEM_NONE, _rule_body

    if getattr(mapper, "_scalar_reason", None):
        raise ValueError(
            f"map uses legacy tunables ({mapper._scalar_reason}); the "
            f"scalar fallback cannot shard — use Mapper.sweep")
    rule_key = mapper._rule_key(ruleno, result_max)
    nd = mapper.packed.max_devices
    firstn = mapper.rule_is_firstn(ruleno)
    axis = mesh.axis_names[0]
    ndev = mesh.devices.size
    if n % ndev:
        raise ValueError(f"n={n} must divide by {ndev} devices")
    block = min(mapper.block, n // ndev)

    cache_key = (rule_key, firstn, nd, mesh, block)
    fn = _SWEEP_CACHE.get(cache_key)
    if fn is None:
        fn_body = _rule_body(*rule_key)

        def local(arrs, xs):
            local_n = xs.shape[0]
            counts = jnp.zeros(nd + 1, dtype=jnp.int64)
            bad = jnp.int64(0)
            for lo in range(0, local_n, block):  # static tile loop
                piece = xs[lo:lo + block]
                if piece.shape[0] < block:
                    piece = jnp.pad(piece, (0, block - piece.shape[0]))
                    valid = jnp.arange(block) < local_n - lo
                else:
                    valid = None
                w = fn_body(arrs, piece)         # (block, rmax)
                live = w != ITEM_NONE
                if valid is not None:
                    live = live & valid[:, None]
                flat = jnp.where(live, w, nd)
                counts = counts.at[flat.reshape(-1)].add(jnp.int64(1))
                if firstn:
                    short = live.sum(axis=1) < result_max
                    if valid is not None:
                        short = short & valid
                    bad = bad + short.sum(dtype=jnp.int64)
            return (jax.lax.psum(counts[:nd], axis),
                    jax.lax.psum(bad, axis))

        # check_vma off: the rule VM's while_loop carries start from
        # unvarying constants, which the varying-manual-axes checker
        # rejects even though the computation is correctly per-shard
        fn = jax.jit(jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(), P(axis)),
            out_specs=(P(), P()),
            check_vma=False))
        _SWEEP_CACHE[cache_key] = fn

    with jax.enable_x64(True):
        xs = start_x + jnp.arange(n, dtype=jnp.uint32)
        counts, bad = fn(mapper.arrays, xs)
        return counts, bad
