"""Multi-controller (multi-host) SPMD: the DCN communication story.

ref: the role of the reference's NCCL/MPI multi-host backend (SURVEY.md
§5.8). On TPU pods the transport hierarchy is ICI within a slice and
DCN between hosts; in JAX the same program runs on every host
(multi-controller SPMD), ``jax.distributed`` supplies the coordination
plane, and XLA inserts the cross-host collectives — there is no NCCL
ring to manage. This module is that story made concrete and testable
without pod hardware: N coordinated CPU processes, each with M virtual
devices, form a global (host, shard) mesh whose ``host`` axis IS the
DCN boundary.

Two framework pipelines run over the global mesh:

- EC encode with the stripe batch sharded over the ``host`` (DCN) axis
  — embarrassingly parallel, zero cross-host bytes on the hot path,
  which is exactly why EC striping scales to pods: only the checksum
  reduction crosses DCN.
- the aggregated CRUSH sweep over a 1-D mesh spanning every device of
  every host — its single ``psum`` of the (max_devices,) count vector
  is the entire cross-host communication cost of scaling placement.

Both are asserted bit-equal to the local single-process computation.

Run one worker per host (the test spawns two):

    python -m ceph_tpu.parallel.multihost --coordinator 127.0.0.1:PORT \
        --num-processes 2 --process-id {0,1}
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ceph_tpu.utils.platform import enable_x64 as _enable_x64


def run_worker(coordinator: str, num_processes: int, process_id: int,
               local_devices: int = 4) -> dict:
    # platform forcing must precede any jax use; the sandbox's
    # sitecustomize force-selects the remote-TPU backend otherwise.
    # APPEND to any existing XLA_FLAGS (a setdefault would silently
    # drop the device count — and with it --local-devices — whenever
    # the caller had unrelated flags set)
    flag = f"--xla_force_host_platform_device_count={local_devices}"
    prior = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in prior:
        os.environ["XLA_FLAGS"] = f"{prior} {flag}".strip()
    else:
        import re as _re
        os.environ["XLA_FLAGS"] = _re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, prior)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator, num_processes, process_id)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ceph_tpu.crush import builder
    from ceph_tpu.crush.mapper import Mapper
    from ceph_tpu.ec import matrix as rs
    from ceph_tpu.gf import ops, tables
    from ceph_tpu.parallel.sharded import sharded_crush_sweep

    devs = jax.devices()
    assert len(devs) == num_processes * local_devices, len(devs)
    assert jax.process_count() == num_processes

    # --- DCN-aware 2-axis mesh: host axis == process boundary ---------
    by_proc: dict[int, list] = {}
    for d in devs:
        by_proc.setdefault(d.process_index, []).append(d)
    dev2d = np.array([by_proc[p] for p in sorted(by_proc)])
    mesh2 = Mesh(dev2d, ("host", "shard"))

    # --- EC over DCN: stripe batch split across hosts -----------------
    k, m, C, batch = 4, 2, 4096, 8 * num_processes
    coding = rs.coding_matrix("reed_sol_van", k, m)
    bitmatrix = jnp.asarray(tables.expand_bitmatrix(coding),
                            dtype=jnp.int8)
    lo, hi = map(jnp.asarray, tables.nibble_tables(coding))
    rng = np.random.default_rng(7)           # same stream on all hosts
    data_np = rng.integers(0, 256, size=(batch, k, C), dtype=np.uint8)
    sharding = NamedSharding(mesh2, P("host", None, None))
    data = jax.make_array_from_callback(
        data_np.shape, sharding, lambda idx: data_np[idx])

    @jax.jit
    def encode(d):
        out = ops.encode_stripes(bitmatrix, lo, hi, d,
                                 backend="bitmatmul")
        # uint32 with wraparound: deterministic, and x64 stays off
        return jax.lax.with_sharding_constraint(out, sharding), \
            jnp.sum(out.astype(jnp.uint32))

    parity, checksum = encode(data)
    jax.block_until_ready(parity)
    # every addressable shard holds exactly this host's DCN slice of
    # the batch (replicated across the host's own shard axis)
    assert all(s.data.shape[0] == batch // num_processes
               for s in parity.addressable_shards), \
        [s.data.shape for s in parity.addressable_shards]
    # ...and the replicated checksum matches a purely local encode
    ref = np.asarray(jax.jit(lambda: ops.encode_stripes(
        bitmatrix, lo, hi, jnp.asarray(data_np),
        backend="bitmatmul"))())
    assert int(jax.device_get(checksum)) == int(
        ref.astype(np.uint64).sum() & 0xFFFFFFFF), \
        "cross-host EC checksum mismatch"

    # --- CRUSH over the full global mesh ------------------------------
    mesh1 = Mesh(dev2d.reshape(-1), ("shard",))
    cm, root = builder.build_hierarchy(8, 8, n_racks=2)
    rid = builder.add_simple_rule(cm, root, builder.TYPE_HOST)
    mapper = Mapper(cm, block=1 << 9)
    # replicated operands must be global arrays in multi-controller
    with _enable_x64(True):
        mapper.arrays = jax.device_put(
            mapper.arrays, NamedSharding(mesh1, P()))
    n_pgs = 256 * len(devs)
    counts, bad = sharded_crush_sweep(mesh1, mapper, rid, 0, n_pgs, 3)
    got = np.asarray(counts)
    # local single-process reference on a fresh Mapper (local arrays)
    ref_counts, ref_bad = Mapper(cm, block=1 << 9).sweep(
        rid, 0, n_pgs, 3)
    assert (got == np.asarray(ref_counts)).all(), \
        "cross-host CRUSH counts diverge from the local sweep"
    assert int(bad) == int(ref_bad)
    assert int(got.sum()) == 3 * n_pgs

    return {"ok": True, "process_id": process_id,
            "processes": jax.process_count(),
            "global_devices": len(devs),
            "local_devices": local_devices,
            "ec_checksum": int(jax.device_get(checksum)),
            "crush_placements": int(got.sum())}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="multihost")
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--local-devices", type=int, default=4)
    args = ap.parse_args(argv)
    out = run_worker(args.coordinator, args.num_processes,
                     args.process_id, args.local_devices)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
