"""Scale-out: device meshes and sharded pipelines.

The TPU-native replacement for the reference's cluster-parallel structure
(SURVEY.md §2.5): PG-sharding and EC fan-out become data-parallel axes of a
``jax.sharding.Mesh``; the messenger's primary->shard fan-out sub-ops become
XLA collectives over ICI; multi-host (DCN) rides the same shardings via
``jax.distributed``.
"""

from ceph_tpu.parallel.mesh import make_mesh, local_mesh
from ceph_tpu.parallel.sharded import (
    sharded_encode,
    sharded_decode,
    sharded_crush_sweep,
)
