"""Binary encoding primitives: the bufferlist/denc analog.

ref: src/include/buffer.h (ceph::buffer::list), src/include/denc.h and
src/include/encoding.h (ENCODE_START/DECODE_START versioned sections).
Same wire discipline as the reference — little-endian fixed-width ints,
u32-length-prefixed strings/blobs, and versioned struct sections carrying
(struct_v, struct_compat, length) so old decoders can skip unknown
trailing fields and new decoders can reject incompatible structs — but
the byte layout is this framework's own (the reference tree was not
available to byte-match; tests/golden pins OUR format so it cannot
drift silently between versions).
"""

from __future__ import annotations

import struct
from contextlib import contextmanager
from typing import Callable, Iterable


class EncodingError(Exception):
    pass


class BufferList:
    """Chained-segment byte container (ref: src/include/buffer.h
    ceph::buffer::list — append-only builder + zero-copy reads).

    Appending never copies existing segments; ``tobytes`` flattens once.
    """

    def __init__(self, data: bytes | bytearray | memoryview | None = None):
        self._segs: list[memoryview] = []
        self._len = 0
        if data is not None:
            self.append(data)

    def append(self, data) -> None:
        if isinstance(data, BufferList):
            self._segs.extend(data._segs)
            self._len += data._len
            return
        mv = memoryview(data).cast("B") if not isinstance(data, memoryview) \
            else data.cast("B")
        if len(mv):
            self._segs.append(mv)
            self._len += len(mv)

    def __len__(self) -> int:
        return self._len

    def __iter__(self):
        return iter(self._segs)

    def tobytes(self) -> bytes:
        if len(self._segs) == 1:
            return bytes(self._segs[0])
        return b"".join(bytes(s) for s in self._segs)

    def substr(self, off: int, length: int) -> bytes:
        return self.tobytes()[off:off + length]

    def crc32(self, seed: int = 0) -> int:
        import zlib
        c = seed
        for s in self._segs:
            c = zlib.crc32(s, c)
        return c & 0xFFFFFFFF


class Encoder:
    """Little-endian append-only encoder (the ::encode side)."""

    def __init__(self) -> None:
        self._buf = bytearray()

    # -- fixed-width ints --------------------------------------------------
    def u8(self, v: int) -> "Encoder":
        self._buf += struct.pack("<B", v)
        return self

    def u16(self, v: int) -> "Encoder":
        self._buf += struct.pack("<H", v)
        return self

    def u32(self, v: int) -> "Encoder":
        self._buf += struct.pack("<I", v & 0xFFFFFFFF)
        return self

    def u64(self, v: int) -> "Encoder":
        self._buf += struct.pack("<Q", v & 0xFFFFFFFFFFFFFFFF)
        return self

    def s32(self, v: int) -> "Encoder":
        self._buf += struct.pack("<i", v)
        return self

    def s64(self, v: int) -> "Encoder":
        self._buf += struct.pack("<q", v)
        return self

    def f64(self, v: float) -> "Encoder":
        self._buf += struct.pack("<d", v)
        return self

    def bool(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    # -- variable ----------------------------------------------------------
    def blob(self, b: bytes | bytearray | memoryview) -> "Encoder":
        self.u32(len(b))
        self._buf += b
        return self

    def string(self, s: str) -> "Encoder":
        return self.blob(s.encode("utf-8"))

    def raw(self, b: bytes) -> "Encoder":
        self._buf += b
        return self

    # -- containers --------------------------------------------------------
    def list(self, items: Iterable, fn: Callable[["Encoder", object], None]
             ) -> "Encoder":
        items = list(items)
        self.u32(len(items))
        for it in items:
            fn(self, it)
        return self

    def map(self, d: dict, kfn, vfn) -> "Encoder":
        self.u32(len(d))
        for k, v in d.items():
            kfn(self, k)
            vfn(self, v)
        return self

    def optional(self, v, fn) -> "Encoder":
        if v is None:
            return self.bool(False)
        self.bool(True)
        fn(self, v)
        return self

    # -- versioned sections ------------------------------------------------
    @contextmanager
    def start(self, version: int, compat: int = 1):
        """ENCODE_START analog: u8 struct_v, u8 struct_compat, u32 len."""
        self.u8(version).u8(compat)
        pos = len(self._buf)
        self.u32(0)  # length placeholder
        yield self
        length = len(self._buf) - pos - 4
        struct.pack_into("<I", self._buf, pos, length)

    def tobytes(self) -> bytes:
        return bytes(self._buf)


class Decoder:
    """The ::decode side; bounds-checked, forward-compatible sections."""

    def __init__(self, data: bytes | bytearray | memoryview, off: int = 0):
        self._mv = memoryview(data)
        self.off = off

    def _take(self, n: int) -> memoryview:
        if self.off + n > len(self._mv):
            raise EncodingError(
                f"decode past end ({self.off}+{n} > {len(self._mv)})")
        out = self._mv[self.off:self.off + n]
        self.off += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def s32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def s64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def bool(self) -> bool:
        return self.u8() != 0

    def blob(self) -> bytes:
        return bytes(self._take(self.u32()))

    def blob_view(self) -> memoryview:
        """Zero-copy blob: a view over the decoder's buffer instead of
        a bytes copy. For bulk payloads (EC write data) the view rides
        the received wire frame all the way into ``np.frombuffer`` —
        no host staging copy between the messenger and the device
        transfer. Holding the view keeps the whole frame alive; copy
        (``bytes(v)``) anything retained past the op."""
        return self._take(self.u32())

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def list(self, fn: Callable[["Decoder"], object]) -> list:
        return [fn(self) for _ in range(self.u32())]

    def map(self, kfn, vfn) -> dict:
        return {kfn(self): vfn(self) for _ in range(self.u32())}

    def optional(self, fn):
        return fn(self) if self.bool() else None

    @contextmanager
    def start(self, max_compat: int):
        """DECODE_START analog: yields struct_v; on exit skips any
        trailing bytes a newer encoder appended (forward compat); raises
        if the struct requires a decoder newer than ``max_compat``."""
        v = self.u8()
        compat = self.u8()
        length = self.u32()
        end = self.off + length
        if end > len(self._mv):
            raise EncodingError("section length past end")
        if compat > max_compat:
            raise EncodingError(
                f"struct requires decoder v{compat}, have v{max_compat}")
        yield v
        if self.off > end:
            raise EncodingError("decoded past section end")
        self.off = end

    def remaining(self) -> int:
        return len(self._mv) - self.off
