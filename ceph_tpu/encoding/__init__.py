from ceph_tpu.encoding.denc import (
    BufferList, Decoder, Encoder, EncodingError,
)
from ceph_tpu.encoding.maps import (
    decode_crush_map, decode_incremental, decode_osdmap,
    encode_crush_map, encode_incremental, encode_osdmap,
)

__all__ = [
    "BufferList", "Decoder", "Encoder", "EncodingError",
    "encode_crush_map", "decode_crush_map",
    "encode_osdmap", "decode_osdmap",
    "encode_incremental", "decode_incremental",
]
