"""Binary codecs for the placement structs.

ref: src/crush/CrushWrapper.cc (CrushWrapper::encode/decode),
src/osd/osd_types.cc (pg_pool_t::encode/decode, pg_t),
src/osd/OSDMap.cc (OSDMap::encode/decode, OSDMap::Incremental) — the
same roles (durable, versioned, self-describing binary forms of the
cluster maps, consumed by crushtool/osdmaptool/monitor stores), with
this framework's own layout (see denc.py provenance note).
"""

from __future__ import annotations

import json

import numpy as np

from ceph_tpu.crush.types import (
    Bucket, ChooseArg, CrushMap, Rule, RuleStep, Tunables,
)
from ceph_tpu.encoding.denc import Decoder, Encoder, EncodingError
from ceph_tpu.osd.types import PGPool, pg_t

CRUSH_MAGIC = 0x74707543  # 'Cpu t' — this framework's crush blob magic
OSDMAP_MAGIC = 0x7470754F


# -- CRUSH ----------------------------------------------------------------

def _enc_bucket(e: Encoder, b: Bucket) -> None:
    with e.start(1):
        e.s32(b.id).u16(b.type).u8(b.alg).u8(b.hash)
        e.list(b.items, lambda e, i: e.s32(i))
        e.list(b.weights, lambda e, w: e.s64(w))
        e.optional(b.straws, lambda e, s: e.list(
            s, lambda e, v: e.s64(v)))
        e.optional(b.node_weights, lambda e, s: e.list(
            s, lambda e, v: e.s64(v)))


def _dec_bucket(d: Decoder) -> Bucket:
    with d.start(1):
        b = Bucket(id=d.s32(), type=d.u16(), alg=d.u8(), hash=d.u8())
        b.items = d.list(lambda d: d.s32())
        b.weights = d.list(lambda d: d.s64())
        b.straws = d.optional(lambda d: d.list(lambda d: d.s64()))
        b.node_weights = d.optional(lambda d: d.list(lambda d: d.s64()))
    return b


def _enc_rule(e: Encoder, r: Rule) -> None:
    with e.start(1):
        e.s32(r.id).u8(r.type).string(r.name)
        e.list(r.steps, lambda e, s:
               e.u16(s.op).s32(s.arg1).s32(s.arg2))


def _dec_rule(d: Decoder) -> Rule:
    with d.start(1):
        r = Rule(id=d.s32(), type=d.u8(), name=d.string())
        r.steps = [RuleStep(op=d.u16(), arg1=d.s32(), arg2=d.s32())
                   for _ in range(d.u32())]
    return r


def _enc_choose_arg(e: Encoder, ca: ChooseArg) -> None:
    with e.start(1):
        e.list(ca.weight_set,
               lambda e, ws: e.list(ws, lambda e, w: e.s64(w)))
        e.optional(ca.ids, lambda e, ids: e.list(
            ids, lambda e, i: e.s32(i)))


def _dec_choose_arg(d: Decoder) -> ChooseArg:
    with d.start(1):
        ws = d.list(lambda d: d.list(lambda d: d.s64()))
        ids = d.optional(lambda d: d.list(lambda d: d.s32()))
    return ChooseArg(weight_set=ws, ids=ids)


def encode_crush_map(m: CrushMap) -> bytes:
    """ref: CrushWrapper::encode (binary crushmap blob, crushtool -o)."""
    e = Encoder()
    e.u32(CRUSH_MAGIC)
    with e.start(1):
        t = m.tunables
        e.u32(t.choose_local_tries).u32(t.choose_local_fallback_tries)
        e.u32(t.choose_total_tries).u32(t.chooseleaf_descend_once)
        e.u32(t.chooseleaf_vary_r).u32(t.chooseleaf_stable)
        e.u32(m.max_devices)
        e.map(m.buckets, lambda e, k: e.s32(k), _enc_bucket)
        e.map(m.rules, lambda e, k: e.s32(k), _enc_rule)
        e.map(m.type_names, lambda e, k: e.u16(k),
              lambda e, v: e.string(v))
        e.map(m.bucket_names, lambda e, k: e.s32(k),
              lambda e, v: e.string(v))
        e.map(m.device_classes, lambda e, k: e.s32(k),
              lambda e, v: e.string(v))
        e.map(m.choose_args, lambda e, k: e.s64(k),
              lambda e, v: e.map(v, lambda e, k2: e.s32(k2),
                                 _enc_choose_arg))
    return e.tobytes()


def decode_crush_map(data: bytes) -> CrushMap:
    d = Decoder(data)
    if d.u32() != CRUSH_MAGIC:
        raise EncodingError("bad crush map magic")
    with d.start(1):
        t = Tunables(
            choose_local_tries=d.u32(),
            choose_local_fallback_tries=d.u32(),
            choose_total_tries=d.u32(),
            chooseleaf_descend_once=d.u32(),
            chooseleaf_vary_r=d.u32(),
            chooseleaf_stable=d.u32(),
        )
        m = CrushMap(tunables=t, max_devices=d.u32())
        m.buckets = d.map(lambda d: d.s32(), _dec_bucket)
        m.rules = d.map(lambda d: d.s32(), _dec_rule)
        m.type_names = d.map(lambda d: d.u16(), lambda d: d.string())
        m.bucket_names = d.map(lambda d: d.s32(), lambda d: d.string())
        m.device_classes = d.map(lambda d: d.s32(), lambda d: d.string())
        m.choose_args = d.map(
            lambda d: d.s64(),
            lambda d: d.map(lambda d: d.s32(), _dec_choose_arg))
    return m


# -- pg_t / pools ---------------------------------------------------------

def enc_pg_t(e: Encoder, pg: pg_t) -> None:
    e.s64(pg.pool).u32(pg.seed)


def dec_pg_t(d: Decoder) -> pg_t:
    return pg_t(d.s64(), d.u32())


def _enc_pool(e: Encoder, p: PGPool) -> None:
    with e.start(4):                    # v4: + qos_* (op scheduler)
        e.s64(p.id).u32(p.pg_num).u32(p.pgp_num).u8(p.type)
        e.u32(p.size).u32(p.min_size).s32(p.crush_rule).u64(p.flags)
        e.u8(p.object_hash).string(p.erasure_code_profile).string(p.name)
        e.bool(p.pg_temp_primaries_first)
        e.string(json.dumps(p.extra) if p.extra else "")
        e.u64(p.quota_bytes).u64(p.quota_objects)          # v2
        e.u32(p.pg_num_pending)                            # v3
        e.f64(p.qos_reservation).f64(p.qos_weight)         # v4
        e.f64(p.qos_limit)                                 # v4


def _dec_pool(d: Decoder) -> PGPool:
    with d.start(4) as _v:
        p = PGPool(id=d.s64(), pg_num=d.u32(), pgp_num=d.u32(),
                   type=d.u8(), size=d.u32(), min_size=d.u32(),
                   crush_rule=d.s32(), flags=d.u64(),
                   object_hash=d.u8(), erasure_code_profile=d.string(),
                   name=d.string(),
                   pg_temp_primaries_first=d.bool())
        extra = d.string()
        p.extra = json.loads(extra) if extra else {}
        if _v >= 2:
            p.quota_bytes = d.u64()
            p.quota_objects = d.u64()
        if _v >= 3:
            p.pg_num_pending = d.u32()
        if _v >= 4:
            p.qos_reservation = d.f64()
            p.qos_weight = d.f64()
            p.qos_limit = d.f64()
    return p


# -- OSDMap ---------------------------------------------------------------

def _enc_addr(e: Encoder, a: tuple) -> None:
    e.string(a[0]).u32(a[1]).u32(a[2] if len(a) > 2 else 0)


def _dec_addr(d: Decoder) -> tuple:
    return (d.string(), d.u32(), d.u32())

def _enc_i64_array(e: Encoder, a: np.ndarray) -> None:
    e.blob(np.asarray(a, dtype="<i8").tobytes())


def _dec_i64_array(d: Decoder) -> np.ndarray:
    return np.frombuffer(d.blob(), dtype="<i8").astype(np.int64)


def encode_osdmap(m) -> bytes:
    """ref: OSDMap::encode — full map blob (osdmaptool input/output,
    monitor store value)."""
    e = Encoder()
    e.u32(OSDMAP_MAGIC)
    with e.start(6):                    # v6: + client QoS profiles
        e.u32(m.epoch)
        e.blob(encode_crush_map(m.crush))
        e.u32(m.max_osd)
        e.blob(np.asarray(m.osd_state, dtype="<i4").tobytes())
        _enc_i64_array(e, m.osd_weight)
        _enc_i64_array(e, m.osd_primary_affinity)
        e.map(m.pools, lambda e, k: e.s64(k), _enc_pool)
        e.map(m.pg_temp, enc_pg_t,
              lambda e, v: e.list(v, lambda e, o: e.s32(o)))
        e.map(m.primary_temp, enc_pg_t, lambda e, v: e.s32(v))
        e.map(m.pg_upmap, enc_pg_t,
              lambda e, v: e.list(v, lambda e, o: e.s32(o)))
        e.map(m.pg_upmap_items, enc_pg_t,
              lambda e, v: e.list(
                  v, lambda e, pr: e.s32(pr[0]).s32(pr[1])))
        e.map(m.osd_addrs, lambda e, k: e.s32(k), _enc_addr)   # v2
        e.map(m.up_thru, lambda e, k: e.s32(k),
              lambda e, v: e.u32(v))                           # v3
        e.map(m.blocklist, lambda e, k: e.string(k),
              lambda e, v: e.f64(v))                           # v4
        e.u64(m.flags)                                         # v5
        e.map(m.client_profiles, lambda e, k: e.string(k),     # v6
              lambda e, v: e.f64(v[0]).f64(v[1]).f64(v[2]))
    return e.tobytes()


def decode_osdmap(data: bytes):
    from ceph_tpu.osd.osdmap import OSDMap
    d = Decoder(data)
    if d.u32() != OSDMAP_MAGIC:
        raise EncodingError("bad osdmap magic")
    with d.start(6) as _v:
        epoch = d.u32()
        crush = decode_crush_map(d.blob())
        max_osd = d.u32()
        m = OSDMap(crush, max_osd=max_osd)
        m.epoch = epoch
        m.osd_state = np.frombuffer(d.blob(), dtype="<i4").astype(np.int32)
        m.osd_weight = _dec_i64_array(d)
        m.osd_primary_affinity = _dec_i64_array(d)
        m.pools = d.map(lambda d: d.s64(), _dec_pool)
        m.pg_temp = d.map(dec_pg_t, lambda d: d.list(lambda d: d.s32()))
        m.primary_temp = d.map(dec_pg_t, lambda d: d.s32())
        m.pg_upmap = d.map(
            dec_pg_t, lambda d: tuple(d.list(lambda d: d.s32())))
        m.pg_upmap_items = d.map(
            dec_pg_t, lambda d: d.list(lambda d: (d.s32(), d.s32())))
        if _v >= 2:
            m.osd_addrs = d.map(lambda d: d.s32(), _dec_addr)
        if _v >= 3:
            m.up_thru = d.map(lambda d: d.s32(), lambda d: d.u32())
        if _v >= 4:
            m.blocklist = d.map(lambda d: d.string(),
                                lambda d: d.f64())
        if _v >= 5:
            m.flags = d.u64()
        if _v >= 6:
            m.client_profiles = d.map(
                lambda d: d.string(),
                lambda d: (d.f64(), d.f64(), d.f64()))
    return m


def encode_incremental(inc) -> bytes:
    """ref: OSDMap::Incremental::encode — the delta the monitor commits
    per epoch and OSDs apply on subscription."""
    e = Encoder()
    with e.start(6):                    # v6: + client QoS profiles
        e.u32(inc.epoch)
        e.optional(inc.new_max_osd, lambda e, v: e.u32(v))
        e.map(inc.new_pools, lambda e, k: e.s64(k), _enc_pool)
        e.list(inc.old_pools, lambda e, v: e.s64(v))
        e.list(inc.new_up, lambda e, v: e.s32(v))
        e.list(inc.new_down, lambda e, v: e.s32(v))
        e.map(inc.new_weight, lambda e, k: e.s32(k),
              lambda e, v: e.s64(v))
        e.map(inc.new_primary_affinity, lambda e, k: e.s32(k),
              lambda e, v: e.s64(v))
        e.map(inc.new_pg_temp, enc_pg_t,
              lambda e, v: e.list(v, lambda e, o: e.s32(o)))
        e.map(inc.new_primary_temp, enc_pg_t, lambda e, v: e.s32(v))
        e.map(inc.new_pg_upmap, enc_pg_t,
              lambda e, v: e.list(v, lambda e, o: e.s32(o)))
        e.list(inc.old_pg_upmap, enc_pg_t)
        e.map(inc.new_pg_upmap_items, enc_pg_t,
              lambda e, v: e.list(
                  v, lambda e, pr: e.s32(pr[0]).s32(pr[1])))
        e.list(inc.old_pg_upmap_items, enc_pg_t)
        e.optional(inc.new_crush,
                   lambda e, c: e.blob(encode_crush_map(c)))
        e.map(inc.new_addrs, lambda e, k: e.s32(k), _enc_addr)    # v2
        e.map(inc.new_state, lambda e, k: e.s32(k),
              lambda e, v: e.s32(v))                              # v2
        e.map(inc.new_up_thru, lambda e, k: e.s32(k),
              lambda e, v: e.u32(v))                              # v3
        e.map(inc.new_blocklist, lambda e, k: e.string(k),
              lambda e, v: e.f64(v))                              # v4
        e.list(inc.old_blocklist, lambda e, v: e.string(v))       # v4
        e.s64(-1 if inc.new_flags is None else inc.new_flags)     # v5
        e.map(inc.new_client_profiles, lambda e, k: e.string(k),  # v6
              lambda e, v: e.f64(v[0]).f64(v[1]).f64(v[2]))
        e.list(inc.old_client_profiles,
               lambda e, v: e.string(v))                          # v6
    return e.tobytes()


def decode_incremental(data: bytes):
    from ceph_tpu.osd.osdmap import Incremental
    d = Decoder(data)
    inc = Incremental()
    with d.start(6) as _v:
        inc.epoch = d.u32()
        inc.new_max_osd = d.optional(lambda d: d.u32())
        inc.new_pools = d.map(lambda d: d.s64(), _dec_pool)
        inc.old_pools = d.list(lambda d: d.s64())
        inc.new_up = d.list(lambda d: d.s32())
        inc.new_down = d.list(lambda d: d.s32())
        inc.new_weight = d.map(lambda d: d.s32(), lambda d: d.s64())
        inc.new_primary_affinity = d.map(lambda d: d.s32(),
                                         lambda d: d.s64())
        inc.new_pg_temp = d.map(dec_pg_t,
                                lambda d: d.list(lambda d: d.s32()))
        inc.new_primary_temp = d.map(dec_pg_t, lambda d: d.s32())
        inc.new_pg_upmap = d.map(
            dec_pg_t, lambda d: tuple(d.list(lambda d: d.s32())))
        inc.old_pg_upmap = d.list(dec_pg_t)
        inc.new_pg_upmap_items = d.map(
            dec_pg_t, lambda d: d.list(lambda d: (d.s32(), d.s32())))
        inc.old_pg_upmap_items = d.list(dec_pg_t)
        inc.new_crush = d.optional(lambda d: decode_crush_map(d.blob()))
        if _v >= 2:
            inc.new_addrs = d.map(lambda d: d.s32(), _dec_addr)
            inc.new_state = d.map(lambda d: d.s32(), lambda d: d.s32())
        if _v >= 3:
            inc.new_up_thru = d.map(lambda d: d.s32(),
                                    lambda d: d.u32())
        if _v >= 4:
            inc.new_blocklist = d.map(lambda d: d.string(),
                                      lambda d: d.f64())
            inc.old_blocklist = d.list(lambda d: d.string())
        if _v >= 5:
            nf = d.s64()
            inc.new_flags = None if nf < 0 else nf
        if _v >= 6:
            inc.new_client_profiles = d.map(
                lambda d: d.string(),
                lambda d: (d.f64(), d.f64(), d.f64()))
            inc.old_client_profiles = d.list(lambda d: d.string())
    return inc
