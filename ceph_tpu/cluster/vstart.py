"""vstart: in-process dev cluster launcher.

ref: src/vstart.sh — spin N mons + N osds (+ client) on localhost,
wait for HEALTH_OK, tear down. The qa-standalone tests and the demo
CLI (`python -m ceph_tpu.cluster.vstart`) both drive this.
"""

from __future__ import annotations

import asyncio

from ceph_tpu.mon.monitor import Monitor, MonMap
from ceph_tpu.msg import Keyring
from ceph_tpu.os_.objectstore import MemStore, WALStore
from ceph_tpu.osd.daemon import OSD
from ceph_tpu.rados import Rados

DEFAULT_CFG = {
    "mon_election_timeout": 0.15, "mon_lease_interval": 0.1,
    "mon_lease": 1.0, "mon_paxos_timeout": 2.0,
    "mon_tick_interval": 0.1, "mon_osd_min_down_reporters": 1,
    "mon_osd_down_out_interval": 5.0,
    "osd_heartbeat_interval": 0.25, "osd_heartbeat_grace": 1.5,
    "osd_stats_interval": 0.3,
    "mds_beacon_interval": 0.25, "mds_beacon_grace": 2.5,
    "mds_reconnect_timeout": 1.5, "mds_replay_interval": 0.25,
    "mgr_beacon_interval": 0.25, "mgr_beacon_grace": 2.0,
    "mgr_stats_period": 0.25, "mgr_stats_stale_s": 5.0,
    "mgr_stats_schema_refresh": 10, "mgr_progress_interval": 0.25,
}


class Cluster:
    """A running dev cluster (the vstart.sh artifact).

    Two backends (round 18): the default ``backend="inproc"`` runs
    every daemon inside this interpreter (fast, introspectable — the
    objects are right there); ``backend="proc"`` returns a
    :class:`ceph_tpu.cluster.proc.ProcCluster` instead, spawning each
    daemon as a SEPARATE supervised OS process over the same real-TCP
    messenger, where kill means SIGKILL and stop means SIGTERM."""

    def __new__(cls, *args, backend: str = "inproc", **kwargs):
        if backend == "proc" and cls is Cluster:
            from ceph_tpu.cluster.proc import ProcCluster
            return ProcCluster(*args, **kwargs)
        return super().__new__(cls)

    def __init__(self, n_mons: int = 1, n_osds: int = 3,
                 config: dict | None = None, auth: bool = True,
                 data_dir: str | None = None,
                 mgr_modules: list | None = None,
                 stores: list | None = None,
                 n_mgrs: int = 1, backend: str = "inproc"):
        self.cfg = dict(DEFAULT_CFG, **(config or {}))
        self.n_mons = n_mons
        self.n_osds = n_osds
        self.n_mgrs = n_mgrs           # honored when mgr_modules set
        self.auth = auth
        self.data_dir = data_dir       # None = MemStore osds
        self.stores = stores           # explicit per-osd ObjectStores
        self.keyring = Keyring() if auth else None
        self.monmap = MonMap(fsid="vstart")
        self.mons: list[Monitor] = []
        self.osds: list[OSD] = []
        self.mdss: list = []                 # MDSDaemons (start_fs)
        self.fs_pool: str | None = None
        self.mgr = None                # first-started mgr (compat)
        self.mgrs: list = []
        self.mgr_modules = mgr_modules       # None = no mgr
        self.client: Rados | None = None
        # cluster-wide fault table (sim/faults.FaultInjector): set via
        # install_faults(); revived daemons inherit it
        self.faults = None
        self.asok = None                     # --serve admin socket

    async def start(self) -> "Cluster":
        names = "abcdefgh"[:self.n_mons]
        mgr_names = "xyzwvuts"[:max(self.n_mgrs, 1)]
        if self.keyring:
            for n in names:
                self.keyring.add(f"mon.{n}")
            for i in range(self.n_osds):
                self.keyring.add(f"osd.{i}")
            self.keyring.add("client.admin")
            for n in mgr_names:
                self.keyring.add(f"mgr.{n}")
        for rank, name in enumerate(names):
            self.monmap.add(name, rank, "127.0.0.1", 0)
        for rank, name in enumerate(names):
            mon = Monitor(name, self.monmap, keyring=self.keyring,
                          config=self.cfg)
            addr = await mon.msgr.bind()
            self.monmap.mons[name] = (rank, addr.host, addr.port)
            self.mons.append(mon)
        for mon in self.mons:
            mon._tick_task = asyncio.ensure_future(mon._tick_loop())
            mon.start_mgr_reporting()
        for mon in self.mons:
            await mon.elector.start()
        for mon in self.mons:
            await mon.start_asok()   # no-op without admin_socket_dir
        self.client = Rados(self.monmap, keyring=self.keyring,
                            config=self.cfg)
        # wait for a working quorum via the client path
        ret, rs, _ = await self.client.mon_command({"prefix": "status"},
                                                   timeout=30.0)
        assert ret == 0, rs
        # provision + boot osds
        for i in range(self.n_osds):
            ret, rs, _ = await self.client.mon_command(
                {"prefix": "osd new"})
            assert ret == 0, rs
            ret, rs, _ = await self.client.mon_command(
                {"prefix": "osd crush add", "id": i, "weight": 1.0,
                 "host": f"host{i}"})
            assert ret == 0, rs
        for i in range(self.n_osds):
            if self.stores is not None:
                store = self.stores[i]
            else:
                store = MemStore() if self.data_dir is None else \
                    WALStore(f"{self.data_dir}/osd{i}")
            osd = OSD(i, self.monmap, store=store,
                      keyring=self.keyring, config=self.cfg)
            self.osds.append(osd)
        await asyncio.gather(*[o.boot() for o in self.osds])
        if self.mgr_modules is not None:
            from ceph_tpu.mgr import Mgr
            for i, mname in enumerate(mgr_names):
                mgr = Mgr(mname, self.monmap, keyring=self.keyring,
                          modules=self.mgr_modules, config=self.cfg)
                # first mgr promotes immediately and claims the
                # MgrMap's active slot via its beacon; the rest are
                # standbys that promote only when the map names them
                await mgr.start(active=(i == 0))
                self.mgrs.append(mgr)
            self.mgr = self.mgrs[0]
        await self.client.connect()
        return self

    # -- fault injection (ref: qa/tasks/ceph_manager.py helpers) -----------
    def install_faults(self, injector) -> None:
        """Attach one FaultInjector to every daemon messenger (mons,
        osds incl. heartbeat, mds, mgr, client) AND to the process
        device-call chokepoint (``utils.devmon.jit_call``), so device
        fault kinds fire too. Daemons revived later inherit it. Pass
        None to detach everywhere."""
        from ceph_tpu.utils import devmon as devmon_mod
        self.faults = injector
        devmon_mod.set_fault_injector(injector)
        # mapper/EC quarantine knobs read the cluster's LIVE config
        devmon_mod.devmon().config = self.cfg
        for mon in self.mons:
            mon.msgr.faults = injector
        for osd in self.osds:
            osd.msgr.faults = injector
            osd.hb_msgr.faults = injector
        for mds in self.mdss:
            mds.msgr.faults = injector
            if mds.monc is not None:
                mds.monc.msgr.faults = injector
        for mgr in self.mgrs:
            mgr.monc.msgr.faults = injector
        if self.client is not None:
            self.client.monc.msgr.faults = injector

    # -- cephfs (ref: vstart.sh CEPH_NUM_MDS + `ceph fs new`) --------------
    async def start_fs(self, pool: str = "cephfs", n_mds: int = 2,
                       pg_num: int = 8,
                       timeout: float = 60.0,
                       max_mds: int = 1) -> list:
        """Create the fs pool and boot ``n_mds`` mon-coordinated MDS
        daemons; returns once the FSMap shows an active. With
        ``n_mds=1`` there is no standby — the configuration the
        session-survival regression pair uses to reproduce the
        pre-subsystem behavior (a dead MDS is a dead filesystem).
        ``max_mds > 1`` opens that many active ranks (multi-active;
        daemons beyond ``max_mds`` stay standbys) and waits until all
        of them reach active."""
        await self.client.pool_create(pool, pg_num=pg_num)
        await self.wait_for_clean(timeout=120)
        self.fs_pool = pool
        names = "abcdefgh"
        for i in range(n_mds):
            await self.add_mds(names[i])
        if max_mds > 1:
            await self.set_max_mds(max_mds)
            await self.wait_for_actives(max_mds, timeout=timeout)
        else:
            await self.wait_for_mds_active(timeout=timeout)
        return self.mdss

    async def set_max_mds(self, n: int) -> None:
        ret, rs, _ = await self.client.mon_command(
            {"prefix": "fs set", "var": "max_mds", "val": str(n)})
        assert ret == 0, rs

    async def wait_for_actives(self, n: int,
                               timeout: float = 60.0) -> dict:
        """Until ``n`` ranks are simultaneously active; returns
        rank -> daemon name."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            lead = self.leader()
            actives = {r: i.name for r, i in
                       lead.mdsmon.fsmap.actives().items()} \
                if lead is not None else {}
            if len(actives) >= n:
                return actives
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"only {len(actives)}/{n} active mds ranks "
                    f"({actives})")
            await asyncio.sleep(0.05)

    async def subtree_pin(self, path: str, rank: int,
                          timeout: float = 30.0) -> None:
        """`fs subtree pin` + wait for the two-phase migration to
        commit (the subtree map names ``rank`` and no migration of
        ``path`` is in flight)."""
        ret, rs, _ = await self.client.mon_command(
            {"prefix": "fs subtree pin", "path": path,
             "rank": rank})
        assert ret == 0, rs
        import json as _json
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            ret, _, out = await self.client.mon_command(
                {"prefix": "fs subtree ls"})
            assert ret == 0
            dump = _json.loads(out)
            from ceph_tpu.cephfs import _norm
            p = _norm(path)
            if dump["subtrees"].get(p) == rank and not any(
                    m["path"] == p for m in dump["migrations"]):
                return
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"subtree {path} -> rank {rank} never committed "
                    f"({dump})")
            await asyncio.sleep(0.05)

    async def add_mds(self, name: str):
        from ceph_tpu.cephfs.mds import MDSDaemon
        assert self.fs_pool is not None, "start_fs first"
        mds = await MDSDaemon.create(self.monmap, self.fs_pool,
                                     name=name, keyring=self.keyring,
                                     config=self.cfg)
        if self.faults is not None:
            mds.msgr.faults = self.faults
            mds.monc.msgr.faults = self.faults
        await mds.start_ha()
        self.mdss.append(mds)
        return mds

    def mds_active_name(self, rank: int = 0) -> str | None:
        """``rank``'s ACTIVE holder per the lead mon's FSMap."""
        lead = self.leader()
        if lead is None:
            return None
        info = lead.mdsmon.fsmap.active(rank)
        return info.name if info is not None else None

    async def wait_for_mds_active(self, not_name: str | None = None,
                                  timeout: float = 60.0,
                                  rank: int = 0) -> str:
        """Wait until SOME daemon is active on ``rank`` — pass
        ``not_name`` (the failed one) to wait out a failover."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            name = self.mds_active_name(rank)
            if name is not None and name != not_name:
                return name
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"no active mds on rank {rank} (have {name!r}, "
                    f"excluded {not_name!r})")
            await asyncio.sleep(0.05)

    async def kill_mds(self, name: str):
        """``kill -9`` the named MDS (no beacons, no teardown); returns
        the zombie object — its RADOS identity stays open so fencing
        is observable."""
        mds = next(m for m in self.mdss
                   if m.name == name and not m._stopping)
        await mds.kill()
        return mds

    async def revive_mds(self, name: str):
        """Boot a FRESH incarnation under the same name (new gid, new
        RADOS identity — the old one stays fenced/tombstoned)."""
        return await self.add_mds(name)

    # -- runtime monmap membership (ref: `ceph mon add/rm` +
    # MonmapMonitor::prepare_update) ---------------------------------------
    async def add_mon(self, name: str | None = None,
                      timeout: float = 30.0) -> Monitor:
        """Grow the mon cluster AT RUNTIME: bind a fresh Monitor,
        commit it into the monmap (`ceph mon add`), and let the
        elector re-form quorum over the new membership — the joiner
        syncs the whole paxos store through the next collect round
        before the quorum is writeable again."""
        used = set(self.monmap.mons)
        name = name or next(n for n in "abcdefghijklmnop"
                            if n not in used)
        assert name not in used, f"mon.{name} already exists"
        if self.keyring is not None and \
                f"mon.{name}" not in self.keyring.keys:
            # provision through the AuthMonitor so the key is a
            # committed cluster decision, not a side-channel insert
            ret, rs, _ = await self.client.mon_command(
                {"prefix": "auth get-or-create",
                 "entity": f"mon.{name}"})
            assert ret == 0, rs
        new_rank = self.monmap.next_rank()
        provisional = self.monmap.clone()
        provisional.add(name, new_rank, "127.0.0.1", 0)
        mon = Monitor(name, provisional, keyring=self.keyring,
                      config=self.cfg)
        addr = await mon.msgr.bind()
        await mon.start_asok()
        provisional.mons[name] = (new_rank, addr.host, addr.port)
        if self.faults is not None:
            mon.msgr.faults = self.faults
        ret, rs, out = await self.client.mon_command(
            {"prefix": "mon add", "name": name, "host": addr.host,
             "port": addr.port})
        assert ret == 0, rs
        import json as _json
        assigned = _json.loads(out).get("rank", new_rank)
        assert assigned == new_rank, \
            f"mon add assigned rank {assigned}, expected {new_rank}"
        self.monmap.add(name, new_rank, addr.host, addr.port)
        self.mons.append(mon)
        mon._tick_task = asyncio.ensure_future(mon._tick_loop())
        mon.start_mgr_reporting()
        await mon.elector.start()
        await self.wait_for_quorum(len(self.monmap.mons),
                                   timeout=timeout)
        return mon

    async def rm_mon(self, name: str, timeout: float = 30.0) -> None:
        """Shrink the mon cluster at runtime (`ceph mon rm`): the
        committed map excludes the member (dead or alive — removing a
        killed mon is how the map heals after a failure), survivors
        re-elect, and a still-running removed mon retires itself."""
        ret, rs, _ = await self.client.mon_command(
            {"prefix": "mon rm", "name": name})
        assert ret == 0, rs
        self.monmap.mons.pop(name, None)
        victim = next((m for m in self.mons if m.name == name), None)
        if victim is not None:
            self.mons.remove(victim)
            if not victim._stopped:
                await victim.stop()
        await self.wait_for_quorum(len(self.monmap.mons),
                                   timeout=timeout)

    async def wait_for_quorum(self, n_mons: int,
                              timeout: float = 30.0) -> dict:
        """Until the quorum spans ``n_mons`` members AND commands are
        served (a command round-trip proves the leader's paxos is
        writeable again after the membership election)."""
        deadline = asyncio.get_event_loop().time() + timeout
        last: dict = {}
        while asyncio.get_event_loop().time() < deadline:
            try:
                ret, _, out = await self.client.mon_command(
                    {"prefix": "quorum_status"}, timeout=5.0)
            except Exception:
                ret = -1
            if ret == 0:
                import json as _json
                last = _json.loads(out)
                if len(last.get("quorum", [])) >= n_mons:
                    return last
            await asyncio.sleep(0.1)
        raise TimeoutError(
            f"quorum of {n_mons} not reached (last: {last})")

    async def kill_mon_leader(self) -> Monitor | None:
        """Hard-stop the current lead mon (ref: the qa mon thrasher).
        Returns the killed Monitor, or None when there is no leader or
        killing one would break quorum majority."""
        lead = self.leader()
        alive = [m for m in self.mons if not m._stopped]
        if lead is None or len(alive) - 1 <= len(self.monmap.mons) // 2:
            return None
        await lead.stop()
        return lead

    # -- mgr failover (ref: the qa mgr thrasher half) ----------------------
    def active_mgr(self):
        """The Mgr instance the lead mon's committed MgrMap names
        active (None when no mgr is active or no leader)."""
        lead = self.leader()
        if lead is None:
            return None
        gid = lead.mgrmon.mgrmap.active_gid
        return next((m for m in self.mgrs
                     if m.gid == gid and not m._stopped), None)

    async def kill_mgr(self, mgr=None):
        """Hard-stop a mgr (default: the active one); the mon's
        beacon-grace tick fails it and promotes a standby. Returns the
        killed Mgr."""
        mgr = mgr or self.active_mgr() or self.mgr
        await mgr.stop()
        return mgr

    async def wait_for_mgr_active(self, not_gid: int | None = None,
                                  timeout: float = 30.0):
        """Until the committed MgrMap names an active mgr whose gid
        differs from ``not_gid`` AND that daemon promoted itself;
        returns the Mgr."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            mgr = self.active_mgr()
            if mgr is not None and mgr.gid != (not_gid or -1) and \
                    mgr.active:
                return mgr
            if asyncio.get_event_loop().time() > deadline:
                lead = self.leader()
                raise TimeoutError(
                    f"no active mgr (map: "
                    f"{lead.mgrmon.mgrmap.summary() if lead else None})")
            await asyncio.sleep(0.05)

    # -- helpers (ref: qa/standalone/ceph-helpers.sh) ----------------------
    def leader(self) -> Monitor | None:
        """The current lead mon, or None mid-election."""
        return next((m for m in self.mons
                     if not m._stopped and m.is_leader()), None)

    async def wait_for_clean(self, timeout: float = 30.0) -> None:
        """All PGs of all pools active+clean on their primaries
        (ref: ceph-helpers.sh wait_for_clean)."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            if self._all_clean():
                return
            if asyncio.get_event_loop().time() > deadline:
                states = [
                    (p, pg.state) for o in self.osds if not o._stopped
                    for p, pg in o.pgs.items() if pg.is_primary()]
                raise TimeoutError(f"not clean: {states}")
            await asyncio.sleep(0.1)

    def _all_clean(self) -> bool:
        live = [o for o in self.osds if not o._stopped]
        if not live:
            return False
        seen = set()
        for o in live:
            for pgid_s, pg in o.pgs.items():
                if pg.is_primary():
                    if pg.state not in ("clean",):
                        return False
                    seen.add(pgid_s)
        # every pg of every pool must have a primary somewhere
        lead = self.leader()
        if lead is None or lead.osdmon.osdmap is None:
            return False
        om = lead.osdmon.osdmap
        want = sum(p.pg_num for p in om.pools.values())
        return len(seen) == want or want == 0

    async def kill_osd(self, osd_id: int) -> None:
        """Hard-stop (the qa kill_daemon analog)."""
        await self.osds[osd_id].stop()

    async def revive_osd(self, osd_id: int, store=None) -> None:
        """``store`` overrides the revived daemon's ObjectStore — pass
        a freshly remounted store to simulate a real process restart
        (mount replay) instead of reusing the in-process object."""
        old = self.osds[osd_id]
        osd = OSD(osd_id, self.monmap, store=store or old.store,
                  keyring=self.keyring, config=self.cfg)
        if self.faults is not None:
            osd.msgr.faults = self.faults
            osd.hb_msgr.faults = self.faults
        self.osds[osd_id] = osd
        await osd.boot()

    async def wait_for_osd_down(self, osd_id: int,
                                timeout: float = 15.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            lead = self.leader()
            om = lead.osdmon.osdmap if lead else None
            if om is not None and not bool(om.is_up(osd_id)):
                return
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(f"osd.{osd_id} still up")
            await asyncio.sleep(0.1)

    async def start_admin_socket(self, path: str) -> None:
        """Cluster-level admin socket: runtime fault-set control on a
        served cluster (`ceph daemon <path> fault ...`, see
        sim/README.md). Commands:

        - ``fault install`` {"name": n, "rules": [rule dicts]}
        - ``fault clear``   {"name": n}  (omit name: clear all)
        - ``fault ls``      -> the installed table
        """
        from ceph_tpu.sim.faults import FaultInjector, rule_from_dict
        from ceph_tpu.utils.admin_socket import AdminSocket

        def _injector() -> FaultInjector:
            if self.faults is None:
                self.install_faults(FaultInjector())
            return self.faults

        def fault_install(cmd):
            rules = [rule_from_dict(r) for r in cmd.get("rules", [])]
            if not rules:
                return {"error": "no rules"}
            _injector().install(cmd.get("name", "default"), rules)
            return {"installed": cmd.get("name", "default"),
                    "rules": len(rules)}

        def fault_clear(cmd):
            if self.faults is None:
                return {"cleared": []}
            name = cmd.get("name")
            if name:
                return {"cleared": [name] if self.faults.clear(name)
                        else []}
            names = list(self.faults.describe())
            self.faults.clear_all()
            return {"cleared": names}

        self.asok = AdminSocket(path)
        self.asok.register("fault install", fault_install,
                           "install a named fault set (rules: list of "
                           "{kind,a,b,...} dicts)")
        self.asok.register("fault clear", fault_clear,
                           "clear one named fault set (or all)")
        self.asok.register(
            "fault ls",
            lambda: self.faults.describe() if self.faults else {},
            "list installed fault sets")
        self.asok.register(
            "status", lambda: {
                "mons": [m.name for m in self.mons if not m._stopped],
                "osds": [o.whoami for o in self.osds
                         if not o._stopped]},
            "cluster daemon summary")
        await self.asok.start()

    async def stop(self, graceful: bool = False) -> None:
        """``graceful=True`` is the SIGTERM path: each OSD announces
        its departure (``stop(mark_down=True)``) so the map converges
        immediately instead of waiting out heartbeat grace — the
        same contract the proc backend's signal handler honors."""
        if self.asok:
            await self.asok.stop()
        if self.client:
            await self.client.shutdown()
        for mgr in self.mgrs:
            if not mgr._stopped:
                await mgr.stop()
        for m in self.mdss:
            if not m._stopping:
                await m.stop()
            elif m._own_rados is not None:
                # a kill()ed zombie keeps its rados open for fencing
                # probes; reap it at cluster teardown
                await m._own_rados.shutdown()
                m._own_rados = None
        for o in self.osds:
            if not o._stopped:
                await o.stop(mark_down=graceful)
        for m in self.mons:
            if not m._stopped:
                await m.stop()


async def _demo() -> None:
    c = await Cluster(n_mons=3, n_osds=3).start()
    await c.client.pool_create("rbd", pg_num=8)
    await c.wait_for_clean(timeout=120)
    io = await c.client.open_ioctx("rbd")
    await io.write_full("hello", b"world")
    print("read back:", await io.read("hello"))
    print("status:", (await c.client.status())["osdmap"])
    await c.stop()


async def _serve(args) -> None:
    """Run a cluster until signalled, publishing its conf for the
    ceph/rados CLIs (the long-lived half of vstart.sh). Every daemon
    type is served — mons/osds/mgrs (and mds with --mds-num) each get
    an admin socket next to the cluster one — and SIGTERM is a
    GRACEFUL stop (departing OSDs mark themselves down) while SIGKILL
    stays an honest crash, on both backends."""
    import signal as _signal

    from ceph_tpu.cluster.conf import write_conf
    cfg = {}
    if args.asok:
        # daemon admin sockets land next to the cluster one, so
        # `ceph_cli daemon <dir>/osd.N.asok ops` works out of the box
        import os
        cfg["admin_socket_dir"] = os.path.dirname(args.asok) or "."
    mgr_modules = None
    if args.mgr_num > 0:
        from ceph_tpu.mgr.modules import (
            BalancerModule, PGAutoscalerModule, ProgressModule,
            PrometheusModule,
        )
        mgr_modules = [BalancerModule, PGAutoscalerModule,
                       PrometheusModule, ProgressModule]
    c = await Cluster(n_mons=args.mon_num, n_osds=args.osd_num,
                      n_mgrs=args.mgr_num, mgr_modules=mgr_modules,
                      data_dir=args.data_dir, config=cfg,
                      backend=args.backend).start()
    if args.pool:
        await c.client.pool_create(args.pool, pg_num=args.pg_num)
        await c.wait_for_clean(timeout=300)
        if args.mds_num > 0:
            await c.start_fs(pool=args.pool, n_mds=args.mds_num)
    write_conf(args.conf, c.monmap, c.keyring)
    if args.asok and args.backend == "inproc":
        await c.start_admin_socket(args.asok)
    print(f"cluster up; conf at {args.conf}", flush=True)
    stop_ev = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        loop.add_signal_handler(sig, stop_ev.set)
    try:
        await stop_ev.wait()
    except asyncio.CancelledError:
        pass
    finally:
        if args.backend == "inproc":
            await c.stop(graceful=True)
        else:
            await c.stop()      # ProcCluster SIGTERMs its children


def main(argv=None) -> None:
    import argparse
    p = argparse.ArgumentParser(prog="vstart", description=__doc__)
    p.add_argument("--serve", action="store_true",
                   help="run until killed; write --conf for the CLIs")
    p.add_argument("--backend", default="inproc",
                   choices=("inproc", "proc"),
                   help="inproc: all daemons in this interpreter; "
                        "proc: one supervised OS process per daemon")
    p.add_argument("--mon-num", type=int, default=1)
    p.add_argument("--osd-num", type=int, default=3)
    p.add_argument("--mgr-num", type=int, default=0)
    p.add_argument("--mds-num", type=int, default=0,
                   help="with --pool: boot a filesystem on it")
    p.add_argument("--pool", default=None,
                   help="create this pool and wait for clean")
    p.add_argument("--pg-num", type=int, default=8)
    p.add_argument("--conf", default="/tmp/ceph_tpu.conf")
    p.add_argument("--data-dir", default=None,
                   help="durable WALStore osd data under this dir")
    p.add_argument("--asok", default=None,
                   help="cluster admin socket path (runtime fault "
                        "injection: `ceph daemon <asok> fault ...`)")
    args = p.parse_args(argv)
    if args.serve:
        asyncio.run(_serve(args))
    else:
        asyncio.run(_demo())


if __name__ == "__main__":
    import jax
    jax.config.update("jax_platforms", "cpu")
    main()
