"""Cluster conf file: how external tools find a running cluster.

ref: ceph.conf + keyring files — a json document holding the fsid,
monmap addresses and entity keys, written by vstart --serve and read
by the ceph/rados CLIs (ref: rados -c ceph.conf --keyring ...).
"""

from __future__ import annotations

import base64
import json

from ceph_tpu.mon.monitor import MonMap
from ceph_tpu.msg import Keyring


def write_conf(path: str, monmap: MonMap,
               keyring: Keyring | None,
               config: dict | None = None,
               extra: dict | None = None) -> None:
    """``config`` (JSON-scalar knob overrides) and ``extra``
    (backend-specific fields like ``data_dir``) extend the document
    for the proc backend's spawned children — readers that only want
    monmap+keyring (read_conf) ignore them."""
    doc = {
        "fsid": monmap.fsid,
        "mons": {n: list(v) for n, v in monmap.mons.items()},
        "keys": {n: base64.b64encode(k).decode()
                 for n, k in keyring.keys.items()} if keyring else {},
    }
    if config:
        doc["config"] = {
            k: v for k, v in config.items()
            if isinstance(v, (str, int, float, bool)) or v is None}
    if extra:
        doc.update(extra)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)


def read_conf_doc(path: str) -> dict:
    """The FULL conf document (incl. ``config``/``data_dir``) — what a
    proc-backend child reads to reconstruct its runtime."""
    with open(path) as f:
        return json.load(f)


def conf_monmap(doc: dict) -> MonMap:
    monmap = MonMap(fsid=doc.get("fsid", ""))
    for name, (rank, host, port) in doc["mons"].items():
        monmap.add(name, rank, host, port)
    return monmap


def conf_keyring(doc: dict) -> Keyring | None:
    if not doc.get("keys"):
        return None
    return Keyring({n: base64.b64decode(k)
                    for n, k in doc["keys"].items()})


def read_conf(path: str) -> tuple[MonMap, Keyring | None]:
    with open(path) as f:
        doc = json.load(f)
    monmap = MonMap(fsid=doc.get("fsid", ""))
    for name, (rank, host, port) in doc["mons"].items():
        monmap.add(name, rank, host, port)
    keyring = None
    if doc.get("keys"):
        keyring = Keyring({n: base64.b64decode(k)
                           for n, k in doc["keys"].items()})
    return monmap, keyring
