"""Cluster conf file: how external tools find a running cluster.

ref: ceph.conf + keyring files — a json document holding the fsid,
monmap addresses and entity keys, written by vstart --serve and read
by the ceph/rados CLIs (ref: rados -c ceph.conf --keyring ...).
"""

from __future__ import annotations

import base64
import json

from ceph_tpu.mon.monitor import MonMap
from ceph_tpu.msg import Keyring


def write_conf(path: str, monmap: MonMap,
               keyring: Keyring | None) -> None:
    doc = {
        "fsid": monmap.fsid,
        "mons": {n: list(v) for n, v in monmap.mons.items()},
        "keys": {n: base64.b64encode(k).decode()
                 for n, k in keyring.keys.items()} if keyring else {},
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)


def read_conf(path: str) -> tuple[MonMap, Keyring | None]:
    with open(path) as f:
        doc = json.load(f)
    monmap = MonMap(fsid=doc.get("fsid", ""))
    for name, (rank, host, port) in doc["mons"].items():
        monmap.add(name, rank, host, port)
    keyring = None
    if doc.get("keys"):
        keyring = Keyring({n: base64.b64decode(k)
                           for n, k in doc["keys"].items()})
    return monmap, keyring
