"""Multi-process cluster backend: one OS process per daemon.

ref: vstart.sh + ceph-run + systemd units — the in-process `Cluster`
(cluster/vstart.py) runs every daemon inside ONE interpreter, which
makes "kill an OSD" a polite in-process teardown. This backend spawns
each daemon (mon, osd, mgr, mds) as a SEPARATE process over the same
real-TCP messenger, supervised by the parent:

- graceful stop = SIGTERM -> the child's signal handler runs
  ``stop(mark_down=True)`` (the daemon TELLS the mon it is leaving,
  ref: the clean-shutdown MOSDMarkMeDown path) and exits 0;
- crash = SIGKILL (or any unexpected exit) -> no goodbye on the wire,
  the cluster finds out the hard way (heartbeat grace, beacon grace),
  and the supervisor restarts the daemon with capped exponential
  backoff (ref: systemd Restart=on-failure + RestartSec).

Children rebuild their runtime from the conf document written by the
parent (cluster/conf.py): monmap with PRE-ASSIGNED mon ports (so a
respawned mon rebinds the address the map advertises), keyring, knob
overrides, data_dir (OSDs mount WALStore so a SIGKILL+respawn is a
real crash-recovery mount replay). Each child serves its own admin
socket, including ``fault install/clear/ls`` verbs so fault injection
is wire-delivered per process — and subscribes to the mon's ``config``
stream, so `ceph config set` flips knobs inside remote processes
without a restart.

Child entrypoint: ``python -m ceph_tpu.cluster.proc --daemon osd
--id 0 --conf /path/cluster.conf``.
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import signal
import socket
import sys
import tempfile

from ceph_tpu.cluster.conf import (
    conf_keyring,
    conf_monmap,
    read_conf_doc,
    write_conf,
)
from ceph_tpu.cluster.vstart import DEFAULT_CFG
from ceph_tpu.mon.monitor import MonMap
from ceph_tpu.msg import Keyring
from ceph_tpu.utils.logging import get_logger

log = get_logger("proc")

# proc children inherit slower-but-realer timings than the in-process
# defaults: a forked interpreter takes real seconds to come up, so
# sub-second beacon/heartbeat graces would flap every restart
PROC_CFG = {
    "osd_heartbeat_grace": 3.0,
    "mds_beacon_grace": 5.0,
    "mgr_beacon_grace": 5.0,
    "mon_osd_down_out_interval": 60.0,
}


def _free_port() -> int:
    """Pre-assign a localhost port (bind 0, read, close). The child
    rebinds it; SO_REUSEADDR makes the tiny window a non-issue for a
    dev harness."""
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Child:
    """One supervised daemon process."""

    def __init__(self, name: str, argv: list[str]):
        self.name = name                 # "osd.0", "mon.a", ...
        self.argv = argv
        self.proc: asyncio.subprocess.Process | None = None
        self.desired = "run"             # "run" | "stopped"
        self.restarts = 0                # supervisor respawns observed
        self.consecutive = 0             # crashes without a calm spell
        self.started_at = 0.0
        self.watcher: asyncio.Task | None = None

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc else None


class ProcCluster:
    """A running dev cluster where every daemon is its own process.

    API mirrors the in-process `Cluster` where the concept survives
    the process boundary (start/stop, wait_for_clean, kill/revive,
    client) and replaces in-process object surgery with signals:
    kill_osd -> SIGKILL (supervisor respawns), stop_osd -> SIGTERM
    (graceful, stays down), pause_osd/resume_osd -> SIGSTOP/SIGCONT
    (the gray-failure primitive: alive on the socket, frozen in
    time)."""

    backend = "proc"

    def __init__(self, n_mons: int = 1, n_osds: int = 3,
                 config: dict | None = None, auth: bool = True,
                 data_dir: str | None = None,
                 mgr_modules: list | None = None,
                 stores: list | None = None,
                 n_mgrs: int = 1, backend: str = "proc"):
        assert stores is None, \
            "proc backend owns its stores (WALStore under data_dir)"
        self.cfg = dict(DEFAULT_CFG)
        self.cfg.update(PROC_CFG)
        self.cfg.update(config or {})
        self.n_mons = n_mons
        self.n_osds = n_osds
        self.n_mgrs = n_mgrs
        self.auth = auth
        self.mgr_modules = mgr_modules
        self._own_dir = data_dir is None
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="ceph_proc_")
        self.asok_dir = f"{self.data_dir}/asok"
        self.conf_path = f"{self.data_dir}/cluster.conf"
        self.keyring = Keyring() if auth else None
        self.monmap = MonMap(fsid="vstart-proc")
        self.children: dict[str, _Child] = {}
        self.client = None
        self.fs_pool: str | None = None
        self.spawn_to_healthy_s: float | None = None
        self._closing = False
        self.asok = None                 # cluster-level, via vstart

    # -- bring-up ----------------------------------------------------------
    async def start(self) -> "ProcCluster":
        from ceph_tpu.rados import Rados
        t0 = asyncio.get_event_loop().time()
        os.makedirs(self.asok_dir, exist_ok=True)
        names = "abcdefgh"[:self.n_mons]
        mgr_names = "xyzwvuts"[:max(self.n_mgrs, 1)]
        if self.keyring:
            for n in names:
                self.keyring.add(f"mon.{n}")
            for i in range(self.n_osds):
                self.keyring.add(f"osd.{i}")
            self.keyring.add("client.admin")
            for n in mgr_names:
                self.keyring.add(f"mgr.{n}")
            for n in "abcdefgh":         # mds names, provisioned ahead
                self.keyring.add(f"mds.{n}")
        for rank, name in enumerate(names):
            self.monmap.add(name, rank, "127.0.0.1", _free_port())
        cfg = dict(self.cfg)
        cfg["admin_socket_dir"] = self.asok_dir
        mods = None
        if self.mgr_modules is not None:
            mods = [m if isinstance(m, str) else m.NAME
                    for m in self.mgr_modules]
        write_conf(self.conf_path, self.monmap, self.keyring,
                   config=cfg,
                   extra={"data_dir": self.data_dir,
                          "mgr_modules": mods})
        for name in names:
            await self._spawn(f"mon.{name}")
        self.client = Rados(self.monmap, keyring=self.keyring,
                            config=self.cfg)
        ret, rs, _ = await self.client.mon_command(
            {"prefix": "status"}, timeout=60.0)
        assert ret == 0, rs
        for i in range(self.n_osds):
            ret, rs, _ = await self.client.mon_command(
                {"prefix": "osd new"})
            assert ret == 0, rs
            ret, rs, _ = await self.client.mon_command(
                {"prefix": "osd crush add", "id": i, "weight": 1.0,
                 "host": f"host{i}"})
            assert ret == 0, rs
        for i in range(self.n_osds):
            await self._spawn(f"osd.{i}")
        await self.wait_for_osds_up(self.n_osds, timeout=90.0)
        if self.mgr_modules is not None:
            for mname in mgr_names:
                # every proc mgr starts STANDBY; the mgrmon promotes
                # the first beacon on an empty map — same rule that
                # re-elects after a SIGKILL
                await self._spawn(f"mgr.{mname}")
            await self.wait_for_mgr_active(timeout=60.0)
        await self.client.connect()
        self.spawn_to_healthy_s = \
            asyncio.get_event_loop().time() - t0
        return self

    async def _spawn(self, name: str,
                     extra: list[str] | None = None) -> _Child:
        dtype, _, did = name.partition(".")
        argv = [sys.executable, "-m", "ceph_tpu.cluster.proc",
                "--daemon", dtype, "--id", did,
                "--conf", self.conf_path] + (extra or [])
        child = self.children.get(name)
        if child is None:
            child = _Child(name, argv)
            self.children[name] = child
        else:
            child.argv = argv
            child.desired = "run"
        await self._exec(child)
        if child.watcher is None or child.watcher.done():
            child.watcher = asyncio.ensure_future(self._watch(child))
        return child

    async def _exec(self, child: _Child) -> None:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        child.proc = await asyncio.create_subprocess_exec(
            *child.argv, env=env)
        child.started_at = asyncio.get_event_loop().time()
        log.dout(5, f"spawned {child.name} pid={child.proc.pid}")

    async def _watch(self, child: _Child) -> None:
        """The supervisor: restart-on-crash with capped exponential
        backoff; a graceful stop (desired != run) is final."""
        base = float(self.cfg.get("proc_restart_backoff_base", 0.3))
        cap = float(self.cfg.get("proc_restart_backoff_max", 5.0))
        while True:
            rc = await child.proc.wait()
            lived = asyncio.get_event_loop().time() - child.started_at
            if child.desired != "run" or self._closing:
                return
            if lived > 5.0:
                child.consecutive = 0
            delay = min(cap, base * (2 ** child.consecutive))
            child.consecutive += 1
            log.dout(1, f"{child.name} exited rc={rc} after "
                        f"{lived:.1f}s; respawn in {delay:.2f}s")
            await asyncio.sleep(delay)
            if child.desired != "run" or self._closing:
                return
            await self._exec(child)
            child.restarts += 1

    # -- signals (the thrasher's verbs) ------------------------------------
    def kill_daemon(self, name: str) -> None:
        """SIGKILL: crash, no goodbye; the supervisor respawns."""
        self.children[name].proc.send_signal(signal.SIGKILL)

    async def stop_daemon(self, name: str) -> None:
        """SIGTERM: graceful stop (mark_down) + STAYS down."""
        child = self.children[name]
        child.desired = "stopped"
        child.proc.send_signal(signal.SIGTERM)
        try:
            await asyncio.wait_for(
                child.proc.wait(),
                float(self.cfg.get("proc_stop_timeout", 10.0)))
        except asyncio.TimeoutError:
            child.proc.send_signal(signal.SIGKILL)
            await child.proc.wait()

    def pause_daemon(self, name: str) -> None:
        """SIGSTOP: the gray-failure primitive — the process holds its
        sockets open but answers nothing; heartbeats age, OSD_SLOW
        must trip (PR 17's responder sees it too)."""
        self.children[name].proc.send_signal(signal.SIGSTOP)

    def resume_daemon(self, name: str) -> None:
        self.children[name].proc.send_signal(signal.SIGCONT)

    def kill_osd(self, osd_id: int) -> None:
        self.kill_daemon(f"osd.{osd_id}")

    def pause_osd(self, osd_id: int) -> None:
        self.pause_daemon(f"osd.{osd_id}")

    def resume_osd(self, osd_id: int) -> None:
        self.resume_daemon(f"osd.{osd_id}")

    async def kill_mon_leader(self) -> str | None:
        """SIGKILL the current lead mon (found over the wire); returns
        its daemon name. None when no quorum/leader is visible or a
        kill would break majority."""
        ret, _, out = await self.client.mon_command(
            {"prefix": "quorum_status"}, timeout=10.0)
        if ret != 0:
            return None
        qs = json.loads(out)
        leader = qs.get("quorum_leader_name") or None
        if leader is None or \
                len(qs.get("quorum", [])) - 1 <= len(self.monmap.mons) // 2:
            return None
        self.kill_daemon(f"mon.{leader}")
        return f"mon.{leader}"

    async def kill_active_mgr(self) -> str | None:
        """SIGKILL the MgrMap's active mgr; returns its daemon name."""
        st = await self.client.status()
        active = st.get("mgrmap", {}).get("active_name")
        if not active:
            return None
        self.kill_daemon(f"mgr.{active}")
        return f"mgr.{active}"

    # -- cephfs ------------------------------------------------------------
    async def start_fs(self, pool: str = "cephfs", n_mds: int = 2,
                       pg_num: int = 8, timeout: float = 90.0) -> None:
        await self.client.pool_create(pool, pg_num=pg_num)
        await self.wait_for_clean(timeout=120)
        self.fs_pool = pool
        for name in "abcdefgh"[:n_mds]:
            await self._spawn(f"mds.{name}", ["--pool", pool])
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            st = await self.client.status()
            fsmap = st.get("fsmap") or {}
            if fsmap.get("active"):
                return
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(f"no active mds ({fsmap})")
            await asyncio.sleep(0.2)

    # -- waiting (all over the wire: the parent has no daemon objects) -----
    async def wait_for_clean(self, timeout: float = 60.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        last: dict = {}
        while True:
            try:
                st = await self.client.status()
                last = st.get("pgmap", {})
                n = last.get("num_pgs", 0)
                if n > 0 and last.get("states", {}).get("clean") == n:
                    return
            except Exception:
                pass
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(f"not clean: {last}")
            await asyncio.sleep(0.2)

    async def wait_for_osds_up(self, n: int,
                               timeout: float = 60.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        last = None
        while True:
            try:
                st = await self.client.status()
                last = st.get("osdmap", {}).get("num_up_osds")
                if last == n:
                    return
            except Exception:
                pass
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(f"{last}/{n} osds up")
            await asyncio.sleep(0.2)

    async def wait_for_mgr_active(self, not_name: str | None = None,
                                  timeout: float = 60.0) -> str:
        deadline = asyncio.get_event_loop().time() + timeout
        last: dict = {}
        while True:
            try:
                st = await self.client.status()
                last = st.get("mgrmap", {})
                name = last.get("active_name")
                if last.get("available") and name and \
                        name != not_name:
                    return name
            except Exception:
                pass
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(f"no active mgr ({last})")
            await asyncio.sleep(0.2)

    async def wait_for_health(self, check: str, present: bool = True,
                              timeout: float = 30.0) -> dict:
        """Until ``check`` appears in (or clears from) the health
        report; returns the final health dict."""
        deadline = asyncio.get_event_loop().time() + timeout
        health: dict = {}
        while True:
            try:
                st = await self.client.status()
                health = st.get("health", {}) or {}
                if (check in health.get("checks", {})) == present:
                    return health
            except Exception:
                pass
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"{check} {'not seen' if present else 'stuck'} "
                    f"in {health}")
            await asyncio.sleep(0.2)

    async def wait_for_restart(self, name: str, restarts_before: int,
                               timeout: float = 60.0) -> None:
        """Until the supervisor has respawned ``name`` at least once
        past ``restarts_before`` AND the fresh process is alive."""
        child = self.children[name]
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            if child.restarts > restarts_before and \
                    child.proc.returncode is None:
                return
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"{name} not restarted "
                    f"({child.restarts} <= {restarts_before})")
            await asyncio.sleep(0.1)

    async def wait_for_daemon_ready(self, name: str,
                                    timeout: float = 60.0) -> dict:
        """Until the daemon's (re-created) admin socket answers
        `status` — and, for an OSD, reports itself up. Proves the
        FRESH incarnation booted: map-level waits can pass trivially
        when the grace window outlives a quick respawn, because the
        dead daemon was never marked down to begin with."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            try:
                out = await self.daemon_command(name, "status")
                if not name.startswith("osd.") or out.get("up"):
                    return out
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    ValueError):
                pass
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(f"{name} asok never came ready")
            await asyncio.sleep(0.2)

    # -- config + asok plumbing --------------------------------------------
    async def config_set(self, who: str, name: str, value) -> None:
        ret, rs, _ = await self.client.mon_command(
            {"prefix": "config set", "who": who, "name": name,
             "value": str(value)})
        assert ret == 0, rs

    async def config_rm(self, who: str, name: str) -> None:
        ret, rs, _ = await self.client.mon_command(
            {"prefix": "config rm", "who": who, "name": name})
        assert ret == 0, rs

    def asok_path(self, name: str) -> str:
        return f"{self.asok_dir}/{name}.asok"

    async def daemon_command(self, name: str, cmd: dict | str) -> dict:
        from ceph_tpu.utils.admin_socket import daemon_command
        return await daemon_command(self.asok_path(name), cmd)

    # -- teardown ----------------------------------------------------------
    async def stop(self) -> None:
        self._closing = True
        if self.asok:
            await self.asok.stop()
        if self.client:
            await self.client.shutdown()
        order = ("mds.", "mgr.", "osd.", "mon.")
        for prefix in order:
            batch = [c for n, c in self.children.items()
                     if n.startswith(prefix)]
            for c in batch:
                c.desired = "stopped"
                if c.proc and c.proc.returncode is None:
                    # a SIGSTOPped child can't run its SIGTERM handler
                    c.proc.send_signal(signal.SIGCONT)
                    c.proc.send_signal(signal.SIGTERM)
            for c in batch:
                if c.proc is None:
                    continue
                try:
                    await asyncio.wait_for(
                        c.proc.wait(),
                        float(self.cfg.get("proc_stop_timeout", 10.0)))
                except asyncio.TimeoutError:
                    c.proc.send_signal(signal.SIGKILL)
                    await c.proc.wait()
        for c in self.children.values():
            if c.watcher is not None:
                c.watcher.cancel()
        if self._own_dir:
            shutil.rmtree(self.data_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# child entrypoint
# ---------------------------------------------------------------------------

def _register_fault_verbs(asok, messengers, cfg) -> None:
    """Per-daemon runtime fault injection (`ceph daemon <asok> fault
    install ...`) — the proc analog of Cluster.install_faults, scoped
    to THIS process's messengers + device chokepoint."""
    from ceph_tpu.sim.faults import FaultInjector, rule_from_dict
    from ceph_tpu.utils import devmon as devmon_mod
    holder: dict = {"inj": None}

    def _injector():
        if holder["inj"] is None:
            inj = FaultInjector()
            holder["inj"] = inj
            devmon_mod.set_fault_injector(inj)
            devmon_mod.devmon().config = cfg
            for m in messengers:
                m.faults = inj
        return holder["inj"]

    def fault_install(cmd):
        rules = [rule_from_dict(r) for r in cmd.get("rules", [])]
        if not rules:
            return {"error": "no rules"}
        _injector().install(cmd.get("name", "default"), rules)
        return {"installed": cmd.get("name", "default"),
                "rules": len(rules)}

    def fault_clear(cmd):
        inj = holder["inj"]
        if inj is None:
            return {"cleared": []}
        name = cmd.get("name")
        if name:
            return {"cleared": [name] if inj.clear(name) else []}
        names = list(inj.describe())
        inj.clear_all()
        return {"cleared": names}

    asok.register("fault install", fault_install,
                  "install a named fault set in THIS daemon process "
                  "(rules: list of {kind,a,b,...} dicts)")
    asok.register("fault clear", fault_clear,
                  "clear one named fault set (or all) in this process")
    asok.register("fault ls",
                  lambda: holder["inj"].describe()
                  if holder["inj"] else {},
                  "list this process's installed fault sets")


async def _child_main(args) -> None:
    doc = read_conf_doc(args.conf)
    monmap = conf_monmap(doc)
    keyring = conf_keyring(doc)
    cfg = dict(doc.get("config") or {})
    data_dir = doc.get("data_dir") or "."
    loop = asyncio.get_event_loop()
    stop_ev = asyncio.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop_ev.set)

    if args.daemon == "mon":
        from ceph_tpu.mon.monitor import Monitor
        from ceph_tpu.mon.store import MonitorDBStore
        # durable paxos store: a SIGKILLed mon must come back with its
        # committed state, not rejoin empty — an amnesiac rank 0 wins
        # re-election and the cluster's maps regress under it
        mon = Monitor(args.id, monmap, keyring=keyring, config=cfg,
                      store=MonitorDBStore(
                          path=f"{data_dir}/mon{args.id}"))
        _, _, port = monmap.mons[args.id]
        await mon.start("127.0.0.1", port)
        _register_fault_verbs(mon.asok, [mon.msgr], cfg)
        await stop_ev.wait()
        await mon.stop()
    elif args.daemon == "osd":
        from ceph_tpu.os_.objectstore import WALStore
        from ceph_tpu.osd.daemon import OSD
        osd = OSD(int(args.id), monmap,
                  store=WALStore(f"{data_dir}/osd{args.id}"),
                  keyring=keyring, config=cfg)
        osd.mirror_global_config = True
        await osd.boot()
        _register_fault_verbs(osd.asok, [osd.msgr, osd.hb_msgr], cfg)
        await stop_ev.wait()
        # graceful exit TELLS the mon (MOSDMarkMeDown analog); a
        # SIGKILL never gets here — that's the crash-honesty contract
        await osd.stop(mark_down=True)
    elif args.daemon == "mgr":
        from ceph_tpu.mgr import Mgr
        mods = None
        if doc.get("mgr_modules") is not None:
            from ceph_tpu.mgr import modules as _m
            by_name = {c.NAME: c for c in (
                _m.BalancerModule, _m.PGAutoscalerModule,
                _m.PrometheusModule, _m.TracingModule,
                _m.ProgressModule, _m.RestModule)}
            from ceph_tpu.mgr.tuner import TunerModule
            by_name[TunerModule.NAME] = TunerModule
            mods = [by_name[n] for n in doc["mgr_modules"]
                    if n in by_name]
        # gid = pid: unique across sibling processes AND respawns
        # (the in-process itertools counter restarts at 1 per child)
        mgr = Mgr(args.id, monmap, keyring=keyring, modules=mods,
                  config=cfg, gid=os.getpid())
        mgr.mirror_global_config = True
        await mgr.start(active=False)
        _register_fault_verbs(mgr.asok, [mgr.monc.msgr], cfg)
        await stop_ev.wait()
        await mgr.stop()
    elif args.daemon == "mds":
        from ceph_tpu.cephfs.mds import MDSDaemon
        from ceph_tpu.utils.admin_socket import AdminSocket
        mds = await MDSDaemon.create(monmap, args.pool, name=args.id,
                                     keyring=keyring, config=cfg,
                                     gid=os.getpid())
        mds.mirror_global_config = True
        await mds.start_ha()
        asok = AdminSocket(
            f"{cfg.get('admin_socket_dir', data_dir)}/"
            f"mds.{args.id}.asok")
        asok.register("status",
                      lambda: {"name": mds.name, "gid": mds.gid,
                               "state": mds.state},
                      "mds identity + fsmap state")
        _register_fault_verbs(asok, [mds.msgr, mds.monc.msgr], cfg)
        await asok.start()
        await stop_ev.wait()
        await asok.stop()
        await mds.stop()
    else:
        raise SystemExit(f"unknown daemon type {args.daemon!r}")


def main(argv=None) -> None:
    import argparse
    p = argparse.ArgumentParser(
        prog="ceph_tpu.cluster.proc",
        description="proc-backend daemon child (spawned by "
                    "ProcCluster; runnable by hand for debugging)")
    p.add_argument("--daemon", required=True,
                   choices=("mon", "osd", "mgr", "mds"))
    p.add_argument("--id", required=True)
    p.add_argument("--conf", required=True)
    p.add_argument("--pool", default="cephfs",
                   help="mds only: the fs metadata/data pool")
    args = p.parse_args(argv)
    asyncio.run(_child_main(args))


if __name__ == "__main__":
    import jax
    jax.config.update("jax_platforms", "cpu")
    main()
