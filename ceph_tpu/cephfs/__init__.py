"""libcephfs-lite: a POSIX-style namespace over RADOS.

ref: src/client/ (Client::ll_* / libcephfs.h) + src/mds/ — the API
surface of libcephfs (mkdir/rmdir/readdir/open/read/write/unlink/
rename/stat) over RADOS objects. The metadata model is the
reference's in miniature: every directory is a *dirfrag* object whose
omap maps entry name -> dentry (type/size), exactly how the MDS stores
directories in the metadata pool (ref: CDir backed by omap objects);
file payloads live in per-file data objects. The reference's separate
MDS daemon (journaling, dynamic subtree partitioning, client caps) is
not rebuilt — metadata ops here go straight to the dirfrag objects,
serialized per-object by the PG op pipeline.
"""

from __future__ import annotations

import json
import posixpath

from ceph_tpu.rados import IoCtx, ObjectOperationError

__all__ = ["CephFSLite", "FSError"]


class FSError(Exception):
    def __init__(self, errno: int, msg: str):
        super().__init__(msg)
        self.errno = errno


def _norm(path: str) -> str:
    p = posixpath.normpath("/" + path.strip("/"))
    return p


def _dirfrag(path: str) -> str:
    return f".dir{_norm(path)}"


def _fileobj(path: str) -> str:
    return f".file{_norm(path)}"


class CephFSLite:
    """ref: libcephfs.h ceph_mount surface."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    async def mount(self) -> "CephFSLite":
        """Create the root dirfrag (ref: ceph_mount + mds mkfs root)."""
        try:
            await self.ioctx.get_omap_vals(_dirfrag("/"))
        except ObjectOperationError:
            await self.ioctx.set_omap(_dirfrag("/"), "_self",
                                      _dentry("dir"))
        return self

    # -- dentries ----------------------------------------------------------
    async def _dir_entries(self, path: str) -> dict[str, dict]:
        try:
            omap = await self.ioctx.get_omap_vals(_dirfrag(path))
        except ObjectOperationError:
            raise FSError(-2, f"no such directory {path}") from None
        return {k: json.loads(v) for k, v in omap.items()
                if not k.startswith("_")}

    async def _lookup(self, path: str) -> dict:
        path = _norm(path)
        if path == "/":
            return {"type": "dir", "size": 0}
        parent, name = posixpath.split(path)
        entries = await self._dir_entries(parent)
        if name not in entries:
            raise FSError(-2, f"no such entry {path}")
        return entries[name]

    async def _add_entry(self, parent: str, name: str,
                         ent: dict) -> None:
        await self.ioctx.set_omap(_dirfrag(parent), name,
                                  json.dumps(ent).encode())

    # -- directories -------------------------------------------------------
    async def mkdir(self, path: str) -> None:
        path = _norm(path)
        parent, name = posixpath.split(path)
        entries = await self._dir_entries(parent)      # parent must exist
        if name in entries:
            raise FSError(-17, f"{path} exists")
        await self.ioctx.set_omap(_dirfrag(path), "_self",
                                  _dentry("dir"))
        await self._add_entry(parent, name, json.loads(_dentry("dir")))

    async def rmdir(self, path: str) -> None:
        path = _norm(path)
        if path == "/":
            raise FSError(-22, "cannot remove /")
        if await self._dir_entries(path):
            raise FSError(-39, f"{path} not empty")     # -ENOTEMPTY
        parent, name = posixpath.split(path)
        await self.ioctx.remove(_dirfrag(path))
        await self.ioctx.rm_omap_key(_dirfrag(parent), name)

    async def ls(self, path: str = "/") -> list[str]:
        """ref: ceph_readdir."""
        ent = await self._lookup(path)
        if ent["type"] != "dir":
            raise FSError(-20, f"{path} is not a directory")
        return sorted(await self._dir_entries(path))

    # -- files -------------------------------------------------------------
    async def write_file(self, path: str, data: bytes,
                         offset: int = 0) -> int:
        path = _norm(path)
        parent, name = posixpath.split(path)
        entries = await self._dir_entries(parent)
        old = entries.get(name)
        if old and old["type"] == "dir":
            raise FSError(-21, f"{path} is a directory")
        if offset:
            await self.ioctx.write(_fileobj(path), data, offset=offset)
        else:
            await self.ioctx.write_full(_fileobj(path), data)
        size = max((old or {}).get("size", 0), offset + len(data)) \
            if offset else len(data)
        await self._add_entry(parent, name, {"type": "file",
                                             "size": size})
        return len(data)

    async def set_size(self, path: str, size: int) -> None:
        """Update a file dentry's size without touching data — the MDS
        setattr path after a cap-holding client's direct data write
        (ref: Client::_setattr CEPH_SETATTR_SIZE without truncate)."""
        path = _norm(path)
        parent, name = posixpath.split(path)
        entries = await self._dir_entries(parent)
        ent = entries.get(name)
        if ent is None:
            raise FSError(-2, f"no such entry {path}")
        if ent["type"] != "file":
            raise FSError(-21, f"{path} is a directory")
        ent["size"] = int(size)
        await self._add_entry(parent, name, ent)

    async def read_file(self, path: str, length: int = 0,
                        offset: int = 0) -> bytes:
        ent = await self._lookup(path)
        if ent["type"] != "file":
            raise FSError(-21, f"{path} is a directory")
        try:
            return await self.ioctx.read(_fileobj(_norm(path)),
                                         length=length, offset=offset)
        except ObjectOperationError:
            return b""

    async def unlink(self, path: str) -> None:
        path = _norm(path)
        ent = await self._lookup(path)
        if ent["type"] == "dir":
            raise FSError(-21, f"{path} is a directory")
        parent, name = posixpath.split(path)
        try:
            await self.ioctx.remove(_fileobj(path))
        except ObjectOperationError:
            pass
        await self.ioctx.rm_omap_key(_dirfrag(parent), name)

    async def rename(self, src: str, dst: str) -> None:
        """ref: ceph_rename (files only here)."""
        src, dst = _norm(src), _norm(dst)
        ent = await self._lookup(src)
        if ent["type"] == "dir":
            raise FSError(-21, "directory rename not supported")
        data = await self.read_file(src)
        await self.write_file(dst, data)
        await self.unlink(src)

    async def stat(self, path: str) -> dict:
        """ref: ceph_stat (subset of struct ceph_statx)."""
        ent = await self._lookup(path)
        return {"path": _norm(path), "type": ent["type"],
                "size": ent.get("size", 0)}


def _dentry(kind: str, size: int = 0) -> bytes:
    return json.dumps({"type": kind, "size": size}).encode()
