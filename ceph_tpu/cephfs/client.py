"""CephFS client: metadata through the MDS, data direct to RADOS.

ref: src/client/Client.{h,cc} (the libcephfs backend) — every
namespace operation is an MClientRequest round-trip to the MDS; file
reads/writes go straight to the data objects, gated by the file
capabilities the MDS granted at open. A revoke arriving from the MDS
invalidates the handle (writers have nothing to flush — writes here
are write-through) and is acked immediately; the next I/O through
that handle re-opens to reacquire a cap, which blocks until the
conflicting holder is done — giving one-writer-or-many-readers
semantics across clients.

HA (round 6): a client mounted without a pinned MDS address
(``create(monmap, None, pool)``) subscribes to the **mdsmap** and
targets whatever daemons the FSMap says hold ranks. On failover it
sends MClientReconnect to the successor — replaying its session and
every live cap claim (ref: Client::send_reconnect) — and resends any
request that never got a reply (op replay; the MDS's completed-request
table dedups mutations that DID land before the crash). Requests
issued while no active exists park until the ladder finishes.

Multi-active routing (round 7, ref: Client::choose_target_mds + the
request-forwarding dance): every request is routed to the rank the
FSMap's subtree map says owns its path (longest-prefix match), with
sessions opened lazily per rank. A rank that does NOT own the path
answers -ESTALE naming the owner; the client records the redirect as
a routing hint (it may be ahead of its fsmap) and resends — hints are
retired once an fsmap that agrees arrives. Failover, reconnect, and
op-replay all run PER RANK, so one rank's takeover never stalls I/O
the other ranks are serving.
"""

from __future__ import annotations

import asyncio
import json

from ceph_tpu.cephfs import FSError, _norm
from ceph_tpu.cephfs.fsmap import (
    FSMap, STATE_ACTIVE, STATE_RECONNECT, STATE_REJOIN,
)
from ceph_tpu.cephfs.mds import (
    CAP_FR, CAP_FW, CAP_OP_ACK, CAP_OP_RELEASE, CAP_OP_REVOKE, ESTALE,
    MClientCaps, MClientReconnect, MClientReply, MClientRequest,
    MClientSession, RECONNECT_ACK, RECONNECT_REQ,
    SESSION_CLOSE, SESSION_OPEN, SESSION_RENEW,
)
from ceph_tpu.mon.messages import MMDSMap
from ceph_tpu.msg import Dispatcher, Messenger
from ceph_tpu.msg.messenger import ConnectionError_
from ceph_tpu.utils.logging import get_logger

log = get_logger("cephfs.client")

# fsmap states in which a rank holder accepts MClientReconnect
_RECONNECTABLE = (STATE_RECONNECT, STATE_REJOIN, STATE_ACTIVE)

# redirect chains longer than this mean the map is flapping under us;
# surface it instead of spinning
_MAX_REDIRECTS = 16


class FileHandle:
    """An open file + the cap that licenses its I/O."""

    def __init__(self, client: "CephFSClient", path: str, oid: str,
                 mode: int, cap_seq: int, size: int,
                 snap_id: int | None = None,
                 snapc: tuple[int, list[int]] | None = None):
        self.client = client
        self.path = path
        self.oid = oid
        self.mode = mode
        self.cap_seq = cap_seq
        self.size = size
        # snap_id: set for a handle opened THROUGH .snap — reads hit
        # the point-in-time clone, writes are refused. snapc: the snap
        # context the MDS granted with the open when the file sits
        # under one or more live snaprealms; stamped on every write so
        # the OSD COWs before the first post-snapshot mutation.
        self.snap_id = snap_id
        self.snapc = snapc
        self.valid = True

    async def _ensure(self) -> None:
        if not self.valid:
            fresh = await self.client.open_file(
                self.path, "w" if self.mode == CAP_FW else "r")
            self.__dict__.update(fresh.__dict__)
            # this handle now IS the reacquired cap; drop the twin so
            # _handles doesn't accumulate orphans (its registration
            # transfers to self)
            hs = self.client._handles.get(self.path, [])
            if fresh in hs:
                hs.remove(fresh)
            if self not in hs:
                hs.append(self)

    async def read(self, length: int = 0, offset: int = 0) -> bytes:
        await self._ensure()
        want = length or max(self.size - offset, 0)
        if want <= 0:
            return b""
        return await self.client.ioctx.read(self.oid, length=want,
                                            offset=offset,
                                            snap_id=self.snap_id)

    async def write(self, data: bytes, offset: int = 0) -> int:
        await self._ensure()
        if self.snap_id is not None:
            raise FSError(-30, "EROFS: snapshots are read-only")
        if self.mode != CAP_FW:
            raise FSError(-9, "handle not open for write")  # -EBADF
        # in-flight accounting: a revoke arriving mid-write must not be
        # acked until the data write AND its setattr have landed (the
        # "writers flush before acking" half of the cap contract)
        self.client._inflight[self.path] = \
            self.client._inflight.get(self.path, 0) + 1
        try:
            if offset:
                await self.client.ioctx.write(self.oid, data,
                                              offset=offset,
                                              snapc=self.snapc)
                self.size = max(self.size, offset + len(data))
            else:
                await self.client.ioctx.write_full(self.oid, data,
                                                   snapc=self.snapc)
                self.size = len(data)
            # dentry size rides a setattr through the MDS (metadata is
            # always MDS-authoritative)
            await self.client._request("setattr", self.path,
                                       flags=self.size)
        finally:
            self.client._inflight[self.path] -= 1
            if self.client._inflight[self.path] <= 0:
                self.client._inflight.pop(self.path, None)
        return len(data)

    async def close(self) -> None:
        hs = self.client._handles.get(self.path, [])
        if self in hs:
            hs.remove(self)
        if not hs:
            self.client._handles.pop(self.path, None)
        if self.valid:
            self.valid = False
            if self.snap_id is None:    # snap handles hold no cap
                await self.client._send_caps(CAP_OP_RELEASE, self.path,
                                             self.mode, self.cap_seq)


class CephFSClient(Dispatcher):
    """ref: libcephfs.h surface, MDS-backed."""

    _next_id = 0

    def __init__(self, ioctx, mds_addr=None,
                 messenger: Messenger | None = None):
        CephFSClient._next_id += 1
        self.ioctx = ioctx
        self.mds_addr = mds_addr       # pinned addr, or rank 0's (HA)
        self.msgr = messenger or Messenger(
            f"client.fs{CephFSClient._next_id}")
        self.msgr.add_dispatcher(self)
        self._tid = 0
        self._waiters: dict[int, asyncio.Future] = {}
        self._session_fut: asyncio.Future | None = None
        self._handles: dict[str, list[FileHandle]] = {}
        self._inflight: dict[str, int] = {}     # path -> writes in flight
        self._renew_task: asyncio.Task | None = None
        self._own_rados = None          # set by create(): owned identity
        self.lease_interval = 3.0       # renew beat; the OPEN ack's
                                        # advertised lease overrides it
        # HA state: fsmap-following mode (addresses resolved at runtime)
        self._ha = mds_addr is None
        self.fsmap: FSMap | None = None
        # -- per-rank session state (round 7) --------------------------
        # rank -> current target address (the rank holder's)
        self._rank_addrs: dict[int, "object"] = {}
        # rank -> Event set while the rank is targetable; requests for
        # a rank mid-failover park on it (rank 0's doubles as the
        # mount gate)
        self._active_event = asyncio.Event()
        self._rank_events: dict[int, asyncio.Event] = {
            0: self._active_event}
        # ranks with an OPEN session; sessions open lazily per rank
        self._open_ranks: set[int] = set()
        # per-rank incarnation: bumped on every (re)established
        # session; _request resends exactly once per (rank,
        # incarnation) — op replay without duplicate sends to a
        # live-but-slow MDS
        self._rank_inc: dict[int, int] = {}
        # redirect-learned routing hints (subtree root -> rank):
        # a -ESTALE reply can be AHEAD of our fsmap; retired once an
        # fsmap that agrees arrives
        self._hints: dict[str, int] = {}
        self._session_lock = asyncio.Lock()     # one OPEN in flight
        self._reconnect_lock = asyncio.Lock()   # one rank reconnects
        self._reconnecting: set[int] = set()
        self._reconnect_fut: asyncio.Future | None = None
        # metadata-path tracing: share the data-path objecter's tracer
        # (one client identity, one span stream + one MTraceReport
        # flush path)
        self._objecter = ioctx.rados.objecter
        self.tracer = self._objecter.tracer
        if not self._ha:
            self._active_event.set()
            self._rank_addrs[0] = mds_addr

    @classmethod
    async def create(cls, monmap, mds_addr, pool: str,
                     keyring=None,
                     config: dict | None = None,
                     name: str | None = None) -> "CephFSClient":
        """Mount with an OWN RADOS identity — the libcephfs model: ONE
        entity name carries both the MDS sessions and the data-path
        ops, so an MDS eviction's osd blocklist actually fences this
        client's data writes (data I/O through a shared admin ioctx
        would dodge the fence).

        ``mds_addr=None`` mounts in **HA mode**: the client subscribes
        to the mdsmap through its own MonClient and follows every
        rank's holder across failovers and subtree migrations instead
        of pinning one address.

        ``name`` pins the entity identity (a provisioned entity whose
        committed caps should bind at the MDS/OSD gates); default is a
        fresh ``client.fsN``."""
        from ceph_tpu.rados import Rados
        CephFSClient._next_id += 1
        pinned = name is not None
        if name is None:
            name = f"client.fs{CephFSClient._next_id}"
        if keyring is not None:
            if pinned:
                # a pinned name is a PROVISIONED identity: its key
                # must already be in this keyring (auth get-or-create
                # committed and the MAuthUpdate push landed). Minting
                # a fresh key here would diverge from the mon's record
                # and fail far from the cause — fail loudly instead.
                if name not in keyring.keys:
                    from ceph_tpu.msg.auth import AuthError
                    raise AuthError(
                        f"pinned entity {name} has no key in this "
                        "keyring — did its auth get-or-create commit "
                        "and propagate here yet?")
            else:
                keyring.add(name)
        # config reaches the owned objecter's tracer: without it a
        # cluster running trace_sampling_rate>0 would never see this
        # client's metadata/data roots (the cluster knobs only live in
        # daemon config dicts)
        r = Rados(monmap, name=name, keyring=keyring, config=config)
        await r.connect()
        io = await r.open_ioctx(pool)
        # warm this identity's data path up front: its first op would
        # otherwise jit the placement pipeline mid-session and stall
        # the shared event loop (blowing MDS beacon graces cluster-
        # wide on an in-process cluster)
        from ceph_tpu.rados import ObjectOperationError
        try:
            await io.stat(".fs_warmup")
        except ObjectOperationError:
            pass
        # the MDS-facing messenger matches the MDS's auth mode (the
        # MDS messenger carries no keyring); the DATA path — where the
        # blocklist fence bites — authenticates through the owned
        # Rados above. The shared identity is the entity NAME.
        cl = cls(io, mds_addr, messenger=Messenger(name))
        cl._own_rados = r
        if cl._ha:
            # MMDSMap publishes ride the MonClient's messenger
            r.monc.msgr.add_dispatcher(cl)
            await r.monc.subscribe("mdsmap", 0)
        return await cl.mount()

    # -- session -----------------------------------------------------------
    async def mount(self) -> "CephFSClient":
        if self._ha:
            await self._wait_active(timeout=30.0)
            await self._ensure_session(0, timeout=30.0)
        else:
            await self._open_session(0, self.mds_addr)
        # cap-lease heartbeat (ref: Client::renew_caps): without it the
        # MDS evicts us the moment a revoke finds our lease stale.
        self._renew_task = asyncio.ensure_future(self._renew_loop())
        return self

    async def _wait_active(self, timeout: float,
                           rank: int = 0) -> None:
        ev = self._rank_events.setdefault(rank, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(), timeout=timeout)
        except asyncio.TimeoutError:
            raise FSError(-110, f"no active mds for rank {rank}") \
                from None

    async def _open_session(self, rank: int, addr) -> None:
        """One OPEN round-trip to ``addr``; on ack the rank is usable
        (serialized — replies carry no tid, so one OPEN at a time)."""
        async with self._session_lock:
            if rank in self._open_ranks and \
                    self._rank_addrs.get(rank) is addr:
                return
            self._session_fut = \
                asyncio.get_event_loop().create_future()
            await self.msgr.send_message(
                MClientSession(op=SESSION_OPEN, cseq=0), addr, "mds")
            ack = await asyncio.wait_for(self._session_fut, timeout=10)
            # the OPEN ack advertises the MDS lease (ms); renew at a
            # third of it so a short-leased MDS never sees a live
            # client go stale
            if getattr(ack, "cseq", 0):
                self.lease_interval = max(0.05, ack.cseq / 3000.0)
            self._rank_addrs[rank] = addr
            self._open_ranks.add(rank)
            self._rank_inc[rank] = self._rank_inc.get(rank, 0) + 1
            if rank == 0:
                self.mds_addr = addr
            self._rank_events.setdefault(rank, asyncio.Event()).set()

    async def _ensure_session(self, rank: int,
                              timeout: float = 10.0) -> None:
        """Open a session with ``rank`` if we don't have one (sessions
        are lazy: only ranks the subtree map actually routes us to get
        one — ref: Client opening sessions per chosen MDS)."""
        if rank in self._open_ranks:
            return
        await self._wait_active(timeout, rank)
        addr = self._rank_addrs.get(rank)
        if addr is None:
            raise FSError(-110, f"rank {rank} has no address")
        await self._open_session(rank, addr)

    async def _renew_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.lease_interval)
                for rank in sorted(self._open_ranks):
                    addr = self._rank_addrs.get(rank)
                    if addr is None:
                        continue
                    try:
                        await self.msgr.send_message(
                            MClientSession(op=SESSION_RENEW, cseq=0),
                            addr, "mds")
                    except (ConnectionError, OSError,
                            ConnectionError_):
                        # transient (injected fault or mid-failover):
                        # a missed beat must NOT end the heartbeat — a
                        # silently dead renew loop gets a perfectly
                        # live client evicted at the next revoke
                        continue
        except asyncio.CancelledError:
            pass

    async def unmount(self) -> None:
        if self._renew_task is not None:
            self._renew_task.cancel()
            self._renew_task = None
        for hs in list(self._handles.values()):   # close() mutates the
            for h in list(hs):                    # dict and the lists
                await h.close()
        for rank in sorted(self._open_ranks):
            addr = self._rank_addrs.get(rank)
            if addr is None:
                continue
            try:
                async with self._session_lock:
                    self._session_fut = \
                        asyncio.get_event_loop().create_future()
                    await self.msgr.send_message(
                        MClientSession(op=SESSION_CLOSE, cseq=0),
                        addr, "mds")
                    await asyncio.wait_for(self._session_fut,
                                           timeout=10)
            except (ConnectionError, OSError, ConnectionError_,
                    asyncio.TimeoutError) as e:
                # best effort: the MDS may be mid-failover/dead; its
                # session-table grace machinery reaps us server-side
                log.dout(1, f"session close (rank {rank}) skipped: "
                            f"{e!r}")
        await self.msgr.shutdown()
        if self._own_rados is not None:
            await self._own_rados.shutdown()
            self._own_rados = None

    # -- dispatch ----------------------------------------------------------
    async def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MClientReply):
            fut = self._waiters.pop(msg.tid, None)
            if fut and not fut.done():
                fut.set_result(msg)
            return True
        if isinstance(msg, MClientSession):
            if msg.op != SESSION_RENEW and self._session_fut \
                    and not self._session_fut.done():
                self._session_fut.set_result(msg)
            return True
        if isinstance(msg, MClientReconnect):
            if self._reconnect_fut and not self._reconnect_fut.done():
                self._reconnect_fut.set_result(msg)
            return True
        if isinstance(msg, MMDSMap):
            self._on_fsmap(FSMap.decode(msg.fsmap))
            return True
        if isinstance(msg, MClientCaps):
            if msg.op == CAP_OP_REVOKE:
                # handled in a task: the ack must wait for in-flight
                # writes, whose setattr REPLIES arrive on this very
                # connection — blocking the reader here would deadlock
                asyncio.ensure_future(self._handle_revoke(msg))
            return True
        return False

    # -- routing (ref: Client::choose_target_mds) --------------------------
    def _route(self, path: str) -> int:
        """Owning rank for a normalized path: redirect hints overlay
        the fsmap's subtree map (a hint can be AHEAD of the map; equal
        or longer roots win)."""
        if not self._ha:
            return 0
        fm = self.fsmap
        best_rank, best_root = fm.subtree_owner(path) if fm is not None \
            else (0, "/")
        for root, rank in self._hints.items():
            if (path == root or path.startswith(root + "/")) and \
                    len(root) >= len(best_root):
                best_root, best_rank = root, rank
        return best_rank

    # -- failover (ref: Client::handle_mds_map + send_reconnect) ----------
    def _on_fsmap(self, fm: FSMap) -> None:
        if self.fsmap is not None and fm.epoch <= self.fsmap.epoch:
            return
        self.fsmap = fm
        # retire hints the authoritative map has caught up with
        for root in [r for r, rk in self._hints.items()
                     if fm.subtree_owner(r) == (rk, r)]:
            self._hints.pop(root, None)
        holders = fm.rank_holders()
        for rank in sorted(set(holders) | set(self._rank_addrs)
                           | self._open_ranks):
            if not self._ha:
                break
            info = holders.get(rank)
            ev = self._rank_events.setdefault(rank, asyncio.Event())
            if info is None or info.state not in _RECONNECTABLE:
                # rank failed / mid-ladder with no reconnectable
                # successor: park its requests until one appears
                if rank in self._open_ranks:
                    ev.clear()
                continue
            addr = info.addr()
            cur = self._rank_addrs.get(rank)
            if cur is not None and (addr.host, addr.port) == \
                    (cur.host, cur.port):
                ev.set()
                continue
            if rank not in self._open_ranks:
                # no session yet: just aim (a session opens lazily the
                # first time a request routes here)
                if info.state == STATE_ACTIVE:
                    self._rank_addrs[rank] = addr
                    if rank == 0:
                        self.mds_addr = addr
                    ev.set()
                continue
            # holder changed for a rank we hold a session with:
            # reconnect (cap replay) against the successor
            ev.clear()
            asyncio.ensure_future(self._reconnect_rank(rank))

    async def _reconnect_rank(self, rank: int) -> None:
        """Re-establish this rank's session against whatever daemon
        now holds it: replay cap claims for the paths the rank serves
        (MClientReconnect), or on reject (session missed the window)
        open a fresh session with every affected handle invalidated.
        One loop per rank at a time; each attempt re-reads the fsmap
        so back-to-back failovers re-aim it."""
        if rank in self._reconnecting:
            return
        self._reconnecting.add(rank)
        try:
            for attempt in range(120):
                holder = self.fsmap.rank_holder(rank) if self.fsmap \
                    else None
                if holder is None or \
                        holder.state not in _RECONNECTABLE:
                    await asyncio.sleep(0.1)
                    continue
                addr = holder.addr()
                caps = {}
                for path, hs in self._handles.items():
                    if self._route(path) != rank:
                        continue
                    live = [h for h in hs if h.valid]
                    if not live:
                        continue
                    caps[path] = json.dumps({
                        "mode": max(h.mode for h in live),
                        "count": len(live),
                        "cseq": max(h.cap_seq for h in live),
                    }).encode()
                async with self._reconnect_lock:
                    self._reconnect_fut = \
                        asyncio.get_event_loop().create_future()
                    try:
                        await self.msgr.send_message(MClientReconnect(
                            op=RECONNECT_REQ, caps=caps), addr, "mds")
                        rep = await asyncio.wait_for(
                            self._reconnect_fut, timeout=5.0)
                    except (ConnectionError, OSError,
                            ConnectionError_, asyncio.TimeoutError):
                        await asyncio.sleep(0.1)
                        continue
                self._rank_addrs[rank] = addr
                if rank == 0:
                    self.mds_addr = addr
                if rep.op == RECONNECT_ACK:
                    log.dout(1, f"reconnected to rank {rank} at "
                                f"{addr} ({len(caps)} caps replayed)")
                else:
                    # session unknown (missed the reconnect window):
                    # this rank's caps are dead — invalidate every
                    # affected handle (next I/O reacquires) and open a
                    # fresh session
                    log.dout(1, f"reconnect to rank {rank} rejected "
                                f"by {addr}; re-opening session")
                    for path, hs in self._handles.items():
                        if self._route(path) != rank:
                            continue
                        for h in hs:
                            h.valid = False
                    self._open_ranks.discard(rank)
                    try:
                        await self._open_session(rank, addr)
                    except (ConnectionError, OSError,
                            ConnectionError_,
                            asyncio.TimeoutError):
                        await asyncio.sleep(0.1)
                        continue
                # wake request loops: they resend once per incarnation
                self._open_ranks.add(rank)
                self._rank_inc[rank] = self._rank_inc.get(rank, 0) + 1
                self._rank_events.setdefault(
                    rank, asyncio.Event()).set()
                return
            log.dout(0, f"rank {rank} reconnect gave up after retries")
        finally:
            self._reconnecting.discard(rank)

    async def _handle_revoke(self, msg) -> None:
        for h in self._handles.get(msg.path, []):
            h.valid = False         # future I/O must reacquire first
        while self._inflight.get(msg.path, 0) > 0:
            await asyncio.sleep(0.01)   # writers flush before the ack
        await msg.conn.send_message(MClientCaps(
            op=CAP_OP_ACK, path=msg.path, mode=msg.mode,
            cseq=msg.cseq))

    async def _send_caps(self, op: int, path: str, mode: int,
                         seq: int) -> None:
        rank = self._route(_norm(path))
        addr = self._rank_addrs.get(rank) or self.mds_addr
        if addr is None:
            # rank mid-failover with no successor yet: a RELEASE is
            # advisory (the MDS reaps dead holders via the cap lease)
            log.dout(5, f"cap send skipped: no addr for rank {rank}")
            return
        await self.msgr.send_message(
            MClientCaps(op=op, path=path, mode=mode, cseq=seq),
            addr, "mds")

    # -- requests ----------------------------------------------------------
    async def _request(self, op: str, path: str, path2: str = "",
                       flags: int = 0,
                       timeout: float = 40.0) -> MClientReply:
        self._tid += 1
        tid = self._tid
        npath = _norm(path)
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        self._waiters[tid] = fut
        msg = MClientRequest(tid=tid, op=op, path=path, path2=path2,
                             flags=flags)
        # metadata-path root span (op_class "metadata"): propagates to
        # the serving rank; -ESTALE redirect hops are tagged so a
        # cross-rank bounce is visible in the reassembled trace
        span = self.tracer.start_root(
            "mds_req", tags={"op": op, "path": npath,
                             "op_class": "metadata"})
        msg.set_trace(span)
        deadline = loop.time() + timeout
        sent_key = None
        redirects = 0
        try:
            while True:
                if fut.done():
                    reply = fut.result()
                    if self._ha and reply.result == ESTALE:
                        # redirect: the serving rank named the owner —
                        # record the hint, re-arm the waiter, resend
                        # to the right rank (same tid: the redirecting
                        # rank executed nothing)
                        redirects += 1
                        if redirects > _MAX_REDIRECTS:
                            raise FSError(
                                ESTALE, f"{op} {path}: redirect loop "
                                        f"(map flapping?)")
                        try:
                            hint = json.loads(reply.payload)
                            self._hints[str(hint["path"])] = \
                                int(hint["rank"])
                        except (json.JSONDecodeError, KeyError,
                                ValueError, TypeError):
                            pass
                        fut = loop.create_future()
                        self._waiters[tid] = fut
                        sent_key = None
                        continue
                    break
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise asyncio.TimeoutError
                rank = self._route(npath)
                if self._ha:
                    ev = self._rank_events.setdefault(
                        rank, asyncio.Event())
                    if not ev.is_set():
                        # failover in progress: park until a successor
                        # is reachable, then fall through to resend
                        try:
                            await asyncio.wait_for(
                                ev.wait(),
                                timeout=min(remaining, 1.0))
                        except asyncio.TimeoutError:
                            continue      # re-route: hint/map may have
                        continue          # moved the path meanwhile
                    if rank not in self._open_ranks:
                        try:
                            await self._ensure_session(
                                rank, timeout=min(remaining, 10.0))
                        except (FSError, ConnectionError, OSError,
                                ConnectionError_,
                                asyncio.TimeoutError):
                            await asyncio.sleep(0.2)
                        continue
                    addr = self._rank_addrs.get(rank)
                    key = (rank, self._rank_inc.get(rank, 0))
                else:
                    addr = self.mds_addr
                    key = (0, 0)
                if sent_key != key and addr is not None:
                    # op replay: exactly one send per (rank, MDS
                    # incarnation) — the successor's completed-request
                    # table dedups mutations that landed before the
                    # crash, and a live-but-slow MDS is never spammed
                    # with duplicates (a duplicate open would leak a
                    # cap refcount)
                    try:
                        await self.msgr.send_message(msg, addr, "mds")
                        sent_key = key
                    except (ConnectionError, OSError,
                            ConnectionError_):
                        if not self._ha:
                            raise
                        await asyncio.sleep(0.2)
                        continue
                try:
                    reply = await asyncio.wait_for(
                        asyncio.shield(fut),
                        timeout=min(1.0, max(remaining, 0.05)))
                    if not (self._ha and reply.result == ESTALE):
                        break
                    # loop top handles the redirect bookkeeping
                except asyncio.TimeoutError:
                    continue
        finally:
            self._waiters.pop(tid, None)
            if span is not None:
                if redirects:
                    span.tag("redirects", redirects)
                span.finish()
            self._objecter.flush_traces()
        if reply.result < 0:
            raise FSError(int(reply.result),
                          reply.payload.decode(errors="replace"))
        return reply

    # -- namespace (ref: libcephfs.h) --------------------------------------
    async def mkdir(self, path: str) -> None:
        await self._request("mkdir", path)

    async def rmdir(self, path: str) -> None:
        await self._request("rmdir", path)

    async def ls(self, path: str = "/") -> list[str]:
        r = await self._request("readdir", path)
        return json.loads(r.payload)

    async def stat(self, path: str) -> dict:
        r = await self._request("stat", path)
        return json.loads(r.payload)

    async def unlink(self, path: str) -> None:
        await self._request("unlink", path)

    async def rename(self, src: str, dst: str) -> None:
        await self._request("rename", src, path2=dst)

    # -- files (cap-gated) -------------------------------------------------
    async def open_file(self, path: str, mode: str = "r") -> FileHandle:
        """'r' wants shared-read; 'w' wants exclusive-write (creating
        the file if absent). Blocks while conflicting caps are being
        revoked from other clients."""
        path = _norm(path)        # cap/revoke bookkeeping is keyed on
        want = CAP_FW if mode == "w" else CAP_FR   # the normalized path
        r = await self._request("open", path, flags=want)
        info = json.loads(r.payload)
        # the handle keeps the REQUESTED mode, not the granted one: a
        # reader whose client happens to hold FW must neither pass the
        # write check nor reacquire exclusivity after a revoke
        snapc = info.get("snapc")
        h = FileHandle(self, path, info["oid"], want,
                       int(r.cap_seq), int(info["size"]),
                       snap_id=info.get("snapid"),
                       snapc=(int(snapc[0]), [int(s) for s in snapc[1]])
                       if snapc else None)
        self._handles.setdefault(h.path, []).append(h)
        return h

    async def read_file(self, path: str) -> bytes:
        h = await self.open_file(path, "r")
        try:
            return await h.read()
        finally:
            await h.close()

    async def write_file(self, path: str, data: bytes) -> int:
        h = await self.open_file(path, "w")
        try:
            return await h.write(data)
        finally:
            await h.close()
