"""CephFS client: metadata through the MDS, data direct to RADOS.

ref: src/client/Client.{h,cc} (the libcephfs backend) — every
namespace operation is an MClientRequest round-trip to the MDS; file
reads/writes go straight to the data objects, gated by the file
capabilities the MDS granted at open. A revoke arriving from the MDS
invalidates the handle (writers have nothing to flush — writes here
are write-through) and is acked immediately; the next I/O through
that handle re-opens to reacquire a cap, which blocks until the
conflicting holder is done — giving one-writer-or-many-readers
semantics across clients.
"""

from __future__ import annotations

import asyncio
import json

from ceph_tpu.cephfs import FSError, _norm
from ceph_tpu.cephfs.mds import (
    CAP_FR, CAP_FW, CAP_OP_ACK, CAP_OP_RELEASE, CAP_OP_REVOKE,
    MClientCaps, MClientReply, MClientRequest, MClientSession,
    SESSION_CLOSE, SESSION_OPEN, SESSION_RENEW,
)
from ceph_tpu.msg import Dispatcher, Messenger
from ceph_tpu.utils.logging import get_logger

log = get_logger("cephfs.client")


class FileHandle:
    """An open file + the cap that licenses its I/O."""

    def __init__(self, client: "CephFSClient", path: str, oid: str,
                 mode: int, cap_seq: int, size: int):
        self.client = client
        self.path = path
        self.oid = oid
        self.mode = mode
        self.cap_seq = cap_seq
        self.size = size
        self.valid = True

    async def _ensure(self) -> None:
        if not self.valid:
            fresh = await self.client.open_file(
                self.path, "w" if self.mode == CAP_FW else "r")
            self.__dict__.update(fresh.__dict__)
            # this handle now IS the reacquired cap; drop the twin so
            # _handles doesn't accumulate orphans (its registration
            # transfers to self)
            hs = self.client._handles.get(self.path, [])
            if fresh in hs:
                hs.remove(fresh)
            if self not in hs:
                hs.append(self)

    async def read(self, length: int = 0, offset: int = 0) -> bytes:
        await self._ensure()
        want = length or max(self.size - offset, 0)
        if want <= 0:
            return b""
        return await self.client.ioctx.read(self.oid, length=want,
                                            offset=offset)

    async def write(self, data: bytes, offset: int = 0) -> int:
        await self._ensure()
        if self.mode != CAP_FW:
            raise FSError(-9, "handle not open for write")  # -EBADF
        # in-flight accounting: a revoke arriving mid-write must not be
        # acked until the data write AND its setattr have landed (the
        # "writers flush before acking" half of the cap contract)
        self.client._inflight[self.path] = \
            self.client._inflight.get(self.path, 0) + 1
        try:
            if offset:
                await self.client.ioctx.write(self.oid, data,
                                              offset=offset)
                self.size = max(self.size, offset + len(data))
            else:
                await self.client.ioctx.write_full(self.oid, data)
                self.size = len(data)
            # dentry size rides a setattr through the MDS (metadata is
            # always MDS-authoritative)
            await self.client._request("setattr", self.path,
                                       flags=self.size)
        finally:
            self.client._inflight[self.path] -= 1
            if self.client._inflight[self.path] <= 0:
                self.client._inflight.pop(self.path, None)
        return len(data)

    async def close(self) -> None:
        hs = self.client._handles.get(self.path, [])
        if self in hs:
            hs.remove(self)
        if not hs:
            self.client._handles.pop(self.path, None)
        if self.valid:
            self.valid = False
            await self.client._send_caps(CAP_OP_RELEASE, self.path,
                                         self.mode, self.cap_seq)


class CephFSClient(Dispatcher):
    """ref: libcephfs.h surface, MDS-backed."""

    _next_id = 0

    def __init__(self, ioctx, mds_addr,
                 messenger: Messenger | None = None):
        CephFSClient._next_id += 1
        self.ioctx = ioctx
        self.mds_addr = mds_addr
        self.msgr = messenger or Messenger(
            f"client.fs{CephFSClient._next_id}")
        self.msgr.add_dispatcher(self)
        self._tid = 0
        self._waiters: dict[int, asyncio.Future] = {}
        self._session_fut: asyncio.Future | None = None
        self._handles: dict[str, list[FileHandle]] = {}
        self._inflight: dict[str, int] = {}     # path -> writes in flight
        self._renew_task: asyncio.Task | None = None
        self._own_rados = None          # set by create(): owned identity
        self.lease_interval = 3.0       # renew beat; the OPEN ack's
                                        # advertised lease overrides it

    @classmethod
    async def create(cls, monmap, mds_addr, pool: str,
                     keyring=None) -> "CephFSClient":
        """Mount with an OWN RADOS identity — the libcephfs model: ONE
        entity name carries both the MDS session and the data-path ops,
        so an MDS eviction's osd blocklist actually fences this
        client's data writes (data I/O through a shared admin ioctx
        would dodge the fence)."""
        from ceph_tpu.rados import Rados
        CephFSClient._next_id += 1
        name = f"client.fs{CephFSClient._next_id}"
        if keyring is not None:
            keyring.add(name)
        r = Rados(monmap, name=name, keyring=keyring)
        await r.connect()
        io = await r.open_ioctx(pool)
        # the MDS-facing messenger matches the MDS's auth mode (the
        # MDS messenger carries no keyring); the DATA path — where the
        # blocklist fence bites — authenticates through the owned
        # Rados above. The shared identity is the entity NAME.
        cl = cls(io, mds_addr, messenger=Messenger(name))
        cl._own_rados = r
        return await cl.mount()

    # -- session -----------------------------------------------------------
    async def mount(self) -> "CephFSClient":
        self._session_fut = asyncio.get_event_loop().create_future()
        await self.msgr.send_message(
            MClientSession(op=SESSION_OPEN, cseq=0), self.mds_addr,
            "mds")
        ack = await asyncio.wait_for(self._session_fut, timeout=10)
        # cap-lease heartbeat (ref: Client::renew_caps): without it the
        # MDS evicts us the moment a revoke finds our lease stale. The
        # OPEN ack advertises the MDS lease (ms); renew at a third of
        # it so a short-leased MDS never sees a live client go stale.
        if getattr(ack, "cseq", 0):
            self.lease_interval = max(0.05, ack.cseq / 3000.0)
        self._renew_task = asyncio.ensure_future(self._renew_loop())
        return self

    async def _renew_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.lease_interval)
                try:
                    await self.msgr.send_message(
                        MClientSession(op=SESSION_RENEW, cseq=0),
                        self.mds_addr, "mds")
                except (ConnectionError, OSError):
                    # transient (e.g. injected socket failure): a
                    # single missed beat must NOT end the heartbeat —
                    # a silently dead renew loop gets a perfectly
                    # live client evicted and blocklisted at the next
                    # revoke
                    continue
        except asyncio.CancelledError:
            pass

    async def unmount(self) -> None:
        if self._renew_task is not None:
            self._renew_task.cancel()
            self._renew_task = None
        for hs in list(self._handles.values()):   # close() mutates the
            for h in list(hs):                    # dict and the lists
                await h.close()
        self._session_fut = asyncio.get_event_loop().create_future()
        await self.msgr.send_message(
            MClientSession(op=SESSION_CLOSE, cseq=0), self.mds_addr,
            "mds")
        await asyncio.wait_for(self._session_fut, timeout=10)
        await self.msgr.shutdown()
        if self._own_rados is not None:
            await self._own_rados.shutdown()
            self._own_rados = None

    # -- dispatch ----------------------------------------------------------
    async def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MClientReply):
            fut = self._waiters.pop(msg.tid, None)
            if fut and not fut.done():
                fut.set_result(msg)
            return True
        if isinstance(msg, MClientSession):
            if msg.op != SESSION_RENEW and self._session_fut \
                    and not self._session_fut.done():
                self._session_fut.set_result(msg)
            return True
        if isinstance(msg, MClientCaps):
            if msg.op == CAP_OP_REVOKE:
                # handled in a task: the ack must wait for in-flight
                # writes, whose setattr REPLIES arrive on this very
                # connection — blocking the reader here would deadlock
                asyncio.ensure_future(self._handle_revoke(msg))
            return True
        return False

    async def _handle_revoke(self, msg) -> None:
        for h in self._handles.get(msg.path, []):
            h.valid = False         # future I/O must reacquire first
        while self._inflight.get(msg.path, 0) > 0:
            await asyncio.sleep(0.01)   # writers flush before the ack
        await msg.conn.send_message(MClientCaps(
            op=CAP_OP_ACK, path=msg.path, mode=msg.mode,
            cseq=msg.cseq))

    async def _send_caps(self, op: int, path: str, mode: int,
                         seq: int) -> None:
        await self.msgr.send_message(
            MClientCaps(op=op, path=path, mode=mode, cseq=seq),
            self.mds_addr, "mds")

    # -- requests ----------------------------------------------------------
    async def _request(self, op: str, path: str, path2: str = "",
                       flags: int = 0) -> MClientReply:
        self._tid += 1
        tid = self._tid
        fut = asyncio.get_event_loop().create_future()
        self._waiters[tid] = fut
        await self.msgr.send_message(
            MClientRequest(tid=tid, op=op, path=path, path2=path2,
                           flags=flags), self.mds_addr, "mds")
        reply = await asyncio.wait_for(fut, timeout=40)
        if reply.result < 0:
            raise FSError(int(reply.result),
                          reply.payload.decode(errors="replace"))
        return reply

    # -- namespace (ref: libcephfs.h) --------------------------------------
    async def mkdir(self, path: str) -> None:
        await self._request("mkdir", path)

    async def rmdir(self, path: str) -> None:
        await self._request("rmdir", path)

    async def ls(self, path: str = "/") -> list[str]:
        r = await self._request("readdir", path)
        return json.loads(r.payload)

    async def stat(self, path: str) -> dict:
        r = await self._request("stat", path)
        return json.loads(r.payload)

    async def unlink(self, path: str) -> None:
        await self._request("unlink", path)

    async def rename(self, src: str, dst: str) -> None:
        await self._request("rename", src, path2=dst)

    # -- files (cap-gated) -------------------------------------------------
    async def open_file(self, path: str, mode: str = "r") -> FileHandle:
        """'r' wants shared-read; 'w' wants exclusive-write (creating
        the file if absent). Blocks while conflicting caps are being
        revoked from other clients."""
        path = _norm(path)        # cap/revoke bookkeeping is keyed on
        want = CAP_FW if mode == "w" else CAP_FR   # the normalized path
        r = await self._request("open", path, flags=want)
        info = json.loads(r.payload)
        # the handle keeps the REQUESTED mode, not the granted one: a
        # reader whose client happens to hold FW must neither pass the
        # write check nor reacquire exclusivity after a revoke
        h = FileHandle(self, path, info["oid"], want,
                       int(r.cap_seq), int(info["size"]))
        self._handles.setdefault(h.path, []).append(h)
        return h

    async def read_file(self, path: str) -> bytes:
        h = await self.open_file(path, "r")
        try:
            return await h.read()
        finally:
            await h.close()

    async def write_file(self, path: str, data: bytes) -> int:
        h = await self.open_file(path, "w")
        try:
            return await h.write(data)
        finally:
            await h.close()
