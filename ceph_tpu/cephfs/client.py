"""CephFS client: metadata through the MDS, data direct to RADOS.

ref: src/client/Client.{h,cc} (the libcephfs backend) — every
namespace operation is an MClientRequest round-trip to the MDS; file
reads/writes go straight to the data objects, gated by the file
capabilities the MDS granted at open. A revoke arriving from the MDS
invalidates the handle (writers have nothing to flush — writes here
are write-through) and is acked immediately; the next I/O through
that handle re-opens to reacquire a cap, which blocks until the
conflicting holder is done — giving one-writer-or-many-readers
semantics across clients.

HA (round 6): a client mounted without a pinned MDS address
(``create(monmap, None, pool)``) subscribes to the **mdsmap** and
targets whatever daemon the FSMap says holds rank 0. On failover it
sends MClientReconnect to the successor — replaying its session and
every live cap claim (ref: Client::send_reconnect) — and resends any
request that never got a reply (op replay; the MDS's completed-request
table dedups mutations that DID land before the crash). Requests
issued while no active exists park until the ladder finishes.
"""

from __future__ import annotations

import asyncio
import json

from ceph_tpu.cephfs import FSError, _norm
from ceph_tpu.cephfs.fsmap import (
    FSMap, STATE_ACTIVE, STATE_RECONNECT, STATE_REJOIN,
)
from ceph_tpu.cephfs.mds import (
    CAP_FR, CAP_FW, CAP_OP_ACK, CAP_OP_RELEASE, CAP_OP_REVOKE,
    MClientCaps, MClientReconnect, MClientReply, MClientRequest,
    MClientSession, RECONNECT_ACK, RECONNECT_REQ,
    SESSION_CLOSE, SESSION_OPEN, SESSION_RENEW,
)
from ceph_tpu.mon.messages import MMDSMap
from ceph_tpu.msg import Dispatcher, Messenger
from ceph_tpu.msg.messenger import ConnectionError_
from ceph_tpu.utils.logging import get_logger

log = get_logger("cephfs.client")

# fsmap states in which the rank holder accepts MClientReconnect
_RECONNECTABLE = (STATE_RECONNECT, STATE_REJOIN, STATE_ACTIVE)


class FileHandle:
    """An open file + the cap that licenses its I/O."""

    def __init__(self, client: "CephFSClient", path: str, oid: str,
                 mode: int, cap_seq: int, size: int):
        self.client = client
        self.path = path
        self.oid = oid
        self.mode = mode
        self.cap_seq = cap_seq
        self.size = size
        self.valid = True

    async def _ensure(self) -> None:
        if not self.valid:
            fresh = await self.client.open_file(
                self.path, "w" if self.mode == CAP_FW else "r")
            self.__dict__.update(fresh.__dict__)
            # this handle now IS the reacquired cap; drop the twin so
            # _handles doesn't accumulate orphans (its registration
            # transfers to self)
            hs = self.client._handles.get(self.path, [])
            if fresh in hs:
                hs.remove(fresh)
            if self not in hs:
                hs.append(self)

    async def read(self, length: int = 0, offset: int = 0) -> bytes:
        await self._ensure()
        want = length or max(self.size - offset, 0)
        if want <= 0:
            return b""
        return await self.client.ioctx.read(self.oid, length=want,
                                            offset=offset)

    async def write(self, data: bytes, offset: int = 0) -> int:
        await self._ensure()
        if self.mode != CAP_FW:
            raise FSError(-9, "handle not open for write")  # -EBADF
        # in-flight accounting: a revoke arriving mid-write must not be
        # acked until the data write AND its setattr have landed (the
        # "writers flush before acking" half of the cap contract)
        self.client._inflight[self.path] = \
            self.client._inflight.get(self.path, 0) + 1
        try:
            if offset:
                await self.client.ioctx.write(self.oid, data,
                                              offset=offset)
                self.size = max(self.size, offset + len(data))
            else:
                await self.client.ioctx.write_full(self.oid, data)
                self.size = len(data)
            # dentry size rides a setattr through the MDS (metadata is
            # always MDS-authoritative)
            await self.client._request("setattr", self.path,
                                       flags=self.size)
        finally:
            self.client._inflight[self.path] -= 1
            if self.client._inflight[self.path] <= 0:
                self.client._inflight.pop(self.path, None)
        return len(data)

    async def close(self) -> None:
        hs = self.client._handles.get(self.path, [])
        if self in hs:
            hs.remove(self)
        if not hs:
            self.client._handles.pop(self.path, None)
        if self.valid:
            self.valid = False
            await self.client._send_caps(CAP_OP_RELEASE, self.path,
                                         self.mode, self.cap_seq)


class CephFSClient(Dispatcher):
    """ref: libcephfs.h surface, MDS-backed."""

    _next_id = 0

    def __init__(self, ioctx, mds_addr=None,
                 messenger: Messenger | None = None):
        CephFSClient._next_id += 1
        self.ioctx = ioctx
        self.mds_addr = mds_addr       # None until the fsmap names one
        self.msgr = messenger or Messenger(
            f"client.fs{CephFSClient._next_id}")
        self.msgr.add_dispatcher(self)
        self._tid = 0
        self._waiters: dict[int, asyncio.Future] = {}
        self._session_fut: asyncio.Future | None = None
        self._handles: dict[str, list[FileHandle]] = {}
        self._inflight: dict[str, int] = {}     # path -> writes in flight
        self._renew_task: asyncio.Task | None = None
        self._own_rados = None          # set by create(): owned identity
        self.lease_interval = 3.0       # renew beat; the OPEN ack's
                                        # advertised lease overrides it
        # HA state: fsmap-following mode (mds_addr resolved at runtime)
        self._ha = mds_addr is None
        self.fsmap: FSMap | None = None
        self._active_event = asyncio.Event()
        if not self._ha:
            self._active_event.set()
        # bumped on every (re)established MDS session; _request resends
        # exactly once per incarnation (op replay without duplicate
        # sends to a live-but-slow MDS)
        self._incarnation = 0
        self._reconnecting = False
        self._reconnect_fut: asyncio.Future | None = None

    @classmethod
    async def create(cls, monmap, mds_addr, pool: str,
                     keyring=None) -> "CephFSClient":
        """Mount with an OWN RADOS identity — the libcephfs model: ONE
        entity name carries both the MDS session and the data-path ops,
        so an MDS eviction's osd blocklist actually fences this
        client's data writes (data I/O through a shared admin ioctx
        would dodge the fence).

        ``mds_addr=None`` mounts in **HA mode**: the client subscribes
        to the mdsmap through its own MonClient and follows rank 0's
        holder across failovers instead of pinning one address."""
        from ceph_tpu.rados import Rados
        CephFSClient._next_id += 1
        name = f"client.fs{CephFSClient._next_id}"
        if keyring is not None:
            keyring.add(name)
        r = Rados(monmap, name=name, keyring=keyring)
        await r.connect()
        io = await r.open_ioctx(pool)
        # warm this identity's data path up front: its first op would
        # otherwise jit the placement pipeline mid-session and stall
        # the shared event loop (blowing MDS beacon graces cluster-
        # wide on an in-process cluster)
        from ceph_tpu.rados import ObjectOperationError
        try:
            await io.stat(".fs_warmup")
        except ObjectOperationError:
            pass
        # the MDS-facing messenger matches the MDS's auth mode (the
        # MDS messenger carries no keyring); the DATA path — where the
        # blocklist fence bites — authenticates through the owned
        # Rados above. The shared identity is the entity NAME.
        cl = cls(io, mds_addr, messenger=Messenger(name))
        cl._own_rados = r
        if cl._ha:
            # MMDSMap publishes ride the MonClient's messenger
            r.monc.msgr.add_dispatcher(cl)
            await r.monc.subscribe("mdsmap", 0)
        return await cl.mount()

    # -- session -----------------------------------------------------------
    async def mount(self) -> "CephFSClient":
        if self._ha:
            await self._wait_active(timeout=30.0)
        await self._open_session()
        self._incarnation += 1
        # cap-lease heartbeat (ref: Client::renew_caps): without it the
        # MDS evicts us the moment a revoke finds our lease stale.
        self._renew_task = asyncio.ensure_future(self._renew_loop())
        return self

    async def _wait_active(self, timeout: float) -> None:
        try:
            await asyncio.wait_for(self._active_event.wait(),
                                   timeout=timeout)
        except asyncio.TimeoutError:
            raise FSError(-110, "no active mds") from None

    async def _open_session(self) -> None:
        self._session_fut = asyncio.get_event_loop().create_future()
        await self.msgr.send_message(
            MClientSession(op=SESSION_OPEN, cseq=0), self.mds_addr,
            "mds")
        ack = await asyncio.wait_for(self._session_fut, timeout=10)
        # the OPEN ack advertises the MDS lease (ms); renew at a third
        # of it so a short-leased MDS never sees a live client go stale
        if getattr(ack, "cseq", 0):
            self.lease_interval = max(0.05, ack.cseq / 3000.0)

    async def _renew_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.lease_interval)
                if self.mds_addr is None:
                    continue
                try:
                    await self.msgr.send_message(
                        MClientSession(op=SESSION_RENEW, cseq=0),
                        self.mds_addr, "mds")
                except (ConnectionError, OSError, ConnectionError_):
                    # transient (e.g. injected socket failure or a
                    # mid-failover window): a missed beat must NOT end
                    # the heartbeat — a silently dead renew loop gets
                    # a perfectly live client evicted and blocklisted
                    # at the next revoke
                    continue
        except asyncio.CancelledError:
            pass

    async def unmount(self) -> None:
        if self._renew_task is not None:
            self._renew_task.cancel()
            self._renew_task = None
        for hs in list(self._handles.values()):   # close() mutates the
            for h in list(hs):                    # dict and the lists
                await h.close()
        try:
            self._session_fut = \
                asyncio.get_event_loop().create_future()
            await self.msgr.send_message(
                MClientSession(op=SESSION_CLOSE, cseq=0),
                self.mds_addr, "mds")
            await asyncio.wait_for(self._session_fut, timeout=10)
        except (ConnectionError, OSError, ConnectionError_,
                asyncio.TimeoutError) as e:
            # best effort: the MDS may be mid-failover/dead; its
            # session-table grace machinery reaps us server-side
            log.dout(1, f"session close skipped: {e!r}")
        await self.msgr.shutdown()
        if self._own_rados is not None:
            await self._own_rados.shutdown()
            self._own_rados = None

    # -- dispatch ----------------------------------------------------------
    async def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MClientReply):
            fut = self._waiters.pop(msg.tid, None)
            if fut and not fut.done():
                fut.set_result(msg)
            return True
        if isinstance(msg, MClientSession):
            if msg.op != SESSION_RENEW and self._session_fut \
                    and not self._session_fut.done():
                self._session_fut.set_result(msg)
            return True
        if isinstance(msg, MClientReconnect):
            if self._reconnect_fut and not self._reconnect_fut.done():
                self._reconnect_fut.set_result(msg)
            return True
        if isinstance(msg, MMDSMap):
            self._on_fsmap(FSMap.decode(msg.fsmap))
            return True
        if isinstance(msg, MClientCaps):
            if msg.op == CAP_OP_REVOKE:
                # handled in a task: the ack must wait for in-flight
                # writes, whose setattr REPLIES arrive on this very
                # connection — blocking the reader here would deadlock
                asyncio.ensure_future(self._handle_revoke(msg))
            return True
        return False

    # -- failover (ref: Client::handle_mds_map + send_reconnect) ----------
    def _on_fsmap(self, fm: FSMap) -> None:
        if self.fsmap is not None and fm.epoch <= self.fsmap.epoch:
            return
        self.fsmap = fm
        holder = fm.rank_holder(0)
        if holder is None or holder.state not in _RECONNECTABLE:
            # rank failed and no successor far enough up the ladder:
            # park new requests until one appears
            if self._incarnation:
                self._active_event.clear()
            return
        addr = holder.addr()
        if self.mds_addr is not None and \
                (addr.host, addr.port) == (self.mds_addr.host,
                                           self.mds_addr.port):
            self._active_event.set()
            return
        if not self._incarnation:
            # never mounted: just aim at the holder (mount() opens the
            # session once it is active)
            if holder.state == STATE_ACTIVE:
                self.mds_addr = addr
                self._active_event.set()
            return
        self._active_event.clear()
        asyncio.ensure_future(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        """Re-establish the session against whatever daemon currently
        holds rank 0: replay cap claims (MClientReconnect), or on
        reject (session missed the window) re-mount from scratch with
        every handle invalidated. One loop at a time; each attempt
        re-reads the fsmap so back-to-back failovers re-aim it."""
        if self._reconnecting:
            return
        self._reconnecting = True
        try:
            for attempt in range(120):
                holder = self.fsmap.rank_holder(0) if self.fsmap \
                    else None
                if holder is None or \
                        holder.state not in _RECONNECTABLE:
                    await asyncio.sleep(0.1)
                    continue
                addr = holder.addr()
                caps = {}
                for path, hs in self._handles.items():
                    live = [h for h in hs if h.valid]
                    if not live:
                        continue
                    caps[path] = json.dumps({
                        "mode": max(h.mode for h in live),
                        "count": len(live),
                        "cseq": max(h.cap_seq for h in live),
                    }).encode()
                self._reconnect_fut = \
                    asyncio.get_event_loop().create_future()
                try:
                    await self.msgr.send_message(MClientReconnect(
                        op=RECONNECT_REQ, caps=caps), addr, "mds")
                    rep = await asyncio.wait_for(self._reconnect_fut,
                                                 timeout=5.0)
                except (ConnectionError, OSError, ConnectionError_,
                        asyncio.TimeoutError):
                    await asyncio.sleep(0.1)
                    continue
                self.mds_addr = addr
                if rep.op == RECONNECT_ACK:
                    log.dout(1, f"reconnected to mds at {addr} "
                                f"({len(caps)} caps replayed)")
                else:
                    # session unknown (missed the reconnect window):
                    # caps are dead — invalidate every handle (next
                    # I/O reacquires) and open a fresh session
                    log.dout(1, f"reconnect rejected by {addr}; "
                                f"re-mounting")
                    for hs in self._handles.values():
                        for h in hs:
                            h.valid = False
                    try:
                        await self._open_session()
                    except (ConnectionError, OSError,
                            ConnectionError_,
                            asyncio.TimeoutError):
                        await asyncio.sleep(0.1)
                        continue
                # wake request loops: they resend once per incarnation
                self._incarnation += 1
                self._active_event.set()
                return
            log.dout(0, "mds reconnect gave up after retries")
        finally:
            self._reconnecting = False

    async def _handle_revoke(self, msg) -> None:
        for h in self._handles.get(msg.path, []):
            h.valid = False         # future I/O must reacquire first
        while self._inflight.get(msg.path, 0) > 0:
            await asyncio.sleep(0.01)   # writers flush before the ack
        await msg.conn.send_message(MClientCaps(
            op=CAP_OP_ACK, path=msg.path, mode=msg.mode,
            cseq=msg.cseq))

    async def _send_caps(self, op: int, path: str, mode: int,
                         seq: int) -> None:
        await self.msgr.send_message(
            MClientCaps(op=op, path=path, mode=mode, cseq=seq),
            self.mds_addr, "mds")

    # -- requests ----------------------------------------------------------
    async def _request(self, op: str, path: str, path2: str = "",
                       flags: int = 0,
                       timeout: float = 40.0) -> MClientReply:
        self._tid += 1
        tid = self._tid
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        self._waiters[tid] = fut
        msg = MClientRequest(tid=tid, op=op, path=path, path2=path2,
                             flags=flags)
        deadline = loop.time() + timeout
        sent_inc = None
        try:
            while True:
                if fut.done():
                    reply = fut.result()
                    break
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise asyncio.TimeoutError
                if self._ha and not self._active_event.is_set():
                    # failover in progress: park until a successor is
                    # reachable, then fall through to the resend check
                    await asyncio.wait_for(self._active_event.wait(),
                                           timeout=remaining)
                    continue
                if sent_inc != self._incarnation:
                    # op replay: exactly one send per MDS incarnation —
                    # the successor's completed-request table dedups
                    # mutations that landed before the crash, and a
                    # live-but-slow MDS is never spammed with
                    # duplicates (a duplicate open would leak a cap
                    # refcount)
                    try:
                        await self.msgr.send_message(
                            msg, self.mds_addr, "mds")
                        sent_inc = self._incarnation
                    except (ConnectionError, OSError,
                            ConnectionError_):
                        if not self._ha:
                            raise
                        await asyncio.sleep(0.2)
                        continue
                try:
                    reply = await asyncio.wait_for(
                        asyncio.shield(fut),
                        timeout=min(1.0, max(remaining, 0.05)))
                    break
                except asyncio.TimeoutError:
                    continue
        finally:
            self._waiters.pop(tid, None)
        if reply.result < 0:
            raise FSError(int(reply.result),
                          reply.payload.decode(errors="replace"))
        return reply

    # -- namespace (ref: libcephfs.h) --------------------------------------
    async def mkdir(self, path: str) -> None:
        await self._request("mkdir", path)

    async def rmdir(self, path: str) -> None:
        await self._request("rmdir", path)

    async def ls(self, path: str = "/") -> list[str]:
        r = await self._request("readdir", path)
        return json.loads(r.payload)

    async def stat(self, path: str) -> dict:
        r = await self._request("stat", path)
        return json.loads(r.payload)

    async def unlink(self, path: str) -> None:
        await self._request("unlink", path)

    async def rename(self, src: str, dst: str) -> None:
        await self._request("rename", src, path2=dst)

    # -- files (cap-gated) -------------------------------------------------
    async def open_file(self, path: str, mode: str = "r") -> FileHandle:
        """'r' wants shared-read; 'w' wants exclusive-write (creating
        the file if absent). Blocks while conflicting caps are being
        revoked from other clients."""
        path = _norm(path)        # cap/revoke bookkeeping is keyed on
        want = CAP_FW if mode == "w" else CAP_FR   # the normalized path
        r = await self._request("open", path, flags=want)
        info = json.loads(r.payload)
        # the handle keeps the REQUESTED mode, not the granted one: a
        # reader whose client happens to hold FW must neither pass the
        # write check nor reacquire exclusivity after a revoke
        h = FileHandle(self, path, info["oid"], want,
                       int(r.cap_seq), int(info["size"]))
        self._handles.setdefault(h.path, []).append(h)
        return h

    async def read_file(self, path: str) -> bytes:
        h = await self.open_file(path, "r")
        try:
            return await h.read()
        finally:
            await h.close()

    async def write_file(self, path: str, data: bytes) -> int:
        h = await self.open_file(path, "w")
        try:
            return await h.write(data)
        finally:
            await h.close()
