"""FSMap: the mon-authoritative MDS cluster membership map.

ref: src/mds/FSMap.h + src/include/fs_types.h (MDSMap::DaemonState) —
the paxos-committed map the MDSMonitor maintains and every MDS/client
subscribes to ("mdsmap"). One filesystem, up to ``max_mds`` active
ranks (round 7): the namespace is partitioned across ranks by the
**subtree map** (directory subtree root -> owning rank, the persistent
analog of the reference's subtree/dirfrag auth delegation), and the
failover ladder runs PER RANK:

    standby -> (standby_replay) -> replay -> reconnect -> rejoin -> active

A daemon is keyed by its **gid** — a per-incarnation id, so a restarted
daemon with the same name is a NEW entity (the reference's mds_gid_t).
``ident`` is the RADOS entity name the incarnation's data-path ops use;
the blocklist fence at failover targets it, so fencing a dead
incarnation never collaterally fences its successor.

``last_failure_osd_epoch`` (ref: MDSMap::last_failure_osd_epoch) is the
osdmap epoch at which the last failed active's blocklist committed; a
promoted standby barriers on it (Objecter.wait_for_map_on_osds) before
touching the journal, so a fenced predecessor can never land a late
journal write after replay began.

Subtree map + migrations (round 7, v2 encoding): ``subtrees`` maps a
normalized subtree root to the rank that serves it; "/" is always
present and defaults to rank 0, and resolution is longest-prefix
(``subtree_owner``) so a deeper pin overrides its ancestors.
``migrations`` records in-flight two-phase subtree handoffs
({path, from, to}) — the authority flip itself is ONLY the paxos
commit that rewrites ``subtrees``, so a migration that dies at any
point simply never moved authority (crash-safe by construction).
"""

from __future__ import annotations

from ceph_tpu.encoding.denc import Decoder, Encoder

# daemon states (ref: MDSMap::DaemonState, the subset this ladder uses)
STATE_STANDBY = "standby"
STATE_STANDBY_REPLAY = "standby_replay"
STATE_REPLAY = "replay"
STATE_RECONNECT = "reconnect"
STATE_REJOIN = "rejoin"
STATE_ACTIVE = "active"
STATE_STOPPED = "stopped"

# the per-rank takeover ladder, in order; a beacon may only advance
# forward along it (ref: MDSMonitor::prepare_beacon state checks)
LADDER = (STATE_REPLAY, STATE_RECONNECT, STATE_REJOIN, STATE_ACTIVE)

# states that hold (or are taking over) a rank — beacon-grace expiry of
# one of these is a FAILOVER, not a standby drop
RANK_STATES = frozenset(LADDER)

# hard ceiling on max_mds (ref: MAX_MDS in the reference's mon checks)
MAX_MDS_CAP = 16


class MDSInfo:
    """One registered daemon incarnation (ref: MDSMap::mds_info_t)."""

    def __init__(self, gid: int, name: str, ident: str = "",
                 host: str = "", port: int = 0,
                 state: str = STATE_STANDBY, rank: int = -1):
        self.gid = gid
        self.name = name
        self.ident = ident          # RADOS entity the fence targets
        self.host = host
        self.port = port
        self.state = state
        self.rank = rank            # -1 = no rank (standby*)

    def addr(self):
        from ceph_tpu.msg import EntityAddr
        return EntityAddr(self.host, self.port)

    def dump(self) -> dict:
        return {"gid": self.gid, "name": self.name, "ident": self.ident,
                "addr": [self.host, self.port], "state": self.state,
                "rank": self.rank}


class FSMap:
    def __init__(self):
        self.epoch = 0
        self.infos: dict[int, MDSInfo] = {}      # gid -> info
        self.failed: list[int] = []              # failed ranks
        self.stopped_gids: list[int] = []        # tombstones (bounded):
        # a failed/removed incarnation may keep beaconing (zombie);
        # its gid must never re-register, or a fenced daemon could
        # climb back to a rank it can no longer write for
        self.last_failure_osd_epoch = 0
        # -- multi-active (v2) --------------------------------------------
        self.max_mds = 1                         # wanted active ranks
        self.subtrees: dict[str, int] = {"/": 0}  # subtree root -> rank
        # in-flight two-phase handoffs: [{"path": root, "from": rank,
        # "to": rank}]; authority flips only when the commit rewrites
        # ``subtrees`` — until then the "from" rank stays authoritative
        self.migrations: list[dict] = []
        # -- fs snapshots (v3, ref: SnapServer's snap table made
        # paxos-durable): snapid -> {"name", "path", "pool"}. The mon is
        # the snap server of record — realms an MDS journals are derived
        # from entries here, so a failover can always rebuild. snapids
        # come from the data pool's selfmanaged-snap allocator and are
        # never reused (pool snap_seq is monotonic).
        self.snaps: dict[int, dict] = {}

    # -- queries -----------------------------------------------------------
    def by_name(self, name: str) -> MDSInfo | None:
        return next((i for i in self.infos.values() if i.name == name),
                    None)

    def rank_holder(self, rank: int = 0) -> MDSInfo | None:
        """The daemon holding (or laddering toward) ``rank``."""
        return next((i for i in self.infos.values()
                     if i.rank == rank and i.state in RANK_STATES),
                    None)

    def active(self, rank: int = 0) -> MDSInfo | None:
        i = self.rank_holder(rank)
        return i if i is not None and i.state == STATE_ACTIVE else None

    def actives(self) -> dict[int, MDSInfo]:
        """rank -> active info for every rank currently serving."""
        return {i.rank: i for i in self.infos.values()
                if i.state == STATE_ACTIVE and i.rank >= 0}

    def rank_holders(self) -> dict[int, MDSInfo]:
        return {i.rank: i for i in self.infos.values()
                if i.state in RANK_STATES and i.rank >= 0}

    def standbys(self) -> list[MDSInfo]:
        return sorted((i for i in self.infos.values()
                       if i.state in (STATE_STANDBY,
                                      STATE_STANDBY_REPLAY)),
                      key=lambda i: (i.state != STATE_STANDBY_REPLAY,
                                     i.gid))

    def subtree_owner(self, path: str) -> tuple[int, str]:
        """(owning rank, matched subtree root) for ``path`` by
        longest-prefix match — the routing primitive clients and the
        per-rank ownership check share. ``path`` must be normalized
        ("/a/b"); "/" always matches."""
        best_root, best_rank = "/", self.subtrees.get("/", 0)
        for root, rank in self.subtrees.items():
            if root == "/":
                continue
            if (path == root or path.startswith(root + "/")) and \
                    len(root) > len(best_root):
                best_root, best_rank = root, rank
        return best_rank, best_root

    def migration_for(self, path: str) -> dict | None:
        return next((m for m in self.migrations
                     if m["path"] == path), None)

    def is_stopped(self, gid: int) -> bool:
        return gid in self.stopped_gids

    def tombstone(self, gid: int, keep: int = 64) -> None:
        self.stopped_gids.append(gid)
        del self.stopped_gids[:-keep]

    def dump(self) -> dict:
        holders = self.rank_holders()
        return {
            "epoch": self.epoch,
            "max_mds": self.max_mds,
            "ranks": [holders[r].dump() for r in sorted(holders)],
            "standbys": [i.dump() for i in self.standbys()],
            "failed": sorted(self.failed),
            "stopped_gids": list(self.stopped_gids),
            "last_failure_osd_epoch": self.last_failure_osd_epoch,
            "subtrees": dict(sorted(self.subtrees.items())),
            "migrations": [dict(m) for m in self.migrations],
            "snaps": {sid: dict(s)
                      for sid, s in sorted(self.snaps.items())},
            "states": {i.name: i.state for i in self.infos.values()},
        }

    def snaps_under(self, path: str) -> dict[int, dict]:
        """snapid -> entry for every snapshot whose realm root is
        ``path`` or an ancestor of it — the set whose snap context
        governs writes at ``path`` (ref: SnapRealm::get_snap_context
        walking parent realms)."""
        return {sid: s for sid, s in self.snaps.items()
                if path == s["path"] or
                path.startswith(s["path"].rstrip("/") + "/")}

    # -- codec -------------------------------------------------------------
    def encode(self) -> bytes:
        e = Encoder()
        with e.start(3):                 # v3: + snaps
            e.u64(self.epoch)
            e.map(self.infos, lambda e, k: e.u64(k),
                  lambda e, i: (e.u64(i.gid).string(i.name)
                                .string(i.ident).string(i.host)
                                .u32(i.port).string(i.state)
                                .s32(i.rank)))
            e.list(self.failed, lambda e, v: e.s32(v))
            e.list(self.stopped_gids, lambda e, v: e.u64(v))
            e.u64(self.last_failure_osd_epoch)
            e.u32(self.max_mds)                            # v2
            e.map(self.subtrees, lambda e, k: e.string(k),  # v2
                  lambda e, v: e.s32(v))
            e.list(self.migrations,                        # v2
                   lambda e, m: (e.string(m["path"])
                                 .s32(m["from"]).s32(m["to"])))
            e.map(self.snaps, lambda e, k: e.u64(k),       # v3
                  lambda e, s: (e.string(s["name"])
                                .string(s["path"])
                                .string(s["pool"])))
        return e.tobytes()

    @classmethod
    def decode(cls, data: bytes) -> "FSMap":
        def info(d: Decoder) -> MDSInfo:
            return MDSInfo(gid=d.u64(), name=d.string(),
                           ident=d.string(), host=d.string(),
                           port=d.u32(), state=d.string(),
                           rank=d.s32())
        m = cls()
        d = Decoder(data)
        with d.start(3) as v:
            m.epoch = d.u64()
            m.infos = d.map(lambda d: d.u64(), info)
            m.failed = d.list(lambda d: d.s32())
            m.stopped_gids = d.list(lambda d: d.u64())
            m.last_failure_osd_epoch = d.u64()
            if v >= 2:
                m.max_mds = d.u32()
                m.subtrees = d.map(lambda d: d.string(),
                                   lambda d: d.s32())
                m.migrations = d.list(
                    lambda d: {"path": d.string(), "from": d.s32(),
                               "to": d.s32()})
            if v >= 3:
                m.snaps = d.map(
                    lambda d: d.u64(),
                    lambda d: {"name": d.string(), "path": d.string(),
                               "pool": d.string()})
        m.subtrees.setdefault("/", 0)     # v1 blob / invariant repair
        return m
