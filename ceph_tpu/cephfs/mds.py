"""The MDS daemon: metadata authority + client capabilities.

ref: src/mds/ (MDSDaemon, Server::handle_client_request, Locker's cap
machinery, MDLog/EUpdate journaling) + src/messages/MClientRequest.h /
MClientReply.h / MClientCaps.h — rebuilt small on this framework's
messenger. The division of labor is the reference's:

- ALL namespace mutations flow through the MDS, which journals each
  one to a metadata-pool journal object before applying it to the
  dirfrag omap objects (the same on-disk model ``CephFSLite`` uses —
  an MDS restart replays uncommitted journal events idempotently, the
  EUpdate/MDLog pattern in miniature).
- File DATA I/O never touches the MDS: clients read/write the
  ``.file<path>`` RADOS objects directly — but only while holding a
  file capability granted by the MDS.

Capabilities (ref: Locker, simplified to the file caps that matter at
this scope): ``CAP_FR`` is shared-read, ``CAP_FW`` is exclusive-write.
A conflicting open triggers revoke messages to the current holders;
the grant is withheld until every holder acks (writers flush before
acking), which is exactly the reference's revoke/ack dance. Sessions
(ref: MClientSession) gate everything; closing a session drops its
caps and wakes any waiter blocked on them.

Cap leases (round 5): clients heartbeat SESSION_RENEW; a holder whose
lease lapses while a revoke is outstanding is EVICTED (session + caps
dropped, its revoke waiters resolved) so a dead client cannot hold
exclusivity hostage — the Session::last_cap_renew + stale-eviction
behavior in miniature.

High availability (round 6, ref: MDSMonitor + MDSMap): an MDS started
through :meth:`MDSDaemon.create` runs **mon-coordinated**: it owns a
per-incarnation RADOS identity (``mds.<name>.<gid>`` — the blocklist
fence at failover targets exactly this incarnation), beacons the
MDSMonitor every ``mds_beacon_interval``, and climbs the failover
ladder the FSMap assigns it:

    standby -> (standby_replay) -> replay -> reconnect -> rejoin -> active

Sessions live in a persistent **session table** (``.mds_sessions``
omap, ref: SessionMap) with each session's recently completed request
tids, so a promoted standby reconstructs who was mounted, accepts
MClientReconnect cap claims from those clients, and dedups replayed
mutations. Before touching the journal the new active barriers on
``last_failure_osd_epoch`` — the osdmap epoch of its predecessor's
blocklist — so a fenced zombie can never land a late journal write.

Multi-active (round 7, ref: the Migrator + MDBalancer + the subtree
map): up to ``max_mds`` ranks serve disjoint namespace subtrees. Each
rank owns a PER-RANK journal + session table (``journal_oid(rank)`` /
``sessions_oid(rank)``), requests for a subtree another rank owns are
redirected with -ESTALE (payload names the owner), and subtree
authority moves between live ranks through a two-phase migration:
freeze + drain -> journaled handoff marker -> caps/completed-table
export (the importer persists them BEFORE acking) -> mon-committed
subtree-map flip -> unfreeze/redirect. Authority only ever moves in
the mon's paxos commit, so a crash on either side leaves the subtree
where it was, and the transferred completed-request tables keep
mutation replay exactly-once across the handoff.

Not rebuilt: the full inode lock matrix, snapshots, cross-rank rename
(-EXDEV; route both paths to one rank).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import time

from ceph_tpu.cephfs import CephFSLite, FSError, _fileobj, _norm
from ceph_tpu.cephfs.fsmap import (
    FSMap, STATE_ACTIVE, STATE_RECONNECT, STATE_REJOIN, STATE_REPLAY,
    STATE_STANDBY, STATE_STANDBY_REPLAY, STATE_STOPPED,
)
from ceph_tpu.mon.messages import MDSBeacon, MMDSMap, MMDSMigrationDone
from ceph_tpu.msg import Dispatcher, Messenger
from ceph_tpu.msg.message import Message, register
from ceph_tpu.utils.locks import KeyedLocks
from ceph_tpu.utils.logging import get_logger
from ceph_tpu.utils.perf_counters import PerfCountersBuilder

log = get_logger("mds")

SESSION_OPEN = 1
SESSION_CLOSE = 2
SESSION_RENEW = 3   # client heartbeat keeping its cap lease alive
                    # (ref: CEPH_SESSION_REQUEST_RENEWCAPS)

CAP_FR = 1          # shared read
CAP_FW = 2          # exclusive write

CAP_OP_GRANT = 1    # mds -> client (unsolicited would go here; unused)
CAP_OP_REVOKE = 2   # mds -> client: stop using this cap, then ack
CAP_OP_ACK = 3      # client -> mds: revoke done (writers flushed)
CAP_OP_RELEASE = 4  # client -> mds: voluntary drop (file close)

RECONNECT_REQ = 1     # client -> mds: session + cap claims
RECONNECT_ACK = 2     # mds -> client: session restored, caps replayed
RECONNECT_REJECT = 3  # mds -> client: unknown session; re-mount

JOURNAL_OID = ".mds_journal"
SESSIONS_OID = ".mds_sessions"   # session table (ref: SessionMap)
REALMS_OID = ".mds_realms"       # snaprealm table (ref: SnapRealm
                                 # state in the mdlog, persisted flat)


def journal_oid(rank: int) -> str:
    """Per-rank journal object (rank 0 keeps the legacy name so every
    pre-multi-active store and test reads unchanged)."""
    return JOURNAL_OID if rank <= 0 else f"{JOURNAL_OID}.{rank}"


def sessions_oid(rank: int) -> str:
    return SESSIONS_OID if rank <= 0 else f"{SESSIONS_OID}.{rank}"


def realms_oid(rank: int) -> str:
    return REALMS_OID if rank <= 0 else f"{REALMS_OID}.{rank}"


SNAPDIR = ".snap"   # the magic snapshot directory component
EROFS = -30         # writes through .snap / under a snapshot path


def snap_split(path: str) -> tuple[str, str, str] | None:
    """Decompose a normalized path that traverses the magic ``.snap``
    directory (ref: the CEPH_SNAPDIR inode): returns
    ``(realm_root, snap_name, rest)`` or None for ordinary paths.

        /d/.snap           -> ("/d", "",   "")
        /d/.snap/s1        -> ("/d", "s1", "")
        /d/.snap/s1/a/f    -> ("/d", "s1", "a/f")
        /.snap/s1          -> ("/",  "s1", "")

    Only the FIRST .snap component is magic — a second one inside
    ``rest`` is simply a name that cannot exist (capture never records
    one)."""
    parts = path.split("/")
    if SNAPDIR not in parts:
        return None
    i = parts.index(SNAPDIR)
    root = "/".join(parts[:i]) or "/"
    name = parts[i + 1] if len(parts) > i + 1 else ""
    rest = "/".join(parts[i + 2:])
    return root, name, rest


# -ESTALE: the reply code a rank answers with for a path it does not
# own — payload carries {"rank": owner, "path": subtree_root} so the
# client re-targets without waiting for the next fsmap publish (ref:
# the CDIR_AUTH forward / MClientRequest forwarding upstream)
ESTALE = -116
EXDEV = -18      # cross-rank rename: not supported at this scope

# ops whose replay after failover must be deduplicated by (client, tid)
# — the completed-request table the reference keeps per Session
MUTATING_OPS = frozenset(
    ("mkdir", "rmdir", "create", "unlink", "rename", "setattr"))

# completed tids retained per session (bounds the table entry)
COMPLETED_KEEP = 64

# per-incarnation gid source: process-monotonic so a restarted daemon
# is a NEW entity the FSMap tombstones can never confuse with its
# predecessor (ref: mds_gid_t allocation in the mon, moved daemon-side
# since incarnations here are in-process objects)
_GID = itertools.count(1)

# process-wide MDS failover counters (exported via `perf dump` and the
# mgr prometheus module's generic ceph_perf rows)
MDS_PERF = (
    PerfCountersBuilder("mds")
    .add_u64_counter("beacons_sent", "MDSBeacons sent to the mon")
    .add_u64_counter("state_transitions", "failover-ladder rungs taken")
    .add_u64_counter("takeovers", "rank takeovers begun (replay)")
    .add_u64_counter("journal_replays", "journal replay passes")
    .add_u64_counter("reconnect_accepted",
                     "client sessions restored via MClientReconnect")
    .add_u64_counter("reconnect_rejected",
                     "reconnect claims refused (unknown session)")
    .add_u64_counter("sessions_dropped",
                     "recovering sessions that never reconnected")
    .add_u64_counter("caps_replayed", "caps reinstated from claims")
    .add_u64_counter("standby_replay_polls",
                     "standby-replay journal/session tail polls")
    .add_u64_counter("subtrees_exported",
                     "subtree handoffs completed as the exporter")
    .add_u64_counter("subtrees_imported",
                     "subtree handoffs completed as the importer")
    .add_u64_counter("redirects_sent",
                     "-ESTALE redirects to the owning rank")
    # the metadata op class of the per-op-class latency histograms
    # (read/write live on the OSD): microseconds, log2 buckets,
    # rendered as le-bucketed series by the prometheus module
    .add_histogram("req_latency_hist",
                   "client metadata op latency, microseconds "
                   "(log2 buckets)")
    .create_perf_counters()
)


@register
class MClientSession(Message):
    """ref: MClientSession (REQUEST_OPEN/REQUEST_CLOSE + ack)."""
    TYPE = 220
    FIELDS = [("op", "u32"), ("cseq", "u64")]


@register
class MClientRequest(Message):
    """ref: MClientRequest — one metadata op. ``op`` is the lowercase
    op name (mkdir/rmdir/readdir/stat/create/unlink/rename/open/
    setattr); path2 = rename target; flags = cap mode for open,
    size for setattr."""
    TYPE = 221
    FIELDS = [("tid", "u64"), ("op", "str"), ("path", "str"),
              ("path2", "str"), ("flags", "u64")]


@register
class MClientReply(Message):
    """ref: MClientReply. result <= 0 errno; payload = op-specific
    JSON; cap_mode/cap_seq set for open replies."""
    TYPE = 222
    FIELDS = [("tid", "u64"), ("result", "s64"), ("payload", "blob"),
              ("cap_mode", "u32"), ("cap_seq", "u64")]


@register
class MClientCaps(Message):
    """ref: MClientCaps — both directions (op disambiguates)."""
    TYPE = 223
    FIELDS = [("op", "u32"), ("path", "str"), ("mode", "u32"),
              ("cseq", "u64")]


@register
class MMDSExportDir(Message):
    """Exporting rank -> importing rank: the payload half of a subtree
    handoff (ref: MExportDir + the cap/session state MExportDirPrep
    carries). The subtree's NAMESPACE needs no copying — dirfrags are
    shared RADOS objects — so what moves is serving state: ``caps``
    maps path -> JSON {holders: {client: [mode, count]}} for every cap
    under the subtree, and ``completed`` maps client -> JSON
    {tid: result} (the completed-request tables), which the importer
    persists to ITS session table BEFORE acking — the durability step
    that keeps mutation replay exactly-once across the handoff."""
    TYPE = 225
    FIELDS = [("path", "str"), ("from_rank", "s32"),
              ("to_rank", "s32"), ("cap_seq", "u64"),
              ("caps", "map:str:blob"), ("completed", "map:str:blob"),
              # snaprealms rooted under the subtree (appended,
              # zero-fills on old corpus): str(snapid) -> realm JSON;
              # the importer persists them to ITS realm table before
              # acking, so .snap keeps serving after authority flips
              ("realms", "map:str:blob")]


@register
class MMDSExportDirAck(Message):
    """Importing rank -> exporting rank: state merged AND persisted;
    the exporter may report MMDSMigrationDone to the mon."""
    TYPE = 226
    FIELDS = [("path", "str"), ("result", "s32")]


@register
class MClientReconnect(Message):
    """ref: MClientReconnect — a client's session + cap claims to a
    newly promoted MDS during its reconnect window. ``caps`` maps
    path -> JSON {mode, count, cseq}; the ack restores the session
    with those caps reinstated, the reject means the session is
    unknown (missed the window / never in the table) and the client
    must re-mount from scratch."""
    TYPE = 224
    FIELDS = [("op", "u32"), ("caps", "map:str:blob")]


class MDSDaemon(Dispatcher):
    """Single-rank MDS over one metadata/data pool ioctx.

    Two modes: **standalone** (``MDSDaemon(ioctx)`` + ``start()`` —
    immediately active, no mon coordination; the pre-round-6 surface,
    still what the single-daemon tests drive) and **HA**
    (``MDSDaemon.create(...)`` + ``start_ha()`` — beacons the
    MDSMonitor and serves only once the FSMap promotes it)."""

    def __init__(self, ioctx, name: str = "a",
                 messenger: Messenger | None = None,
                 lease_timeout: float = 10.0,
                 revoke_timeout: float = 30.0,
                 config: dict | None = None,
                 keyring=None):
        cfg = config or {}
        self.fs = CephFSLite(ioctx)
        self.ioctx = ioctx
        self.name = name
        # the committed-caps table for the per-op request gate
        # (ROADMAP #3b). NOT the messenger's keyring: client MDS-
        # facing messengers are keyless, so the transport stays
        # keyless-CRC; in HA mode create()'s monc keyring wins.
        self.keyring = keyring
        self.msgr = messenger or Messenger(f"mds.{name}")
        self.msgr.add_dispatcher(self)
        self.sessions: dict[str, object] = {}       # client -> conn
        # cap leases (ref: Session::last_cap_renew + the Locker's
        # stale-session eviction): a client renews via SESSION_RENEW;
        # one whose lease lapses while sitting on an unacked revoke is
        # EVICTED (session + caps dropped) instead of stalling every
        # conflicting open to the revoke timeout.
        self.lease_timeout = lease_timeout
        self.revoke_timeout = revoke_timeout
        self._session_seen: dict[str, float] = {}   # client -> loop time
        # path -> {client: [mode, refcount]}; invariant: at most one
        # CAP_FW holder, never FW alongside another client's FR. A
        # same-client re-open bumps the refcount and can only upgrade
        # the mode (FW absorbs FR); releases drop the entry at zero.
        self.caps: dict[str, dict[str, list]] = {}
        self._cap_seq = 0
        # (path, client, seq) -> future resolved by the holder's ack
        self._revoke_waiters: dict[tuple, asyncio.Future] = {}
        # serializes the revoke+grant decision per path: without it two
        # concurrent conflicting opens both see the pre-revoke holder
        # table and both grant themselves exclusivity. User-counted so
        # entries drop when the last opener leaves (no per-path leak).
        self._open_locks = KeyedLocks()
        self._req_tasks: set[asyncio.Task] = set()
        self._stopping = False
        self._journal_seq = 0
        self.addr = None
        # journal residency (segments-of-one, batch-trimmed): a
        # successful event stays in the journal until the trim horizon
        # passes it, so a standby-replay follower has something real to
        # tail; failed events are removed immediately (an op the client
        # was told failed must never replay "successfully" later).
        # The APPLIED WATERMARK (the "applied" journal key) records
        # the contiguous prefix already applied: replay skips it —
        # re-applying an applied rename/unlink against LATER namespace
        # state is destructive (an old rename replayed after its path
        # was recreated overwrites acked data), so only the genuine
        # crash window (applied-but-unflushed, bounded by in-flight
        # concurrency, same as the pre-residency design) ever replays.
        self._resident_seqs: set[int] = set()
        self._pending_seqs: set[int] = set()
        self._applied_flushed = 0
        self._trimming = False
        self.journal_max = cfg.get("mds_journal_max_entries", 64)
        # session table mirror + per-session completed request tids
        # (ref: SessionMap + Session::completed_requests)
        self._session_table: set[str] = set()
        self._completed: dict[str, dict[int, int]] = {}
        # -- HA state -------------------------------------------------
        self.config = cfg
        self.gid = next(_GID)
        self.ident = f"mds.{name}.{self.gid}"   # RADOS entity; fence key
        self.state = STATE_ACTIVE               # standalone default
        self.monc = None                        # set by create()
        self._own_rados = None
        self.fsmap: FSMap | None = None
        # -- snaprealms (ref: SnapRealm + SnapServer client side) ------
        # sid -> {"name", "path", "tree"}: the point-in-time namespace
        # capture under the realm root. Journaled (mksnap/rmsnap
        # events) AND persisted flat in realms_oid so failover replay
        # and cold takeover both rebuild it; rides MMDSExportDir on
        # subtree migration.
        self.realms: dict[int, dict] = {}
        self.snap_enabled = cfg.get("mds_snap_enabled", True)
        self.snap_max = int(cfg.get("mds_snap_max_per_realm", 100))
        # -- multi-active state (round 7) ------------------------------
        self.rank = 0                           # standalone serves rank 0
        self.journal_oid = journal_oid(0)
        self.sessions_oid = sessions_oid(0)
        self.realms_oid = realms_oid(0)
        # cumulative op counters for the beacon's load report
        self._op_count = 0
        self._subtree_op_counts: dict[str, int] = {}
        # distributed tracing: metadata-op spans continue the client's
        # context; completed spans piggyback on the beacon
        from ceph_tpu.utils.tracing import Tracer
        self.tracer = Tracer(f"mds.{name}", cfg)
        # migration path -> Event set when the freeze lifts; requests
        # whose path falls UNDER a frozen path park on it (export in
        # progress). NB the frozen key is the MIGRATION path, which is
        # usually not yet a subtree-map root (first pin of /d1 while
        # the map holds only "/") — so matching is by prefix against
        # the request path, never via subtree_owner.
        self._frozen: dict[str, asyncio.Event] = {}
        # admitted request path -> in-flight count; the export drain
        # waits until nothing under the migrating path remains
        self._inflight_reqs: dict[str, int] = {}
        self._exports: set[str] = set()          # roots being exported
        self._export_acks: dict[str, asyncio.Future] = {}
        self._export_tasks: set[asyncio.Task] = set()
        self.migration_timeout = cfg.get("mds_migration_timeout", 10.0)
        self.beacon_interval = cfg.get("mds_beacon_interval", 1.0)
        self.reconnect_timeout = cfg.get("mds_reconnect_timeout", 2.0)
        self.replay_interval = cfg.get("mds_replay_interval", 0.25)
        self._beacon_seq = 0
        self._beacon_task: asyncio.Task | None = None
        self._mgr_reporter = None
        self._mgr_report_task: asyncio.Task | None = None
        self._tail_task: asyncio.Task | None = None
        self._takeover_task: asyncio.Task | None = None
        self._active_event = asyncio.Event()
        self._replay_done = asyncio.Event()
        self._recovering: set[str] = set()       # sessions awaiting
        self._killed = False                     # reconnect claims
        # central-config application state (round 18)
        self._mon_cfg_state: dict = {}
        self.mirror_global_config = False

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    async def create(cls, monmap, pool: str, name: str = "a",
                     keyring=None, config: dict | None = None,
                     gid: int | None = None) -> "MDSDaemon":
        """Build a mon-coordinated MDS with an OWN per-incarnation
        RADOS identity. The identity is what the MDSMonitor blocklists
        at failover — data-path ops through a shared admin ioctx would
        dodge the fence, exactly like the client-side reasoning in
        :meth:`CephFSClient.create`."""
        from ceph_tpu.rados import Rados
        cfg = config or {}
        self = cls.__new__(cls)
        # _GID is process-local: proc-backend children pass their pid
        # so separate-process MDSs can't collide on gid
        if gid is None:
            gid = next(_GID)
        ident = f"mds.{name}.{gid}"
        if keyring is not None and f"mds.{name}" not in keyring.keys:
            # no provisioned base entity to derive from (standalone
            # harnesses): mint a local key. When ``mds.<name>`` IS
            # provisioned, Keyring.get derives the incarnation key on
            # BOTH ends — adding a random one here would shadow the
            # derivation locally and fail auth against a remote mon.
            keyring.add(ident)
        r = Rados(monmap, name=ident, keyring=keyring)
        await r.connect()
        io = await r.open_ioctx(pool)
        # warm the data path BEFORE beaconing starts: the identity's
        # first op jit-compiles the placement pipeline, which on an
        # in-process cluster blocks the shared event loop for seconds
        # — long enough to blow every daemon's beacon grace at once
        from ceph_tpu.rados import ObjectOperationError
        try:
            await io.stat(".mds_warmup")
        except ObjectOperationError:
            pass
        MDSDaemon.__init__(
            self, io, name=name,
            lease_timeout=cfg.get("mds_session_timeout", 10.0),
            revoke_timeout=cfg.get("mds_revoke_timeout", 30.0),
            config=cfg)
        self.gid = gid
        self.ident = ident
        self._own_rados = r
        self.monc = r.monc
        self.state = STATE_STANDBY
        self.rank = -1                 # no rank until the FSMap assigns
        return self

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        """Standalone start: immediately active (no mon coordination)."""
        # root dirfrag first (idempotent): journal replay on a fresh
        # pool needs it, and every request would ENOENT without it
        await self.fs.mount()
        await self._load_realms()
        await self._replay_journal()
        await self._load_session_table()
        self.addr = await self.msgr.bind(host, port)
        self.state = STATE_ACTIVE
        self._active_event.set()
        self._replay_done.set()
        log.dout(1, f"mds up at {self.addr}")
        return self.addr

    def _apply_config_map(self, cfgmap: dict) -> None:
        """Apply a mon-published central config map (round 18)."""
        from ceph_tpu.utils.config import apply_mon_config
        changed = apply_mon_config(
            f"mds.{self.name}", cfgmap, self.config,
            self._mon_cfg_state,
            mirror_global=self.mirror_global_config)
        if changed:
            log.dout(10, f"mds.{self.name} applied mon config "
                         f"{sorted(changed)}")

    async def start_ha(self, host: str = "127.0.0.1", port: int = 0):
        """Mon-coordinated start: bind, subscribe to the mdsmap, and
        beacon as a standby; all serving waits for the FSMap to
        promote this gid (ref: MDSDaemon::init + Beacon::init)."""
        self.addr = await self.msgr.bind(host, port)
        self.state = STATE_STANDBY
        # MMDSMap publishes arrive on the MonClient's messenger
        self.monc.msgr.add_dispatcher(self)
        await self.monc.subscribe("mdsmap", 0)
        # mgr report session (round 12, ref: MgrClient): mgrmap finds
        # the active mgr; the shared "mds" logger ships under THIS
        # daemon's name (the in-process daemons share one logger —
        # documented delta; a real multi-process MDS would own it)
        await self.monc.subscribe("mgrmap", 0)
        # central config db (round 18): wire-delivered live knob flips
        self.monc.config_callbacks.append(self._apply_config_map)
        await self.monc.subscribe("config", 0)
        from ceph_tpu.mgr.client import MgrReporter
        self._mgr_reporter = MgrReporter(
            f"mds.{self.name}", self.monc.msgr,
            lambda: self.monc.mgrmap, lambda: [MDS_PERF],
            self.config)
        # crash capture (round 14): the long-lived loops carry the
        # top-level exception hook — a dead beacon loop is a dead
        # daemon in disguise, and the report says so
        from ceph_tpu.utils import crash as _crash
        self._mgr_report_task = _crash.watch(
            asyncio.ensure_future(self._mgr_reporter.loop()),
            f"mds.{self.name}", self.monc, where="mgr_report_loop")
        self._beacon_task = _crash.watch(
            asyncio.ensure_future(self._beacon_loop()),
            f"mds.{self.name}", self.monc, where="beacon_loop")
        log.dout(1, f"mds.{self.name} (gid {self.gid}) standby at "
                    f"{self.addr}")
        return self.addr

    async def stop(self) -> None:
        # cancel detached request handlers FIRST: a handler parked in
        # the 30 s revoke wait must not outlive the daemon and mutate
        # caps / append journal events a later MDS would replay. The
        # stopping flag stops ms_dispatch spawning NEW tasks while the
        # gather below yields to the loop; the while drains any that
        # slipped in before the flag was observed.
        self._stopping = True
        for t in (self._beacon_task, self._tail_task,
                  self._takeover_task, self._mgr_report_task,
                  *self._export_tasks):
            if t is not None:
                t.cancel()
        while self._req_tasks:
            tasks = list(self._req_tasks)
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
        await self.msgr.shutdown()
        if self._own_rados is not None:
            await self._own_rados.shutdown()
            self._own_rados = None

    async def kill(self) -> None:
        """``kill -9`` analog for storms: drop everything on the floor
        — no beacons, no session teardown, no rados shutdown (the
        zombie keeps its identity so fencing is observable: its late
        writes must bounce off the blocklist)."""
        self._killed = True
        self._stopping = True
        for t in (self._beacon_task, self._tail_task,
                  self._takeover_task, self._mgr_report_task,
                  *self._export_tasks):
            if t is not None:
                t.cancel()
        for t in list(self._req_tasks):
            t.cancel()
        await self.msgr.shutdown()

    # -- beacons + fsmap (HA) ---------------------------------------------
    async def _beacon_loop(self) -> None:
        try:
            while not self._stopping and self.state != STATE_STOPPED:
                await self._send_beacon()
                await asyncio.sleep(self.beacon_interval)
        except asyncio.CancelledError:
            pass

    async def _send_beacon(self) -> None:
        if self.monc is None or self.state == STATE_STOPPED:
            return
        self._beacon_seq += 1
        # completed trace spans piggyback on the beacon (the MDS's
        # only periodic monward report)
        spans = self.tracer.drain_ship()
        try:
            await self.monc.send_report(MDSBeacon(
                gid=self.gid, name=self.name, ident=self.ident,
                addr_host=self.addr.host, addr_port=self.addr.port,
                state=self.state, seq=self._beacon_seq,
                epoch=self.fsmap.epoch if self.fsmap else 0,
                ops=self._op_count,
                subtree_ops=dict(self._subtree_op_counts),
                trace_spans=spans))
            MDS_PERF.inc("beacons_sent")
        except Exception as e:
            log.dout(5, f"beacon send failed: {e!r}")

    def _handle_fsmap(self, fm: FSMap) -> None:
        if self.fsmap is not None and fm.epoch <= self.fsmap.epoch:
            return
        self.fsmap = fm
        self._check_migrations()
        me = fm.infos.get(self.gid)
        if me is None:
            if fm.is_stopped(self.gid) and \
                    self.state != STATE_STOPPED:
                # removed/fenced: stop serving. The reference respawns;
                # here the cluster harness revives with a fresh
                # incarnation (new gid, new identity).
                log.dout(1, f"mds.{self.name} (gid {self.gid}) "
                            f"removed from fsmap; stopping service")
                self.state = STATE_STOPPED
                self._active_event.clear()
            return
        if me.state == STATE_STANDBY_REPLAY and \
                self.state == STATE_STANDBY:
            self.state = STATE_STANDBY_REPLAY
            MDS_PERF.inc("state_transitions")
            self._tail_task = asyncio.ensure_future(
                self._standby_replay_loop())
            log.dout(1, f"mds.{self.name} -> standby_replay")
        elif me.state == STATE_REPLAY and self.state in (
                STATE_STANDBY, STATE_STANDBY_REPLAY):
            if self._tail_task is not None:
                self._tail_task.cancel()
                self._tail_task = None
            self.state = STATE_REPLAY
            # the rank this incarnation now serves: journal + session
            # table are PER RANK (rank 0 keeps the legacy object names)
            self.rank = me.rank
            self.journal_oid = journal_oid(self.rank)
            self.sessions_oid = sessions_oid(self.rank)
            self.realms_oid = realms_oid(self.rank)
            MDS_PERF.inc("state_transitions")
            MDS_PERF.inc("takeovers")
            self._takeover_task = asyncio.ensure_future(
                self._takeover())

    async def _takeover(self) -> None:
        """replay -> reconnect -> rejoin -> active (ref: the
        MDSDaemon rank-start sequence MDSRank::replay_start ..
        active_start)."""
        try:
            # FENCE BARRIER first (ref: MDSMap::last_failure_osd_epoch
            # + MDSRank waiting on the objecter's map): the journal
            # must not be replayed while any OSD could still accept
            # the fenced predecessor's writes.
            epoch = self.fsmap.last_failure_osd_epoch \
                if self.fsmap else 0
            objecter = getattr(self.ioctx.rados, "objecter", None)
            while epoch and objecter is not None and \
                    not self._stopping:
                try:
                    await objecter.wait_for_map_on_osds(
                        epoch, timeout=10.0)
                    break
                except Exception as e:
                    log.dout(0, f"takeover fence barrier (epoch "
                                f"{epoch}) not proven: {e}; retrying")
                    await asyncio.sleep(0.2)
            await self.fs.mount()
            await self._load_realms()     # before replay: a replayed
            await self._replay_journal()  # mksnap re-persists on top
            await self._load_session_table()
            self._recovering = set(self._session_table)
            self._replay_done.set()
            await self._advance(STATE_RECONNECT)
            # reconnect window (ref: MDSRank::reconnect_start): bounded
            # wait for every session in the table to re-claim its caps
            loop = asyncio.get_event_loop()
            deadline = loop.time() + self.reconnect_timeout
            while self._recovering and loop.time() < deadline:
                await asyncio.sleep(0.02)
            for client in sorted(self._recovering):
                if client not in self._recovering:
                    # a parked reconnect task landed while an earlier
                    # straggler was being dropped (the await below
                    # yields): that session was just restored + ACKed
                    # — forgetting it now would silently destroy it
                    continue
                # missed the window: session + caps die (the client
                # must re-mount); ref: MDSRank kills unreconnected
                # sessions at reconnect_done
                log.dout(1, f"session {client} never reconnected; "
                            f"dropping")
                MDS_PERF.inc("sessions_dropped")
                await self._forget_session(client)
            self._recovering.clear()
            await self._advance(STATE_REJOIN)
            # rejoin: cap/lock state was rebuilt from the reconnect
            # claims themselves; nothing further to recover at this
            # scope (no distributed subtrees)
            await self._advance(STATE_ACTIVE)
            self._active_event.set()
            # an in-flight migration FROM this rank (committed against
            # the predecessor, aborted only if the mon noticed the
            # death) restarts here with the replayed state
            self._check_migrations()
            log.dout(1, f"mds.{self.name} active (takeover complete, "
                        f"rank {self.rank}, {len(self.sessions)} "
                        f"sessions)")
        except asyncio.CancelledError:
            pass
        except Exception as e:
            log.dout(0, f"mds takeover failed: {e!r}")

    async def _advance(self, state: str) -> None:
        self.state = state
        MDS_PERF.inc("state_transitions")
        await self._send_beacon()     # don't wait a beacon interval

    async def _standby_replay_loop(self) -> None:
        """Warm follower (ref: standby-replay tailing the active's
        MDLog). The namespace itself lives in the RADOS dirfrags, so
        the real warm state is the journal position and the session
        table — tailed here so a takeover starts its replay and its
        reconnect window without cold reads. Entries are NEVER applied
        from this loop: applying against the shared dirfrag objects
        would race the live active."""
        from ceph_tpu.rados import ObjectOperationError
        try:
            while not self._stopping and \
                    self.state == STATE_STANDBY_REPLAY:
                MDS_PERF.inc("standby_replay_polls")
                try:
                    entries = await self.ioctx.get_omap_vals(
                        self.journal_oid)
                    seqs = [int(k) for k in entries if k.isdigit()]
                    if seqs:
                        self._journal_seq = max(self._journal_seq,
                                                max(seqs))
                except ObjectOperationError:
                    pass                      # nothing journaled yet
                try:
                    table = await self.ioctx.get_omap_vals(
                        self.sessions_oid)
                    self._ingest_session_table(table)
                except ObjectOperationError:
                    pass                      # no sessions yet
                await asyncio.sleep(self.replay_interval)
        except asyncio.CancelledError:
            pass

    # -- subtree migration (round 7; ref: src/mds/Migrator.{h,cc},
    # two-phase: freeze -> journaled handoff -> import -> mon flip) -------
    def _check_migrations(self) -> None:
        """Spawn an export task for every in-flight migration whose
        FROM rank is ours (idempotent — one task per subtree root)."""
        fm = self.fsmap
        if fm is None or self._stopping or self.state != STATE_ACTIVE:
            return
        for mig in fm.migrations:
            if mig["from"] == self.rank and \
                    mig["path"] not in self._exports:
                t = asyncio.ensure_future(
                    self._export_subtree(dict(mig)))
                self._export_tasks.add(t)
                t.add_done_callback(self._export_tasks.discard)

    async def _export_subtree(self, mig: dict) -> None:
        """Run the exporter's half of the two-phase handoff:

        1. FREEZE the subtree (new requests under it park) and drain
           the in-flight ones, so the journal + cap table are a
           consistent snapshot;
        2. journal the handoff marker (crash here: nothing moved —
           the mon's intent entry survives and a successor retries);
        3. ship caps + completed-request tables to the importer and
           wait for its ack (the importer PERSISTS the tables before
           acking — the exactly-once handoff durability);
        4. report MMDSMigrationDone until the mon's commit flips the
           subtree map (authority moves exactly here);
        5. unfreeze: parked requests wake, re-check ownership, and
           redirect to the new owner.

        Aborts (mon dropped the intent, e.g. the importer died) just
        unfreeze — authority never moved."""
        path, to = mig["path"], mig["to"]
        if path in self._exports:
            return
        self._exports.add(path)
        ev = self._frozen.setdefault(path, asyncio.Event())
        ev.clear()
        loop = asyncio.get_event_loop()
        # the handoff is traceable like any op: the exporter's root
        # span context rides MMDSExportDir so the importer's merge
        # shows up as a child in the reassembled trace
        span = self.tracer.start_root(
            "subtree_export",
            tags={"path": path, "from_rank": self.rank, "to_rank": to})
        try:
            while self._inflight_under(path):
                if self._stopping or self.fsmap is None or \
                        self.fsmap.migration_for(path) is None:
                    return
                await asyncio.sleep(0.01)
            await self._journaled_apply(
                {"op": "export_subtree", "path": path, "to": to})
            caps = {
                p: json.dumps({"holders": {
                    c: [mode, cnt]
                    for c, (mode, cnt) in holders.items()}}).encode()
                for p, holders in self.caps.items()
                if p == path or p.startswith(path + "/")}
            completed = {
                c: json.dumps({str(t): r
                               for t, r in tids.items()}).encode()
                for c, tids in self._completed.items()}
            # snaprealms rooted in the subtree move with it — the
            # importer is the one serving .snap lookups afterwards
            realms = {
                str(sid): json.dumps(r).encode()
                for sid, r in self.realms.items()
                if r["path"] == path or
                r["path"].startswith(path.rstrip("/") + "/")}
            acked = False
            while not acked and not self._stopping:
                fm = self.fsmap
                if fm is None or fm.migration_for(path) is None:
                    return                  # aborted: finally unfreezes
                dest = fm.rank_holder(to)
                if dest is None or dest.state != STATE_ACTIVE:
                    await asyncio.sleep(0.05)
                    continue
                fut = loop.create_future()
                self._export_acks[path] = fut
                try:
                    export_msg = MMDSExportDir(
                        path=path, from_rank=self.rank, to_rank=to,
                        cap_seq=self._cap_seq, caps=caps,
                        completed=completed, realms=realms)
                    export_msg.set_trace(span)
                    await self.msgr.send_message(
                        export_msg, dest.addr(), "mds")
                    rep = await asyncio.wait_for(fut, timeout=2.0)
                    acked = rep.result == 0
                except Exception:
                    await asyncio.sleep(0.1)
                finally:
                    self._export_acks.pop(path, None)
            if not acked:
                return
            while not self._stopping:
                fm = self.fsmap
                if fm is None or fm.subtrees.get(path) == to:
                    break
                if fm.migration_for(path) is None:
                    return                  # aborted after the ack
                try:
                    await self.monc.send_report(MMDSMigrationDone(
                        gid=self.gid, path=path, from_rank=self.rank,
                        to_rank=to))
                except Exception as e:
                    log.dout(5, f"migration-done send failed: {e!r}")
                await asyncio.sleep(0.1)
            # flipped: the importer is authoritative — drop the
            # transferred caps so a stale holder entry here can never
            # feed a grant/revoke decision again
            for p in list(self.caps):
                if p == path or p.startswith(path + "/"):
                    self.caps.pop(p, None)
            for sid in [int(s) for s in realms]:
                self.realms.pop(sid, None)
                try:
                    await self.ioctx.rm_omap_key(self.realms_oid,
                                                 f"{sid:016d}")
                except Exception:
                    pass     # stale copy is routing-shadowed anyway
            MDS_PERF.inc("subtrees_exported")
            log.dout(1, f"mds.{self.name} (rank {self.rank}) exported "
                        f"subtree {path} -> rank {to}")
        except asyncio.CancelledError:
            pass
        finally:
            if span is not None:
                span.finish()
            self._exports.discard(path)
            done_ev = self._frozen.pop(path, None)
            if done_ev is not None:
                done_ev.set()

    async def _handle_import(self, m: MMDSExportDir) -> None:
        """The importer's half: journal the marker, merge caps, and
        PERSIST the merged completed-request tables before acking —
        a client's post-migration resend of a mutation that already
        landed on the exporter must answer from the table, not
        re-execute (the exactly-once guarantee's durable half)."""
        if not self._active_event.is_set():
            await self._active_event.wait()
        span = self.tracer.from_msg(
            "subtree_import", m, tags={"path": m.path,
                                       "rank": self.rank})
        await self._journaled_apply(
            {"op": "import_subtree", "path": m.path,
             "from": m.from_rank})
        for p, blob in m.caps.items():
            try:
                ent = json.loads(blob)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            holders = self.caps.setdefault(p, {})
            for client, mode_cnt in ent.get("holders", {}).items():
                cur = holders.setdefault(client, [0, 0])
                cur[0] = max(cur[0], int(mode_cnt[0]))
                cur[1] = max(cur[1], int(mode_cnt[1]))
        self._cap_seq = max(self._cap_seq, m.cap_seq)
        for client, blob in m.completed.items():
            try:
                tids = json.loads(blob)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            done = self._completed.setdefault(client, {})
            for t, r in tids.items():
                done.setdefault(int(t), int(r))
            while len(done) > COMPLETED_KEEP:
                done.pop(next(iter(done)))
            await self._save_session(client)
        for s, blob in getattr(m, "realms", {}).items():
            try:
                realm = json.loads(blob)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            sid = int(s)
            self.realms[sid] = realm
            await self._save_realm(sid)     # durable BEFORE the ack
        MDS_PERF.inc("subtrees_imported")
        if span is not None:
            span.finish()
        log.dout(1, f"mds.{self.name} (rank {self.rank}) imported "
                    f"subtree {m.path} from rank {m.from_rank}")
        await m.conn.send_message(MMDSExportDirAck(
            path=m.path, result=0))

    @staticmethod
    def _depth1(path: str) -> str:
        """Load-tracking prefix for a path owned via "/": its depth-1
        component ("/a/b/c" -> "/a") — the granularity at which the
        rebalancer can carve load off the root subtree."""
        if path == "/":
            return "/"
        return "/" + path.split("/", 2)[1]

    def _frozen_event(self, *paths: str) -> asyncio.Event | None:
        """The freeze Event covering any of ``paths``, or None.
        Matching is frozen-path-prefix against the request path — the
        frozen key (a migration path) need not be a subtree-map root
        yet."""
        for froot, ev in self._frozen.items():
            if ev.is_set():
                continue
            for p in paths:
                if p and (p == froot or p.startswith(froot + "/")):
                    return ev
        return None

    def _inflight_under(self, path: str) -> bool:
        return any(p == path or p.startswith(path + "/")
                   for p in self._inflight_reqs)

    async def _route_or_park(self, m: MClientRequest
                             ) -> MClientReply | None:
        """Ownership gate (multi-active): park while the path sits
        under a frozen migration (export in flight), then redirect
        with -ESTALE when this rank is not the owner. Returns the
        reply to send, or None to serve locally — in which case the op
        has been counted and its path(s) registered in-flight
        (``m._admitted``; caller decrements when done). No await sits
        between the freeze check and the registration, so the export
        drain can never miss an admitted op."""
        while True:
            fm = self.fsmap
            owner, root = fm.subtree_owner(m.path)
            ev = self._frozen_event(m.path, m.path2)
            if ev is not None:
                await ev.wait()
                continue             # ownership may have just flipped
            if m.op == "rename" and m.path2:
                owner2, _ = fm.subtree_owner(m.path2)
                if owner2 != owner:
                    return MClientReply(
                        tid=m.tid, result=EXDEV,
                        payload=(f"cross-rank rename not supported: "
                                 f"{m.path} is served by rank {owner},"
                                 f" {m.path2} by rank {owner2}; pin "
                                 f"both under one rank").encode(),
                        cap_mode=0, cap_seq=0)
            if owner != self.rank:
                MDS_PERF.inc("redirects_sent")
                return MClientReply(
                    tid=m.tid, result=ESTALE,
                    payload=json.dumps(
                        {"rank": owner, "path": root}).encode(),
                    cap_mode=0, cap_seq=0)
            self._op_count += 1
            key = root if root != "/" else self._depth1(m.path)
            counts = self._subtree_op_counts
            if key not in counts and len(counts) >= 64:
                # bound the beacon payload: drop the coldest prefix
                counts.pop(min(counts, key=counts.get))
            counts[key] = counts.get(key, 0) + 1
            # path2 rides along for renames: a rename INTO a freezing
            # subtree must neither slip past the park nor be missed by
            # the export drain
            m._admitted = [m.path] + \
                ([m.path2] if m.op == "rename" and m.path2 else [])
            for p in m._admitted:
                self._inflight_reqs[p] = \
                    self._inflight_reqs.get(p, 0) + 1
            return None

    def _inflight_done(self, paths: list) -> None:
        for p in paths:
            n = self._inflight_reqs.get(p, 0) - 1
            if n <= 0:
                self._inflight_reqs.pop(p, None)
            else:
                self._inflight_reqs[p] = n

    # -- journaling (ref: MDLog + EUpdate, batch-trimmed segments) ---------
    async def _journal(self, event: dict) -> int:
        """Append-then-apply: the event lands durably in the journal
        omap before the dirfrag mutation happens. Successful events
        stay resident until the trim horizon passes (replay is
        idempotent and order-converging); failed events are removed
        immediately."""
        self._journal_seq += 1
        seq = self._journal_seq
        await self.ioctx.set_omap(self.journal_oid, f"{seq:016d}",
                                  json.dumps(event).encode())
        self._pending_seqs.add(seq)
        self._resident_seqs.add(seq)
        return seq

    async def _commit(self, seq: int) -> None:
        self._pending_seqs.discard(seq)
        self._resident_seqs.discard(seq)
        await self.ioctx.rm_omap_key(self.journal_oid, f"{seq:016d}")

    async def _journaled_apply(self, ev: dict) -> None:
        """journal -> apply -> (lazy) trim. The entry is removed at
        once on FAILURE: an op the client was told failed must not
        linger and replay 'successfully' after conditions change (only
        a crash between append and apply leaves an unapplied entry)."""
        seq = await self._journal(ev)
        try:
            await self._apply(ev)
        except BaseException:
            await self._commit(seq)
            raise
        self._pending_seqs.discard(seq)
        await self._flush_applied()
        await self._maybe_trim()

    def _applied_horizon(self) -> int:
        """Largest seq with every seq <= it applied (pending = the
        journaled-not-yet-applied set)."""
        return (min(self._pending_seqs) - 1 if self._pending_seqs
                else self._journal_seq)

    async def _flush_applied(self) -> None:
        """Persist the contiguous applied watermark. Monotonic guard:
        flushes initiate in increasing order on one loop + one
        connection, so the stored value never regresses."""
        horizon = self._applied_horizon()
        if horizon <= self._applied_flushed:
            return
        self._applied_flushed = horizon
        # plain (non-underscore) key: the OSD's omap GET hides
        # "_"-prefixed keys as store-internal; the digit-only filters
        # in replay/tail skip this one
        await self.ioctx.set_omap(self.journal_oid, "applied",
                                  str(horizon).encode())

    async def _maybe_trim(self) -> None:
        """Trim applied journal entries once residency exceeds
        ``mds_journal_max_entries`` (ref: MDLog segment trimming).
        Horizon = just below the oldest still-pending event, so a
        crash can only ever leave a replayable suffix."""
        if self._trimming or \
                len(self._resident_seqs) <= self.journal_max:
            return
        self._trimming = True
        try:
            horizon = self._applied_horizon()
            for seq in sorted(s for s in self._resident_seqs
                              if s <= horizon):
                await self.ioctx.rm_omap_key(self.journal_oid,
                                             f"{seq:016d}")
                self._resident_seqs.discard(seq)
        finally:
            self._trimming = False

    async def _replay_journal(self) -> None:
        from ceph_tpu.rados import ObjectOperationError
        MDS_PERF.inc("journal_replays")
        try:
            entries = await self.ioctx.get_omap_vals(self.journal_oid)
        except ObjectOperationError:
            return
        # entries at or below the applied watermark already landed:
        # re-applying them against the LATEST namespace (instead of
        # the state they were appended over) is not idempotent —
        # an old rename/unlink would clobber later acked writes
        applied = int(entries.get("applied", b"0") or 0)
        for k in sorted(k for k in entries if k.isdigit()):
            seq = int(k)
            if seq > applied:
                ev = json.loads(entries[k])
                log.dout(4, f"mds journal replay: {ev}")
                try:
                    await self._apply(ev)
                except FSError as e:
                    # idempotent within the crash window: EEXIST /
                    # ENOENT mean the mutation already landed
                    log.dout(5, f"replay skip ({e.errno}): {ev}")
            await self.ioctx.rm_omap_key(self.journal_oid, k)
            self._journal_seq = max(self._journal_seq, seq)
        if "applied" in entries:
            await self.ioctx.rm_omap_key(self.journal_oid, "applied")
        self._applied_flushed = 0
        self._resident_seqs.clear()
        self._pending_seqs.clear()

    async def _apply(self, ev: dict) -> None:
        op = ev["op"]
        if op == "mkdir":
            await self.fs.mkdir(ev["path"])
        elif op == "rmdir":
            await self.fs.rmdir(ev["path"])
        elif op == "create":
            # must stay idempotent AND non-destructive: a stale create
            # replayed after the file gained data must not truncate it
            try:
                await self.fs.stat(ev["path"])
            except FSError:
                await self.fs.write_file(ev["path"], b"")
        elif op == "unlink":
            await self.fs.unlink(ev["path"])
        elif op == "rename":
            await self.fs.rename(ev["path"], ev["path2"])
        elif op == "setattr":
            await self.fs.set_size(ev["path"], ev["size"])
        elif op == "mksnap":
            sid = int(ev["sid"])
            self.realms[sid] = {"name": ev["name"], "path": ev["path"],
                                "tree": ev["tree"]}
            await self._save_realm(sid)
        elif op == "rmsnap":
            sid = int(ev["sid"])
            self.realms.pop(sid, None)
            try:
                await self.ioctx.rm_omap_key(self.realms_oid,
                                             f"{sid:016d}")
            except Exception:      # already gone: replay-idempotent
                pass
        elif op in ("export_subtree", "import_subtree"):
            # handoff markers: authority lives in the mon's subtree
            # map, not the journal — replay has nothing to do (the
            # marker's value is the watermark ordering around it)
            pass
        else:                                        # pragma: no cover
            raise ValueError(f"unknown journal op {op}")

    # -- snaprealm table (ref: SnapRealm persistence — flat per-rank
    # omap, the same durability model as the session table) ---------------
    async def _save_realm(self, sid: int) -> None:
        await self.ioctx.set_omap(
            self.realms_oid, f"{sid:016d}",
            json.dumps(self.realms[sid]).encode())

    async def _load_realms(self) -> None:
        from ceph_tpu.rados import ObjectOperationError
        try:
            omap = await self.ioctx.get_omap_vals(self.realms_oid)
        except ObjectOperationError:
            omap = {}
        self.realms = {int(k): json.loads(v)
                       for k, v in omap.items() if k.isdigit()}

    def _snaps_governing(self, path: str) -> list[int]:
        """Ascending snapids whose realm root is ``path`` or an
        ancestor — the snap context a write at ``path`` must carry
        (ref: SnapRealm::get_snap_context walking parent realms). The
        union of this rank's realm table and the FSMap's registry: the
        FSMap half makes the context correct even for realms whose
        tree lives on another rank."""
        out = {sid for sid, r in self.realms.items()
               if path == r["path"] or
               path.startswith(r["path"].rstrip("/") + "/")}
        if self.fsmap is not None:
            out |= set(self.fsmap.snaps_under(path))
        return sorted(out)

    def _realm(self, root: str, name: str) -> tuple[int, dict]:
        entry = next(((sid, r) for sid, r in self.realms.items()
                      if r["path"] == root and r["name"] == name), None)
        if entry is None:
            raise FSError(-2, f"no snapshot {name!r} at {root}")
        return entry

    async def _capture_tree(self, root: str) -> dict:
        """Point-in-time namespace capture under ``root``: relative
        path ("" = the root itself) -> {type, size[, oid]}. Data is NOT
        copied — a file entry records the head object's name, and
        point-in-time reads go through the OSD snap machinery
        (snap_id resolves to the COW clone)."""
        tree: dict[str, dict] = {"": {"type": "dir"}}
        stack = [""]
        base = root.rstrip("/")
        while stack:
            rel = stack.pop()
            absd = (base + "/" + rel) if rel else (root or "/")
            for nm in await self.fs.ls(absd):
                chrel = f"{rel}/{nm}" if rel else nm
                chabs = base + "/" + chrel
                try:
                    st = await self.fs.stat(chabs)
                except FSError:
                    continue            # raced an unlink: skip
                ent: dict = {"type": st["type"],
                             "size": st.get("size", 0)}
                if st["type"] == "file":
                    ent["oid"] = _fileobj(chabs)
                else:
                    stack.append(chrel)
                tree[chrel] = ent
        return tree

    @staticmethod
    def _tree_children(tree: dict, rest: str) -> list[str]:
        ent = tree.get(rest)
        if ent is None:
            raise FSError(-2, f"no such entry {rest!r} in snapshot")
        if ent["type"] != "dir":
            raise FSError(-20, f"{rest!r} is not a directory")
        pre = rest + "/" if rest else ""
        return sorted(k[len(pre):] for k in tree
                      if k and k != rest and k.startswith(pre)
                      and "/" not in k[len(pre):])

    async def _recall_realm_caps(self, root: str) -> None:
        """Revoke every cap under the realm root so writers flush and
        their next open carries the grown snap context (ref: the
        snaprealm split/update cap recall in Locker) — without this a
        holder would keep writing with the pre-snapshot context and
        the OSD would never COW, silently dirtying the snapshot."""
        base = root.rstrip("/")
        for path in [p for p in list(self.caps)
                     if p == root or p.startswith(base + "/")]:
            # the sentinel requester matches no real client, so EVERY
            # holder (including the mksnap caller itself) is revoked
            await self._revoke_conflicting(path, "\0mksnap", CAP_FW)

    async def _mksnap(self, root: str, name: str) -> int:
        """mkdir <root>/.snap/<name> (ref: Server::handle_client_
        mksnap): allocate the snapid at the mon, recall write caps,
        capture the namespace, journal the realm."""
        if not self.snap_enabled:
            raise FSError(-1, "EPERM: snapshots disabled "
                              "(mds_snap_enabled=false)")
        if not name or name.startswith("_") or name == SNAPDIR:
            raise FSError(-22, f"invalid snapshot name {name!r}")
        st = await self.fs.stat(root)
        if st["type"] != "dir":
            raise FSError(-20, f"{root} is not a directory")
        if any(r["path"] == root and r["name"] == name
               for r in self.realms.values()):
            raise FSError(-17, f"snapshot {name!r} exists at {root}")
        if sum(1 for r in self.realms.values()
               if r["path"] == root) >= self.snap_max:
            raise FSError(-31, "EMLINK: mds_snap_max_per_realm "
                               "snapshots already exist here")
        ret, rs, out = await self.ioctx.rados.mon_command(
            {"prefix": "fs snap create", "path": root, "name": name,
             "pool": self.ioctx.pool_name})
        if ret == -17:
            # a prior attempt allocated the sid but died before its
            # journal event landed (mon committed, realm didn't):
            # adopt the registered sid instead of failing the retry
            ret2, _, out2 = await self.ioctx.rados.mon_command(
                {"prefix": "fs snap ls", "path": root})
            sid = next((int(k) for k, v in
                        json.loads(out2)["snaps"].items()
                        if v["name"] == name), None) if ret2 == 0 \
                else None
            if sid is None:
                raise FSError(-17, rs or "snapshot exists")
        elif ret != 0:
            raise FSError(ret, rs or "snapid allocation refused")
        else:
            sid = int(json.loads(out)["snapid"])
        # recall BEFORE capture: holders flush their in-flight writes
        # and reacquire with a context including sid, so everything
        # captured below is stable and every later write COWs
        await self._recall_realm_caps(root)
        tree = await self._capture_tree(root)
        await self._journaled_apply({"op": "mksnap", "path": root,
                                     "name": name, "sid": sid,
                                     "tree": tree})
        log.dout(1, f"mds.{self.name}: mksnap {root}/.snap/{name} "
                    f"(snapid {sid}, {len(tree)} entries)")
        return sid

    async def _rmsnap(self, root: str, name: str) -> None:
        """rmdir <root>/.snap/<name>: drop the mon registry entry
        (queues the snapid into removed_snaps — the OSDs trim the
        clones) and journal the realm removal."""
        sid, _r = self._realm(root, name)
        ret, rs, _ = await self.ioctx.rados.mon_command(
            {"prefix": "fs snap rm", "path": root, "name": name})
        if ret not in (0, -2):       # -2: mon already forgot it
            raise FSError(ret, rs or "snap rm refused")
        await self._journaled_apply({"op": "rmsnap", "sid": sid})
        log.dout(1, f"mds.{self.name}: rmsnap {root}/.snap/{name} "
                    f"(snapid {sid})")

    async def _serve_snap(self, m: MClientRequest,
                          sp: tuple) -> tuple[bytes, int, int]:
        """Serve one request whose path traverses .snap. Returns
        (payload, cap_mode, cap_seq); raises FSError for errors.
        Everything inside a snapshot is immutable: only mkdir/rmdir of
        the snapshot names themselves mutate, all else is read-only."""
        root, name, rest = sp
        if m.op == "mkdir" and name and not rest:
            await self._mksnap(root, name)
            return b"", 0, 0
        if m.op == "rmdir" and name and not rest:
            await self._rmsnap(root, name)
            return b"", 0, 0
        if m.op == "readdir":
            if not name:      # ls <root>/.snap -> snapshot names
                return json.dumps(sorted(
                    r["name"] for r in self.realms.values()
                    if r["path"] == root)).encode(), 0, 0
            _sid, r = self._realm(root, name)
            return json.dumps(
                self._tree_children(r["tree"], rest)).encode(), 0, 0
        if m.op == "stat":
            if not name:      # the .snap dir itself
                return json.dumps({"path": m.path, "type": "dir",
                                   "size": 0}).encode(), 0, 0
            _sid, r = self._realm(root, name)
            ent = r["tree"].get(rest)
            if ent is None:
                raise FSError(-2, f"no such entry in snapshot")
            return json.dumps(
                {"path": m.path, "type": ent["type"],
                 "size": ent.get("size", 0)}).encode(), 0, 0
        if m.op == "open":
            if int(m.flags) == CAP_FW:
                raise FSError(EROFS, "snapshots are read-only")
            sid, r = self._realm(root, name)
            ent = r["tree"].get(rest)
            if ent is None:
                raise FSError(-2, "no such file in snapshot")
            if ent["type"] != "file":
                raise FSError(-21, "EISDIR")
            # no cap bookkeeping: snapshot content is immutable, so a
            # shared-read grant can never need revoking
            return json.dumps(
                {"size": ent.get("size", 0), "oid": ent["oid"],
                 "snapid": sid}).encode(), CAP_FR, 0
        raise FSError(EROFS, "snapshots are read-only")

    # -- session table (ref: SessionMap) ----------------------------------
    def _ingest_session_table(self, omap: dict) -> None:
        self._session_table = set(omap)
        for client, blob in omap.items():
            try:
                ent = json.loads(blob)
            except (json.JSONDecodeError, UnicodeDecodeError):
                ent = {}
            self._completed[client] = {
                int(t): int(r)
                for t, r in ent.get("completed", {}).items()}

    async def _load_session_table(self) -> None:
        from ceph_tpu.rados import ObjectOperationError
        try:
            omap = await self.ioctx.get_omap_vals(self.sessions_oid)
        except ObjectOperationError:
            omap = {}
        self._ingest_session_table(omap)

    async def _save_session(self, client: str) -> None:
        done = self._completed.get(client, {})
        await self.ioctx.set_omap(
            self.sessions_oid, client,
            json.dumps({"completed": {str(t): r for t, r in
                                      done.items()}}).encode())
        self._session_table.add(client)

    async def _forget_session(self, client: str) -> None:
        self.sessions.pop(client, None)
        self._session_seen.pop(client, None)
        self._drop_client_caps(client)
        self._completed.pop(client, None)
        if client in self._session_table:
            self._session_table.discard(client)
            try:
                await self.ioctx.rm_omap_key(self.sessions_oid, client)
            except Exception as e:
                log.dout(5, f"session table trim for {client} "
                            f"failed: {e!r}")

    async def _record_completed(self, client: str, tid: int,
                                result: int) -> None:
        """Persist one finished mutation's (tid, result) so a replay
        against a successor MDS answers from the table instead of
        re-executing (ref: Session::add_completed_request)."""
        done = self._completed.setdefault(client, {})
        done[tid] = result
        while len(done) > COMPLETED_KEEP:
            done.pop(next(iter(done)))
        if client in self.sessions or client in self._session_table:
            await self._save_session(client)

    # -- dispatch ----------------------------------------------------------
    async def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MMDSMap):
            self._handle_fsmap(FSMap.decode(msg.fsmap))
            return True
        if isinstance(msg, MClientSession):
            if self._active_event.is_set() or \
                    msg.op != SESSION_OPEN:
                await self._handle_session(msg)
            else:
                # an OPEN racing the ladder parks until active (a
                # standby must not admit sessions — its session-table
                # writes would race the live active's); parked in a
                # task so the reader loop keeps draining
                if self._stopping:
                    return True
                t = asyncio.ensure_future(
                    self._session_when_active(msg))
                self._req_tasks.add(t)
                t.add_done_callback(self._req_task_done)
            return True
        if isinstance(msg, MClientReconnect):
            if self._stopping:
                return True
            t = asyncio.ensure_future(self._handle_reconnect(msg))
            self._req_tasks.add(t)
            t.add_done_callback(self._req_task_done)
            return True
        if isinstance(msg, MClientRequest):
            # Own task, NOT awaited: the messenger's reader loop
            # dispatches serially per connection, so an open blocked in
            # the revoke/ack wait would head-of-line-block every later
            # frame from that client — including its own CAP_OP_ACK,
            # deadlocking two clients that each hold a cap the other's
            # open needs (the reference MDS never blocks the dispatcher
            # on Locker revocation). Per-path _open_locks keep the
            # ordering that matters.
            if self._stopping:
                return True              # shutting down: drop, no task
            t = asyncio.ensure_future(self._handle_request(msg))
            self._req_tasks.add(t)
            t.add_done_callback(self._req_task_done)
            return True
        if isinstance(msg, MClientCaps):
            await self._handle_caps(msg)
            return True
        if isinstance(msg, MMDSExportDir):
            if self._stopping:
                return True
            t = asyncio.ensure_future(self._handle_import(msg))
            self._req_tasks.add(t)
            t.add_done_callback(self._req_task_done)
            return True
        if isinstance(msg, MMDSExportDirAck):
            fut = self._export_acks.get(msg.path)
            if fut and not fut.done():
                fut.set_result(msg)
            return True
        return False

    async def _session_when_active(self, m: MClientSession) -> None:
        await self._active_event.wait()
        await self._handle_session(m)

    async def _handle_session(self, m: MClientSession) -> None:
        now = asyncio.get_event_loop().time()
        if m.op == SESSION_OPEN:
            self.sessions[m.src] = m.conn
            self._session_seen[m.src] = now
            # table BEFORE ack: a session the client believes open must
            # survive into a successor's reconnect window
            await self._save_session(m.src)
        elif m.op == SESSION_RENEW:
            if m.src not in self.sessions:
                return                   # evicted: renewals are void
            self._session_seen[m.src] = now
        else:
            await self._forget_session(m.src)
        # the OPEN ack advertises the lease (ms) so the client paces
        # its renewals off the MDS's configuration instead of a
        # hardcoded beat that could exceed a short lease
        await m.conn.send_message(MClientSession(
            op=m.op,
            cseq=int(self.lease_timeout * 1000)
            if m.op == SESSION_OPEN else m.cseq))

    async def _handle_reconnect(self, m: MClientReconnect) -> None:
        """A client re-claims its session + caps from this (normally
        freshly promoted) MDS (ref: Server::handle_client_reconnect).
        Parked until journal replay finishes; claims from sessions not
        in the table are refused — the client must re-mount."""
        if not self._replay_done.is_set():
            await self._replay_done.wait()
        if m.src not in self._session_table:
            MDS_PERF.inc("reconnect_rejected")
            await m.conn.send_message(MClientReconnect(
                op=RECONNECT_REJECT, caps={}))
            return
        now = asyncio.get_event_loop().time()
        self.sessions[m.src] = m.conn
        self._session_seen[m.src] = now
        for path, blob in m.caps.items():
            try:
                claim = json.loads(blob)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            ent = self.caps.setdefault(path, {}) \
                .setdefault(m.src, [0, 0])
            ent[0] = max(ent[0], int(claim.get("mode", CAP_FR)))
            ent[1] = max(ent[1], int(claim.get("count", 1)))
            self._cap_seq = max(self._cap_seq,
                                int(claim.get("cseq", 0)))
            MDS_PERF.inc("caps_replayed")
        self._recovering.discard(m.src)
        MDS_PERF.inc("reconnect_accepted")
        await m.conn.send_message(MClientReconnect(
            op=RECONNECT_ACK, caps={}))
        log.dout(1, f"session {m.src} reconnected "
                    f"({len(m.caps)} cap claims)")

    def _drop_client_caps(self, client: str) -> None:
        for path in list(self.caps):
            if self.caps[path].pop(client, None) is not None:
                if not self.caps[path]:
                    del self.caps[path]
        # a dead client can't ack: resolve its pending revokes
        for (path, holder, seq), fut in list(self._revoke_waiters.items()):
            if holder == client and not fut.done():
                fut.set_result(None)

    async def _handle_caps(self, m: MClientCaps) -> None:
        if m.op == CAP_OP_ACK:
            fut = self._revoke_waiters.pop((m.path, m.src, m.cseq), None)
            if fut and not fut.done():
                fut.set_result(None)
            holders = self.caps.get(m.path, {})
            holders.pop(m.src, None)
            if not holders:
                self.caps.pop(m.path, None)
        elif m.op == CAP_OP_RELEASE:
            holders = self.caps.get(m.path, {})
            ent = holders.get(m.src)
            if ent is not None:
                ent[1] -= 1               # one handle closed; the cap
                if ent[1] <= 0:           # survives while others remain
                    holders.pop(m.src, None)
            if not holders:
                self.caps.pop(m.path, None)

    async def _revoke_conflicting(self, path: str, client: str,
                                  want: int) -> None:
        """Send revokes to every holder whose cap conflicts with
        ``want`` and wait for their acks (ref: Locker::revoke_client_
        caps + the grant-after-ack ordering)."""
        holders = self.caps.get(path, {})
        waits = []
        keys = []
        for holder, (mode, _cnt) in list(holders.items()):
            if holder == client:
                continue
            conflict = want == CAP_FW or mode == CAP_FW
            if not conflict:
                continue
            self._cap_seq += 1
            seq = self._cap_seq
            fut = asyncio.get_event_loop().create_future()
            self._revoke_waiters[(path, holder, seq)] = fut
            keys.append((path, holder, seq))
            conn = self.sessions.get(holder)
            if conn is None:
                fut.set_result(None)
                holders.pop(holder, None)
            else:
                await conn.send_message(MClientCaps(
                    op=CAP_OP_REVOKE, path=path, mode=mode, cseq=seq))
            waits.append(fut)
        if waits:
            loop = asyncio.get_event_loop()
            deadline = loop.time() + self.revoke_timeout
            try:
                while True:
                    pending = [f for f in waits if not f.done()]
                    if not pending:
                        break
                    slice_t = min(self.lease_timeout,
                                  deadline - loop.time())
                    if slice_t <= 0:
                        raise asyncio.TimeoutError
                    await asyncio.wait(pending, timeout=slice_t)
                    # evict holders whose lease lapsed while we waited:
                    # a dead/hung client must not hold exclusivity
                    # hostage (drop_client_caps resolves its waiters)
                    now = loop.time()
                    for p, holder, seq in keys:
                        fut = self._revoke_waiters.get((p, holder, seq))
                        if fut and not fut.done() and \
                                now - self._session_seen.get(holder, 0) \
                                > self.lease_timeout:
                            log.dout(1, f"evicting client {holder}: "
                                        f"cap lease expired with a "
                                        f"revoke outstanding")
                            # FENCE FIRST (ref: MDS eviction pairs with
                            # an osdmap blocklist): until the OSDs
                            # refuse the zombie's ops, dropping its
                            # caps would let it keep writing under the
                            # stale grant when it resumes. Only after
                            # the blocklist commits do the waiters
                            # resolve and the competing open proceed.
                            try:
                                ret, rs, outbl = await \
                                    self.ioctx.rados.mon_command(
                                        {"prefix": "osd blocklist",
                                         "blocklistop": "add",
                                         "addr": holder})
                            except Exception as e:
                                ret, rs = -1, repr(e)
                            if ret != 0:
                                # NO fence, NO eviction: releasing the
                                # caps without the OSD-level fence
                                # would let the zombie write under its
                                # stale grant. Retry next slice; the
                                # revoke deadline bounds the wait.
                                log.dout(0, f"blocklist of {holder} "
                                            f"failed ({rs}); eviction "
                                            f"deferred")
                                continue
                            # EPOCH BARRIER (ref: upstream eviction's
                            # wait-for-blocklist-epoch): the mon commit
                            # alone is not a fence — an OSD still on a
                            # pre-blocklist map would accept the
                            # zombie's writes. Wait until every OSD
                            # that could serve them has OBSERVED the
                            # blocklist epoch; if that can't be proven
                            # inside the revoke window, keep the caps
                            # (defer, like a failed blocklist).
                            if not await self._blocklist_barrier(
                                    holder, outbl):
                                continue
                            await self._forget_session(holder)
            finally:
                # a holder that never acks must not leak its waiter
                for key in keys:
                    self._revoke_waiters.pop(key, None)

    async def _blocklist_barrier(self, holder: str,
                                 outbl: bytes) -> bool:
        """Wait until the OSDs observe the blocklist epoch (the fence
        is enforced OSD-side against each OSD's OWN map). True when
        proven; False defers the eviction to the next revoke slice."""
        try:
            epoch = int(json.loads(outbl).get("epoch", 0)) if outbl \
                else 0
        except (json.JSONDecodeError, ValueError):
            epoch = 0
        if not epoch:
            # old mon without epoch reporting: nothing to barrier on;
            # keep the pre-barrier behavior rather than deadlocking
            return True
        objecter = getattr(self.ioctx.rados, "objecter", None)
        if objecter is None:
            return True
        try:
            await objecter.wait_for_map_on_osds(
                epoch, timeout=min(self.lease_timeout, 10.0))
            return True
        except Exception as e:
            log.dout(0, f"epoch barrier for {holder} (epoch {epoch}) "
                        f"not reached: {e}; eviction deferred")
            return False

    def _req_task_done(self, t: asyncio.Task) -> None:
        self._req_tasks.discard(t)
        if not t.cancelled() and t.exception() is not None:
            log.dout(0, f"client request task failed: "
                        f"{t.exception()!r}")

    def _req_cap_denied(self, entity: str) -> bool:
        """Per-op MDS cap check (ref: MDSAuthCaps::is_capable, scoped
        to the r/w class like the OSD/mon slices): True when the
        sender has a committed cap table whose ``mds`` spec does not
        grant writes. Capless entities stay unrestricted — the same
        legacy-boot-key policy as the mon command and OSD admission
        checks. The table reaches this daemon through a keyring fed
        by the MAuthUpdate subscription (the monc's in HA mode, an
        explicitly handed one standalone)."""
        kr = None
        if self.monc is not None:
            kr = self.monc.msgr.keyring
        if kr is None:
            kr = self.keyring
        if kr is None or not entity:
            return False
        caps = kr.caps_of(entity)
        if not caps:
            return False
        from ceph_tpu.msg.auth import cap_allows
        return not cap_allows(str(caps.get("mds", "")), "w")

    async def _handle_request(self, m: MClientRequest) -> None:
        if not self._active_event.is_set():
            # not (yet) the active rank: park — clients only target the
            # FSMap's active, so this resolves as the ladder finishes
            # (the task is cancelled if the daemon stops instead)
            await self._active_event.wait()
        m.path = _norm(m.path)          # caps/journal key consistently
        if m.path2:
            m.path2 = _norm(m.path2)
        span = self.tracer.from_msg(
            "mds_op", m, tags={"op": m.op, "path": m.path,
                               "rank": self.rank})
        t0 = time.monotonic()
        # multi-active routing (round 7): a request for a subtree this
        # rank does not own is REDIRECTED before the session check — a
        # client aimed at the wrong rank needs the owner's address,
        # not a session here. Frozen subtrees park inside.
        admitted = None
        if self.monc is not None and self.fsmap is not None:
            red = await self._route_or_park(m)
            if red is not None:
                if span is not None:
                    # the -ESTALE hop is a real phase of the op: keep
                    # it in the trace so a cross-rank bounce shows up
                    span.tag("redirect", True)
                    try:
                        span.tag("redirect_to", json.loads(
                            red.payload).get("rank"))
                    except Exception:
                        pass
                    span.finish()
                await m.conn.send_message(red)
                return
            admitted = m._admitted
        try:
            await self._serve_request(m)
        finally:
            if admitted is not None:
                self._inflight_done(admitted)
            if span is not None:
                span.finish()
            MDS_PERF.hist_add("req_latency_hist",
                              (time.monotonic() - t0) * 1e6)

    async def _serve_request(self, m: MClientRequest) -> None:
        if m.src not in self.sessions:
            await m.conn.send_message(MClientReply(
                tid=m.tid, result=-1, payload=b"no session",
                cap_mode=0, cap_seq=0))
            return
        # completed-request dedup (ref: Session::have_completed_request):
        # a mutation replayed after failover must answer from the
        # table, not re-execute — a second rename/unlink would fail and
        # a second create could truncate acknowledged data. The dedup
        # outranks the cap gate below: a mutation that ALREADY applied
        # must keep answering its recorded result even if the entity's
        # caps were narrowed after the fact (the at-most-once contract
        # is about what happened, not what would be admitted today).
        if m.op in MUTATING_OPS:
            done = self._completed.get(m.src)
            if done is not None and m.tid in done:
                await m.conn.send_message(MClientReply(
                    tid=m.tid, result=done[m.tid],
                    payload=b"(replayed)", cap_mode=0, cap_seq=0))
                return
        if m.op in MUTATING_OPS and self._req_cap_denied(m.src):
            # per-op cap enforcement at the request gate (ROADMAP #3b,
            # the MDS leg of PR 11's OSD admission check): an
            # `mds r`-only entity's NEW mutation is refused -EPERM
            # before the journal sees it — deterministic and
            # unrecorded, so a replayed refusal re-refuses identically
            await m.conn.send_message(MClientReply(
                tid=m.tid, result=-1,
                payload=b"EPERM: mds caps deny write",
                cap_mode=0, cap_seq=0))
            return
        result, payload, cap_mode, cap_seq = 0, b"", 0, 0
        sp = snap_split(m.path)
        try:
            if sp is not None or (m.path2 and snap_split(m.path2)):
                if sp is None:
                    # rename INTO .snap (src outside): still a mutation
                    # of snapshot namespace
                    raise FSError(EROFS, "snapshots are read-only")
                payload, cap_mode, cap_seq = await self._serve_snap(
                    m, sp)
            elif m.op in ("mkdir", "rmdir", "create", "unlink"):
                await self._journaled_apply({"op": m.op, "path": m.path})
            elif m.op == "rename":
                await self._journaled_apply(
                    {"op": "rename", "path": m.path, "path2": m.path2})
            elif m.op == "setattr":
                await self._journaled_apply(
                    {"op": "setattr", "path": m.path,
                     "size": int(m.flags)})
            elif m.op == "readdir":
                payload = json.dumps(await self.fs.ls(m.path)).encode()
            elif m.op == "stat":
                payload = json.dumps(await self.fs.stat(m.path)).encode()
            elif m.op == "open":
                want = int(m.flags)
                # stat + create-on-open + revoke + grant all under the
                # per-path lock: two concurrent conflicting opens must
                # decide sequentially or both can believe they hold
                # exclusivity — and the existence check must be atomic
                # with the create, or a racing open-w's create (a
                # write_full truncate) can land AFTER the first opener
                # was granted FW and wrote data, destroying an
                # acknowledged write.
                async with self._open_locks.hold(m.path):
                    st = None
                    try:
                        st = await self.fs.stat(m.path)
                    except FSError:
                        if want != CAP_FW:
                            raise
                    if st is not None and st["type"] != "file":
                        raise FSError(-21, "EISDIR")
                    if st is None:                   # create on open-w
                        await self._journaled_apply(
                            {"op": "create", "path": m.path})
                    await self._revoke_conflicting(m.path, m.src,
                                                   want)
                    self._cap_seq += 1
                    cap_seq = self._cap_seq
                    ent = self.caps.setdefault(m.path, {}) \
                        .setdefault(m.src, [0, 0])
                    ent[0] = max(ent[0], want)       # FW absorbs FR
                    ent[1] += 1
                    cap_mode = ent[0]
                    # re-stat AFTER the revoke wait: a writer's
                    # setattr may have landed while we blocked
                    try:
                        st = await self.fs.stat(m.path)
                    except FSError:
                        st = None
                info = {"size": 0 if st is None else st["size"],
                        "oid": _fileobj(m.path)}
                # snap context for writes under a live realm (ref:
                # the SnapContext a Client stamps on OSD writes): the
                # OSD COWs the head into a clone before the first
                # write that carries a snapid it hasn't preserved yet
                sids = self._snaps_governing(m.path)
                if sids:
                    info["snapc"] = [sids[-1], sids[::-1]]
                payload = json.dumps(info).encode()
            else:
                result = -22                          # -EINVAL
        except FSError as e:
            result = e.errno
            payload = str(e).encode()
        except asyncio.TimeoutError:
            result = -110                             # -ETIMEDOUT
            payload = b"cap revoke timed out"
        if m.op in MUTATING_OPS and result != -110:
            # -ETIMEDOUT stays retryable; anything else is this op's
            # final answer and must survive a replay against a
            # successor
            await self._record_completed(m.src, m.tid, result)
        await m.conn.send_message(MClientReply(
            tid=m.tid, result=result, payload=payload,
            cap_mode=cap_mode, cap_seq=cap_seq))
