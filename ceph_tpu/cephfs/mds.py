"""The MDS daemon: metadata authority + client capabilities.

ref: src/mds/ (MDSDaemon, Server::handle_client_request, Locker's cap
machinery, MDLog/EUpdate journaling) + src/messages/MClientRequest.h /
MClientReply.h / MClientCaps.h — rebuilt small on this framework's
messenger. The division of labor is the reference's:

- ALL namespace mutations flow through the MDS, which journals each
  one to a metadata-pool journal object before applying it to the
  dirfrag omap objects (the same on-disk model ``CephFSLite`` uses —
  an MDS restart replays uncommitted journal events idempotently, the
  EUpdate/MDLog pattern in miniature).
- File DATA I/O never touches the MDS: clients read/write the
  ``.file<path>`` RADOS objects directly — but only while holding a
  file capability granted by the MDS.

Capabilities (ref: Locker, simplified to the file caps that matter at
this scope): ``CAP_FR`` is shared-read, ``CAP_FW`` is exclusive-write.
A conflicting open triggers revoke messages to the current holders;
the grant is withheld until every holder acks (writers flush before
acking), which is exactly the reference's revoke/ack dance. Sessions
(ref: MClientSession) gate everything; closing a session drops its
caps and wakes any waiter blocked on them.

Cap leases (round 5): clients heartbeat SESSION_RENEW; a holder whose
lease lapses while a revoke is outstanding is EVICTED (session + caps
dropped, its revoke waiters resolved) so a dead client cannot hold
exclusivity hostage — the Session::last_cap_renew + stale-eviction
behavior in miniature.

Not rebuilt: dynamic subtree partitioning/multi-MDS, the full inode
lock matrix.
"""

from __future__ import annotations

import asyncio
import json

from ceph_tpu.cephfs import CephFSLite, FSError, _fileobj, _norm
from ceph_tpu.msg import Dispatcher, Messenger
from ceph_tpu.msg.message import Message, register
from ceph_tpu.utils.locks import KeyedLocks
from ceph_tpu.utils.logging import get_logger

log = get_logger("mds")

SESSION_OPEN = 1
SESSION_CLOSE = 2
SESSION_RENEW = 3   # client heartbeat keeping its cap lease alive
                    # (ref: CEPH_SESSION_REQUEST_RENEWCAPS)

CAP_FR = 1          # shared read
CAP_FW = 2          # exclusive write

CAP_OP_GRANT = 1    # mds -> client (unsolicited would go here; unused)
CAP_OP_REVOKE = 2   # mds -> client: stop using this cap, then ack
CAP_OP_ACK = 3      # client -> mds: revoke done (writers flushed)
CAP_OP_RELEASE = 4  # client -> mds: voluntary drop (file close)

JOURNAL_OID = ".mds_journal"


@register
class MClientSession(Message):
    """ref: MClientSession (REQUEST_OPEN/REQUEST_CLOSE + ack)."""
    TYPE = 220
    FIELDS = [("op", "u32"), ("cseq", "u64")]


@register
class MClientRequest(Message):
    """ref: MClientRequest — one metadata op. ``op`` is the lowercase
    op name (mkdir/rmdir/readdir/stat/create/unlink/rename/open/
    setattr); path2 = rename target; flags = cap mode for open,
    size for setattr."""
    TYPE = 221
    FIELDS = [("tid", "u64"), ("op", "str"), ("path", "str"),
              ("path2", "str"), ("flags", "u64")]


@register
class MClientReply(Message):
    """ref: MClientReply. result <= 0 errno; payload = op-specific
    JSON; cap_mode/cap_seq set for open replies."""
    TYPE = 222
    FIELDS = [("tid", "u64"), ("result", "s64"), ("payload", "blob"),
              ("cap_mode", "u32"), ("cap_seq", "u64")]


@register
class MClientCaps(Message):
    """ref: MClientCaps — both directions (op disambiguates)."""
    TYPE = 223
    FIELDS = [("op", "u32"), ("path", "str"), ("mode", "u32"),
              ("cseq", "u64")]


class MDSDaemon(Dispatcher):
    """Single-rank MDS over one metadata/data pool ioctx."""

    def __init__(self, ioctx, name: str = "a",
                 messenger: Messenger | None = None,
                 lease_timeout: float = 10.0,
                 revoke_timeout: float = 30.0):
        self.fs = CephFSLite(ioctx)
        self.ioctx = ioctx
        self.msgr = messenger or Messenger(f"mds.{name}")
        self.msgr.add_dispatcher(self)
        self.sessions: dict[str, object] = {}       # client -> conn
        # cap leases (ref: Session::last_cap_renew + the Locker's
        # stale-session eviction): a client renews via SESSION_RENEW;
        # one whose lease lapses while sitting on an unacked revoke is
        # EVICTED (session + caps dropped) instead of stalling every
        # conflicting open to the revoke timeout.
        self.lease_timeout = lease_timeout
        self.revoke_timeout = revoke_timeout
        self._session_seen: dict[str, float] = {}   # client -> loop time
        # path -> {client: [mode, refcount]}; invariant: at most one
        # CAP_FW holder, never FW alongside another client's FR. A
        # same-client re-open bumps the refcount and can only upgrade
        # the mode (FW absorbs FR); releases drop the entry at zero.
        self.caps: dict[str, dict[str, list]] = {}
        self._cap_seq = 0
        # (path, client, seq) -> future resolved by the holder's ack
        self._revoke_waiters: dict[tuple, asyncio.Future] = {}
        # serializes the revoke+grant decision per path: without it two
        # concurrent conflicting opens both see the pre-revoke holder
        # table and both grant themselves exclusivity. User-counted so
        # entries drop when the last opener leaves (no per-path leak).
        self._open_locks = KeyedLocks()
        self._req_tasks: set[asyncio.Task] = set()
        self._stopping = False
        self._journal_seq = 0
        self.addr = None

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        # root dirfrag first (idempotent): journal replay on a fresh
        # pool needs it, and every request would ENOENT without it
        await self.fs.mount()
        await self._replay_journal()
        self.addr = await self.msgr.bind(host, port)
        log.dout(1, f"mds up at {self.addr}")
        return self.addr

    async def stop(self) -> None:
        # cancel detached request handlers FIRST: a handler parked in
        # the 30 s revoke wait must not outlive the daemon and mutate
        # caps / append journal events a later MDS would replay. The
        # stopping flag stops ms_dispatch spawning NEW tasks while the
        # gather below yields to the loop; the while drains any that
        # slipped in before the flag was observed.
        self._stopping = True
        while self._req_tasks:
            tasks = list(self._req_tasks)
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
        await self.msgr.shutdown()

    # -- journaling (ref: MDLog + EUpdate, segments of one) ---------------
    async def _journal(self, event: dict) -> int:
        """Append-then-apply: the event lands durably in the journal
        omap before the dirfrag mutation happens; _commit trims it
        after. Replay applies any event still present (idempotent ops,
        same outcome)."""
        self._journal_seq += 1
        seq = self._journal_seq
        await self.ioctx.set_omap(JOURNAL_OID, f"{seq:016d}",
                                  json.dumps(event).encode())
        return seq

    async def _commit(self, seq: int) -> None:
        await self.ioctx.rm_omap_key(JOURNAL_OID, f"{seq:016d}")

    async def _journaled_apply(self, ev: dict) -> None:
        """journal -> apply -> trim. The entry is trimmed on FAILURE
        too: an op the client was told failed must not linger and
        replay 'successfully' after conditions change (only a crash
        between append and apply leaves an entry for replay)."""
        seq = await self._journal(ev)
        try:
            await self._apply(ev)
        finally:
            await self._commit(seq)

    async def _replay_journal(self) -> None:
        from ceph_tpu.rados import ObjectOperationError
        try:
            entries = await self.ioctx.get_omap_vals(JOURNAL_OID)
        except ObjectOperationError:
            return
        for k in sorted(entries):
            ev = json.loads(entries[k])
            log.dout(1, f"mds journal replay: {ev}")
            try:
                await self._apply(ev)
            except FSError as e:
                # idempotent replay: EEXIST/ENOENT mean the mutation
                # already landed before the crash
                log.dout(5, f"replay skip ({e.errno}): {ev}")
            await self.ioctx.rm_omap_key(JOURNAL_OID, k)
            self._journal_seq = max(self._journal_seq, int(k))

    async def _apply(self, ev: dict) -> None:
        op = ev["op"]
        if op == "mkdir":
            await self.fs.mkdir(ev["path"])
        elif op == "rmdir":
            await self.fs.rmdir(ev["path"])
        elif op == "create":
            # must stay idempotent AND non-destructive: a stale create
            # replayed after the file gained data must not truncate it
            try:
                await self.fs.stat(ev["path"])
            except FSError:
                await self.fs.write_file(ev["path"], b"")
        elif op == "unlink":
            await self.fs.unlink(ev["path"])
        elif op == "rename":
            await self.fs.rename(ev["path"], ev["path2"])
        elif op == "setattr":
            await self.fs.set_size(ev["path"], ev["size"])
        else:                                        # pragma: no cover
            raise ValueError(f"unknown journal op {op}")

    # -- dispatch ----------------------------------------------------------
    async def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MClientSession):
            await self._handle_session(msg)
            return True
        if isinstance(msg, MClientRequest):
            # Own task, NOT awaited: the messenger's reader loop
            # dispatches serially per connection, so an open blocked in
            # the revoke/ack wait would head-of-line-block every later
            # frame from that client — including its own CAP_OP_ACK,
            # deadlocking two clients that each hold a cap the other's
            # open needs (the reference MDS never blocks the dispatcher
            # on Locker revocation). Per-path _open_locks keep the
            # ordering that matters.
            if self._stopping:
                return True              # shutting down: drop, no task
            t = asyncio.ensure_future(self._handle_request(msg))
            self._req_tasks.add(t)
            t.add_done_callback(self._req_task_done)
            return True
        if isinstance(msg, MClientCaps):
            await self._handle_caps(msg)
            return True
        return False

    async def _handle_session(self, m: MClientSession) -> None:
        now = asyncio.get_event_loop().time()
        if m.op == SESSION_OPEN:
            self.sessions[m.src] = m.conn
            self._session_seen[m.src] = now
        elif m.op == SESSION_RENEW:
            if m.src not in self.sessions:
                return                   # evicted: renewals are void
            self._session_seen[m.src] = now
        else:
            self.sessions.pop(m.src, None)
            self._session_seen.pop(m.src, None)
            self._drop_client_caps(m.src)
        # the OPEN ack advertises the lease (ms) so the client paces
        # its renewals off the MDS's configuration instead of a
        # hardcoded beat that could exceed a short lease
        await m.conn.send_message(MClientSession(
            op=m.op,
            cseq=int(self.lease_timeout * 1000)
            if m.op == SESSION_OPEN else m.cseq))

    def _drop_client_caps(self, client: str) -> None:
        for path in list(self.caps):
            if self.caps[path].pop(client, None) is not None:
                if not self.caps[path]:
                    del self.caps[path]
        # a dead client can't ack: resolve its pending revokes
        for (path, holder, seq), fut in list(self._revoke_waiters.items()):
            if holder == client and not fut.done():
                fut.set_result(None)

    async def _handle_caps(self, m: MClientCaps) -> None:
        if m.op == CAP_OP_ACK:
            fut = self._revoke_waiters.pop((m.path, m.src, m.cseq), None)
            if fut and not fut.done():
                fut.set_result(None)
            holders = self.caps.get(m.path, {})
            holders.pop(m.src, None)
            if not holders:
                self.caps.pop(m.path, None)
        elif m.op == CAP_OP_RELEASE:
            holders = self.caps.get(m.path, {})
            ent = holders.get(m.src)
            if ent is not None:
                ent[1] -= 1               # one handle closed; the cap
                if ent[1] <= 0:           # survives while others remain
                    holders.pop(m.src, None)
            if not holders:
                self.caps.pop(m.path, None)

    async def _revoke_conflicting(self, path: str, client: str,
                                  want: int) -> None:
        """Send revokes to every holder whose cap conflicts with
        ``want`` and wait for their acks (ref: Locker::revoke_client_
        caps + the grant-after-ack ordering)."""
        holders = self.caps.get(path, {})
        waits = []
        keys = []
        for holder, (mode, _cnt) in list(holders.items()):
            if holder == client:
                continue
            conflict = want == CAP_FW or mode == CAP_FW
            if not conflict:
                continue
            self._cap_seq += 1
            seq = self._cap_seq
            fut = asyncio.get_event_loop().create_future()
            self._revoke_waiters[(path, holder, seq)] = fut
            keys.append((path, holder, seq))
            conn = self.sessions.get(holder)
            if conn is None:
                fut.set_result(None)
                holders.pop(holder, None)
            else:
                await conn.send_message(MClientCaps(
                    op=CAP_OP_REVOKE, path=path, mode=mode, cseq=seq))
            waits.append(fut)
        if waits:
            loop = asyncio.get_event_loop()
            deadline = loop.time() + self.revoke_timeout
            try:
                while True:
                    pending = [f for f in waits if not f.done()]
                    if not pending:
                        break
                    slice_t = min(self.lease_timeout,
                                  deadline - loop.time())
                    if slice_t <= 0:
                        raise asyncio.TimeoutError
                    await asyncio.wait(pending, timeout=slice_t)
                    # evict holders whose lease lapsed while we waited:
                    # a dead/hung client must not hold exclusivity
                    # hostage (drop_client_caps resolves its waiters)
                    now = loop.time()
                    for p, holder, seq in keys:
                        fut = self._revoke_waiters.get((p, holder, seq))
                        if fut and not fut.done() and \
                                now - self._session_seen.get(holder, 0) \
                                > self.lease_timeout:
                            log.dout(1, f"evicting client {holder}: "
                                        f"cap lease expired with a "
                                        f"revoke outstanding")
                            # FENCE FIRST (ref: MDS eviction pairs with
                            # an osdmap blocklist): until the OSDs
                            # refuse the zombie's ops, dropping its
                            # caps would let it keep writing under the
                            # stale grant when it resumes. Only after
                            # the blocklist commits do the waiters
                            # resolve and the competing open proceed.
                            try:
                                ret, rs, outbl = await \
                                    self.ioctx.rados.mon_command(
                                        {"prefix": "osd blocklist",
                                         "blocklistop": "add",
                                         "addr": holder})
                            except Exception as e:
                                ret, rs = -1, repr(e)
                            if ret != 0:
                                # NO fence, NO eviction: releasing the
                                # caps without the OSD-level fence
                                # would let the zombie write under its
                                # stale grant. Retry next slice; the
                                # revoke deadline bounds the wait.
                                log.dout(0, f"blocklist of {holder} "
                                            f"failed ({rs}); eviction "
                                            f"deferred")
                                continue
                            # EPOCH BARRIER (ref: upstream eviction's
                            # wait-for-blocklist-epoch): the mon commit
                            # alone is not a fence — an OSD still on a
                            # pre-blocklist map would accept the
                            # zombie's writes. Wait until every OSD
                            # that could serve them has OBSERVED the
                            # blocklist epoch; if that can't be proven
                            # inside the revoke window, keep the caps
                            # (defer, like a failed blocklist).
                            if not await self._blocklist_barrier(
                                    holder, outbl):
                                continue
                            self.sessions.pop(holder, None)
                            self._session_seen.pop(holder, None)
                            self._drop_client_caps(holder)
            finally:
                # a holder that never acks must not leak its waiter
                for key in keys:
                    self._revoke_waiters.pop(key, None)

    async def _blocklist_barrier(self, holder: str,
                                 outbl: bytes) -> bool:
        """Wait until the OSDs observe the blocklist epoch (the fence
        is enforced OSD-side against each OSD's OWN map). True when
        proven; False defers the eviction to the next revoke slice."""
        try:
            epoch = int(json.loads(outbl).get("epoch", 0)) if outbl \
                else 0
        except (json.JSONDecodeError, ValueError):
            epoch = 0
        if not epoch:
            # old mon without epoch reporting: nothing to barrier on;
            # keep the pre-barrier behavior rather than deadlocking
            return True
        objecter = getattr(self.ioctx.rados, "objecter", None)
        if objecter is None:
            return True
        try:
            await objecter.wait_for_map_on_osds(
                epoch, timeout=min(self.lease_timeout, 10.0))
            return True
        except Exception as e:
            log.dout(0, f"epoch barrier for {holder} (epoch {epoch}) "
                        f"not reached: {e}; eviction deferred")
            return False

    def _req_task_done(self, t: asyncio.Task) -> None:
        self._req_tasks.discard(t)
        if not t.cancelled() and t.exception() is not None:
            log.dout(0, f"client request task failed: "
                        f"{t.exception()!r}")

    async def _handle_request(self, m: MClientRequest) -> None:
        if m.src not in self.sessions:
            await m.conn.send_message(MClientReply(
                tid=m.tid, result=-1, payload=b"no session",
                cap_mode=0, cap_seq=0))
            return
        m.path = _norm(m.path)          # caps/journal key consistently
        if m.path2:
            m.path2 = _norm(m.path2)
        result, payload, cap_mode, cap_seq = 0, b"", 0, 0
        try:
            if m.op in ("mkdir", "rmdir", "create", "unlink"):
                await self._journaled_apply({"op": m.op, "path": m.path})
            elif m.op == "rename":
                await self._journaled_apply(
                    {"op": "rename", "path": m.path, "path2": m.path2})
            elif m.op == "setattr":
                await self._journaled_apply(
                    {"op": "setattr", "path": m.path,
                     "size": int(m.flags)})
            elif m.op == "readdir":
                payload = json.dumps(await self.fs.ls(m.path)).encode()
            elif m.op == "stat":
                payload = json.dumps(await self.fs.stat(m.path)).encode()
            elif m.op == "open":
                want = int(m.flags)
                # stat + create-on-open + revoke + grant all under the
                # per-path lock: two concurrent conflicting opens must
                # decide sequentially or both can believe they hold
                # exclusivity — and the existence check must be atomic
                # with the create, or a racing open-w's create (a
                # write_full truncate) can land AFTER the first opener
                # was granted FW and wrote data, destroying an
                # acknowledged write.
                async with self._open_locks.hold(m.path):
                    st = None
                    try:
                        st = await self.fs.stat(m.path)
                    except FSError:
                        if want != CAP_FW:
                            raise
                    if st is not None and st["type"] != "file":
                        raise FSError(-21, "EISDIR")
                    if st is None:                   # create on open-w
                        await self._journaled_apply(
                            {"op": "create", "path": m.path})
                    await self._revoke_conflicting(m.path, m.src,
                                                   want)
                    self._cap_seq += 1
                    cap_seq = self._cap_seq
                    ent = self.caps.setdefault(m.path, {}) \
                        .setdefault(m.src, [0, 0])
                    ent[0] = max(ent[0], want)       # FW absorbs FR
                    ent[1] += 1
                    cap_mode = ent[0]
                    # re-stat AFTER the revoke wait: a writer's
                    # setattr may have landed while we blocked
                    try:
                        st = await self.fs.stat(m.path)
                    except FSError:
                        st = None
                payload = json.dumps(
                    {"size": 0 if st is None else st["size"],
                     "oid": _fileobj(m.path)}).encode()
            else:
                result = -22                          # -EINVAL
        except FSError as e:
            result = e.errno
            payload = str(e).encode()
        except asyncio.TimeoutError:
            result = -110                             # -ETIMEDOUT
            payload = b"cap revoke timed out"
        await m.conn.send_message(MClientReply(
            tid=m.tid, result=result, payload=payload,
            cap_mode=cap_mode, cap_seq=cap_seq))
