"""Platform selection shim.

This sandbox's sitecustomize force-selects the remote-TPU backend through
``jax.config`` — plain ``JAX_PLATFORMS=cpu`` in the environment is
silently outranked, and initializing the remote backend dials a device
claim that can block for minutes. CLI entry points call
``honor_platform_env()`` so the conventional env var works as users
expect; when the var is unset the configured default (the real TPU under
the driver) stands.
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    plats = os.environ.get("JAX_PLATFORMS")
    if plats:
        import jax

        jax.config.update("jax_platforms", plats)


def cli_main(fn):
    """Decorator for CLI main(argv) functions: apply the platform shim
    before any device work. Every bench/tool entry point uses this."""
    import functools

    @functools.wraps(fn)
    def wrapper(argv=None):
        honor_platform_env()
        return fn(argv)

    return wrapper
