"""Platform selection shim.

This sandbox's sitecustomize force-selects the remote-TPU backend through
``jax.config`` — plain ``JAX_PLATFORMS=cpu`` in the environment is
silently outranked, and initializing the remote backend dials a device
claim that can block for minutes. CLI entry points call
``honor_platform_env()`` so the conventional env var works as users
expect; when the var is unset the configured default (the real TPU under
the driver) stands.
"""

from __future__ import annotations

import os


def enable_x64(new_val: bool = True):
    """Scoped 64-bit-dtype context, portable across jax releases:
    ``jax.enable_x64`` (newer) vs ``jax.experimental.enable_x64``
    (the only spelling in the pinned 0.4.x)."""
    import jax

    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx
    return ctx(new_val)


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Portable shard_map: ``jax.shard_map(check_vma=...)`` (newer)
    vs ``jax.experimental.shard_map.shard_map(check_rep=...)`` (the
    pinned 0.4.x spelling of the same replication checker knob)."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as sm_exp
    return sm_exp(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_rep=check_vma)


def honor_platform_env() -> None:
    plats = os.environ.get("JAX_PLATFORMS")
    if plats:
        import jax

        jax.config.update("jax_platforms", plats)


def cli_main(fn):
    """Decorator for CLI main(argv) functions: apply the platform shim
    before any device work. Every bench/tool entry point uses this."""
    import functools

    @functools.wraps(fn)
    def wrapper(argv=None):
        honor_platform_env()
        return fn(argv)

    return wrapper
