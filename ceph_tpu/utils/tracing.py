"""Distributed op tracing: Tracer/Span core + cross-daemon reassembly.

TPU-native analog of Ceph's tracing layer (ref: src/common/tracer.{h,cc}
— the Jaeger/blkin integration whose trace context rides MOSDOp so one
client op can be decomposed into queue / replica / store time across
daemons). A ``Span`` is one timed phase inside one daemon; spans of one
logical op share a ``trace_id`` and link through ``parent_span_id``, and
the context crosses message boundaries as two u64s appended to every
wire ``Message`` (zero = untraced).

Sampling model:

- **head-based**: ``trace_sampling_rate`` decides at the op's root
  (client side) whether the trace gets a nonzero trace_id and therefore
  propagates downstream;
- **tail-based retention for slow ops**: an UNSAMPLED root is still
  timed locally (one Span object, no propagation), and if its duration
  crosses ``trace_slow_keep_s`` it is assigned a trace id post-hoc and
  kept in the slow buffer — SLOW_OPS warnings stay drill-downable even
  at sampling 0. ``trace_slow_keep_s <= 0`` disables even this local
  timing (the truly-off path the bench pins).

Completed spans land in a bounded per-daemon buffer (asok
``dump_tracing``) and a bounded ship queue the daemon's existing
reporting loop drains monward (MPGStats / MDSBeacon piggyback,
MTraceReport for clients); the mon pools them and the mgr
TracingModule reassembles cross-daemon traces by trace_id
(``ceph trace ls`` / ``ceph trace show <trace_id>``).
"""

from __future__ import annotations

import json
import random
import time
from collections import OrderedDict, deque
from typing import Any


def new_trace_id() -> int:
    """Nonzero 63-bit id (0 is the 'untraced' sentinel on the wire)."""
    return random.getrandbits(63) | 1


class Span:
    """One timed phase inside one daemon (ref: a jspan/blkin trace
    point pair). ``trace_id == 0`` marks a local-only root still
    awaiting the tail-retention decision."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_span_id",
                 "name", "service", "start", "_t0", "duration", "tags",
                 "finished")

    def __init__(self, tracer: "Tracer | None", name: str,
                 trace_id: int, parent_span_id: int = 0,
                 tags: dict | None = None):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = new_trace_id()
        self.parent_span_id = parent_span_id
        self.name = name
        self.service = tracer.service if tracer is not None else ""
        self.start = time.time()          # wall: cross-daemon alignment
        self._t0 = time.monotonic()       # monotonic: durations
        self.duration: float | None = None
        self.tags: dict = dict(tags) if tags else {}
        self.finished = False

    def tag(self, key: str, value: Any) -> "Span":
        self.tags[key] = value
        return self

    def child(self, name: str, tags: dict | None = None) -> "Span":
        """A child span in the SAME daemon (same trace, linked)."""
        return Span(self.tracer, name, self.trace_id,
                    parent_span_id=self.span_id, tags=tags)

    def annotate(self, name: str, duration: float,
                 tags: dict | None = None) -> None:
        """Record an already-measured sub-phase as a FINISHED child
        span (the kv/WAL split: synchronous store code times its own
        phases and the caller attaches them post-hoc — a live child
        span would double-count the enclosing wall)."""
        s = self.child(name, tags=tags)
        s.finished = True
        s.duration = max(float(duration), 0.0)
        # start back-dated so the child nests inside this span's wall
        s.start = self.start
        if s.tracer is not None:
            s.tracer.record(s)

    def finish(self) -> None:
        if self.finished:
            return
        self.finished = True
        self.duration = time.monotonic() - self._t0
        if self.tracer is not None:
            self.tracer.record(self)

    def dump(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "name": self.name,
            "service": self.service,
            "start": self.start,
            "duration": round(
                self.duration if self.duration is not None
                else time.monotonic() - self._t0, 9),
            "tags": self.tags,
        }


class Tracer:
    """Per-daemon span factory + bounded completed-span buffers.

    Knobs are read LIVE from the daemon's config dict (falling back to
    the registered utils.config defaults), so `config set` style
    runtime changes apply to the next op."""

    def __init__(self, service: str, config: dict | None = None):
        self.service = service
        self.config = config if config is not None else {}
        self._buf: deque[dict] = deque(maxlen=self._buffer_size())
        # slow spans survive fast-op churn in their own bounded ring
        self._slow: deque[dict] = deque(maxlen=64)
        # pending shipment to the mon (piggybacked on the daemon's
        # existing report loop); bounded — observability must never
        # become the memory leak it exists to find
        self._shipq: deque[bytes] = deque(maxlen=1024)

    # -- knobs -------------------------------------------------------------
    def _get(self, name: str, default):
        if name in self.config:
            return self.config[name]
        try:
            from ceph_tpu.utils.config import global_config
            return global_config().get(name)
        except Exception:
            return default

    def sampling_rate(self) -> float:
        return float(self._get("trace_sampling_rate", 0.0))

    def slow_keep_s(self) -> float:
        return float(self._get("trace_slow_keep_s", 30.0))

    def _buffer_size(self) -> int:
        return int(self._get("trace_buffer_size", 256))

    # -- span creation -----------------------------------------------------
    def start_root(self, name: str,
                   tags: dict | None = None) -> Span | None:
        """Root span for a NEW logical op. Head-sampled roots get a
        propagating trace id; unsampled roots are local-only (tail
        retention candidates); None when tracing is fully off
        (sampling 0 AND tail tracking disabled)."""
        rate = self.sampling_rate()
        if rate > 0.0 and random.random() < rate:
            return Span(self, name, new_trace_id(), tags=tags)
        if self.slow_keep_s() > 0.0:
            return Span(self, name, 0, tags=tags)
        return None

    def from_msg(self, name: str, msg,
                 tags: dict | None = None) -> Span | None:
        """Continue a propagated trace from an incoming message's
        appended context; None when the message is untraced."""
        tid = getattr(msg, "trace_id", 0)
        if not tid:
            return None
        return Span(self, name, tid,
                    parent_span_id=getattr(msg, "parent_span_id", 0),
                    tags=tags)

    # -- recording ---------------------------------------------------------
    def record(self, span: Span) -> None:
        slow = span.duration is not None and \
            0.0 < self.slow_keep_s() <= span.duration
        if span.trace_id == 0:
            if not slow:
                return                    # unsampled and fast: drop
            # tail retention: promote the local-only root so the mgr
            # can index it (children were never created — by design)
            span.trace_id = new_trace_id()
            span.tags["tail_sampled"] = True
        if slow:
            span.tags.setdefault("slow", True)
        d = span.dump()
        size = self._buffer_size()
        if size != self._buf.maxlen:      # knob changed at runtime
            self._buf = deque(self._buf, maxlen=size)
        self._buf.append(d)
        if slow:
            self._slow.append(d)
        self._shipq.append(json.dumps(d).encode())

    # -- surfaces ----------------------------------------------------------
    def drain_ship(self, max_n: int = 256) -> list[bytes]:
        """Spans awaiting shipment to the mon (destructive read)."""
        out = []
        while self._shipq and len(out) < max_n:
            out.append(self._shipq.popleft())
        return out

    def ship_pending(self) -> int:
        return len(self._shipq)

    def dump(self) -> dict:
        """The asok ``dump_tracing`` payload."""
        return {
            "service": self.service,
            "sampling_rate": self.sampling_rate(),
            "slow_keep_s": self.slow_keep_s(),
            "buffered": len(self._buf),
            "pending_ship": len(self._shipq),
            "spans": list(self._buf),
            "slow_spans": list(self._slow),
        }


class TraceIndex:
    """Cross-daemon trace reassembly by trace_id (the mgr
    TracingModule's — and the mon's `trace ls/show` — backing store).

    Bounded at ``max_traces`` complete trace groups; the oldest (by
    last span arrival) are evicted first."""

    # spans retained per trace: far above any real op tree (a
    # replicated write is ~10 spans), low enough that one hostile
    # trace_id cannot grow the index without bound
    MAX_SPANS_PER_TRACE = 256
    # tree depth served by show(): beyond it children are elided
    # rather than recursing toward Python's recursion limit
    MAX_TREE_DEPTH = 64

    def __init__(self, max_traces: int = 512):
        self.max_traces = max_traces
        # trace_id -> {"spans": {span_id: span-dict}, "stamp": wall}
        self.traces: "OrderedDict[int, dict]" = OrderedDict()

    def add(self, span: dict) -> None:
        # normalize BEFORE storing: span blobs arrive over the wire
        # from arbitrary clients (MTraceReport is an uncapped
        # fire-and-forget report), and one mistyped field must not
        # poison every later ls()/show() — malformed spans drop here
        try:
            tid = int(span.get("trace_id", 0))
            sid = int(span.get("span_id", 0))
            if not tid or not sid:
                return
            tags = span.get("tags")
            norm = {
                "trace_id": tid,
                "span_id": sid,
                "parent_span_id": int(span.get("parent_span_id", 0)),
                "name": str(span.get("name", "?")),
                "service": str(span.get("service", "?")),
                "start": float(span.get("start", 0.0)),
                "duration": float(span.get("duration", 0.0)),
                "tags": tags if isinstance(tags, dict) else {},
            }
        except (TypeError, ValueError):
            return
        ent = self.traces.get(tid)
        if ent is None:
            ent = self.traces[tid] = {"spans": {}, "stamp": 0.0}
        if sid not in ent["spans"] and \
                len(ent["spans"]) >= self.MAX_SPANS_PER_TRACE:
            return                    # one trace can't eat the index
        ent["spans"][sid] = norm
        ent["stamp"] = max(ent["stamp"], norm["start"])
        self.traces.move_to_end(tid)
        while len(self.traces) > self.max_traces:
            self.traces.popitem(last=False)

    # -- views -------------------------------------------------------------
    def _root(self, ent: dict) -> dict | None:
        spans = ent["spans"]
        ids = set(spans)
        roots = [s for s in spans.values()
                 if int(s.get("parent_span_id", 0)) not in ids]
        if not roots:
            return None
        # prefer the true root (no parent at all), else earliest start
        roots.sort(key=lambda s: (int(s.get("parent_span_id", 0)) != 0,
                                  s.get("start", 0.0)))
        return roots[0]

    def duration_of(self, tid: int) -> float:
        ent = self.traces.get(tid)
        if not ent:
            return 0.0
        root = self._root(ent)
        if root is not None and int(root.get("parent_span_id", 0)) == 0:
            return float(root.get("duration", 0.0))
        # partial trace: span envelope
        starts = [s["start"] for s in ent["spans"].values()]
        ends = [s["start"] + s.get("duration", 0.0)
                for s in ent["spans"].values()]
        return max(ends) - min(starts) if starts else 0.0

    def ls(self, limit: int = 20) -> list[dict]:
        """Slowest traces first (ref: the 'where did the latency go'
        entry point)."""
        rows = []
        for tid, ent in self.traces.items():
            root = self._root(ent)
            rows.append({
                "trace_id": tid,
                "root": root.get("name", "?") if root else "?",
                "service": root.get("service", "?") if root else "?",
                "duration": round(self.duration_of(tid), 6),
                "num_spans": len(ent["spans"]),
                "services": sorted({s.get("service", "?")
                                    for s in ent["spans"].values()}),
                "slow": any(s.get("tags", {}).get("slow")
                            for s in ent["spans"].values()),
            })
        rows.sort(key=lambda r: r["duration"], reverse=True)
        return rows[:limit]

    def show(self, tid: int) -> dict | None:
        """One reassembled trace: the span tree plus a per-phase
        latency breakdown (span name -> summed duration)."""
        ent = self.traces.get(tid)
        if ent is None:
            return None
        spans = ent["spans"]
        children: dict[int, list[int]] = {}
        for sid, s in spans.items():
            children.setdefault(
                int(s.get("parent_span_id", 0)), []).append(sid)
        root = self._root(ent)
        t0 = min(s["start"] for s in spans.values())

        def node(sid: int, depth: int = 0) -> dict:
            s = spans[sid]
            kids = sorted(children.get(sid, []),
                          key=lambda c: spans[c]["start"])
            return {
                "span_id": sid,
                "name": s.get("name"),
                "service": s.get("service"),
                "offset": round(s["start"] - t0, 6),
                "duration": round(s.get("duration", 0.0), 6),
                "tags": s.get("tags", {}),
                # depth-capped: a hostile parent chain must not drive
                # this recursion toward the interpreter limit
                "children": [node(c, depth + 1) for c in kids]
                if depth < self.MAX_TREE_DEPTH else ([{
                    "span_id": 0, "name": f"({len(kids)} elided)",
                    "service": "", "offset": 0.0, "duration": 0.0,
                    "tags": {}, "children": [],
                }] if kids else []),
            }

        phases: dict[str, float] = {}
        for s in spans.values():
            phases[s.get("name", "?")] = round(
                phases.get(s.get("name", "?"), 0.0) +
                s.get("duration", 0.0), 6)
        top = [sid for sid, s in spans.items()
               if int(s.get("parent_span_id", 0)) not in spans]
        return {
            "trace_id": tid,
            "duration": round(self.duration_of(tid), 6),
            "root": root.get("name") if root else None,
            "num_spans": len(spans),
            "phases": phases,
            "tree": [node(sid) for sid in sorted(
                top, key=lambda c: spans[c]["start"])],
        }
