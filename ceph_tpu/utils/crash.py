"""Crash capture: daemons' top-level task exception hook (round 14).

ref: src/global/signal_handler.cc + the ceph-crash/crash-module
pipeline — upstream daemons dump a crash metadata file on a fatal
signal and ``ceph-crash`` posts it to the mon, where `ceph crash ls`
and the RECENT_CRASH health warning surface it until acknowledged.

Here the failure mode worth catching is an asyncio one: every daemon
runs its long-lived loops (heartbeats, stats, admission, reporting) as
fire-and-forget tasks, and an uncaught exception in one of them kills
the loop SILENTLY — the daemon limps on half-alive, which is exactly
the gray failure the observability plane exists to expose.
:func:`watch` is the hook: wrap the task at spawn, and a non-cancel
death builds a BOUNDED crash report (exception, capped traceback,
daemon identity, wall time) and ships it monward as an
:class:`~ceph_tpu.mon.messages.MCrashReport` — fire-and-forget,
leader-forwarded like every other daemon report. The mon pools reports
in memory, serves ``ceph crash ls/info <id>`` (read-only cap class),
and raises RECENT_CRASH until ``ceph crash archive`` acks them.

A bounded process-local ring (:func:`recent_crashes`) keeps the same
reports for asok/debug reads even when no mon is reachable.
"""

from __future__ import annotations

import asyncio
import itertools
import time
import traceback
from collections import deque

from ceph_tpu.utils.logging import get_logger

log = get_logger("crash")

# hard caps: a crash report must never become the memory problem (or
# the giant frame) it exists to report
MAX_TRACEBACK = 4000
MAX_EXCEPTION = 400

_RECENT: deque = deque(maxlen=16)
_SEQ = itertools.count(1)


def build_report(daemon: str, exc: BaseException,
                 where: str = "") -> dict:
    """One bounded crash report dict. ``crash_id`` is unique per
    process (stamp + seq + daemon) — the mon keys its pool on it."""
    stamp = time.time()
    tb = "".join(traceback.format_exception(
        type(exc), exc, exc.__traceback__))
    return {
        "crash_id": f"{int(stamp)}.{next(_SEQ)}.{daemon}",
        "daemon": str(daemon),
        "where": str(where)[:120],
        "exception": repr(exc)[:MAX_EXCEPTION],
        "traceback": tb[-MAX_TRACEBACK:],
        "stamp": stamp,
    }


def recent_crashes() -> list[dict]:
    """The process-local ring (newest last) — the asok/debug view."""
    return list(_RECENT)


def watch(task: asyncio.Task, daemon: str, monc,
          where: str = "") -> asyncio.Task:
    """The top-level task exception hook: attach a done-callback that,
    when ``task`` dies with a real exception (cancellation is a normal
    stop, not a crash), records a bounded report locally and ships it
    monward via ``monc.send_report``. Returns ``task`` so spawn sites
    wrap in place:

        self._hb_task = crash.watch(
            asyncio.ensure_future(self._hb_loop()), name, self.monc,
            where="hb_loop")

    Shipping is itself fire-and-forget and exception-swallowed: crash
    reporting must never cascade a second failure into the daemon.
    """
    def _done(t: asyncio.Task) -> None:
        if t.cancelled():
            return
        try:
            exc = t.exception()
        except asyncio.CancelledError:       # pragma: no cover
            return
        if exc is None:
            return
        rep = build_report(daemon, exc, where=where)
        _RECENT.append(rep)
        log.dout(0, f"{daemon} task {where or '?'} crashed: "
                    f"{rep['exception']} (crash_id {rep['crash_id']})")
        if monc is None:
            return
        try:
            from ceph_tpu.mon.messages import MCrashReport
            asyncio.ensure_future(monc.send_report(MCrashReport(
                daemon=rep["daemon"], crash_id=rep["crash_id"],
                exception=rep["exception"],
                traceback=rep["traceback"], stamp=rep["stamp"])))
        except Exception:
            pass                 # never cascade out of the hook

    task.add_done_callback(_done)
    return task
