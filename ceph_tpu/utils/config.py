"""Layered typed configuration.

TPU-native analog of Ceph's option system (ref: src/common/options/*.yaml.in
-> Option structs; src/common/config.h md_config_t/ConfigProxy). Ceph resolves
each option through layered precedence:

    compiled default < conf file < mon config db < env < cli < runtime override

We keep the same precedence semantics with explicit named layers, a typed
``Option`` declaration table, and change-notification observers
(ref: src/common/config_obs.h md_config_obs_t). Option names keep their Ceph
spellings where an analog exists (``erasure_code_dir``,
``osd_pool_default_*``) for operator familiarity.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

# Layer precedence, low to high (ref: src/common/config.h CONF_DEFAULT..CONF_OVERRIDE).
LAYERS = ("default", "file", "mon", "env", "cmdline", "override")


@dataclass(frozen=True)
class Option:
    """One declared option (ref: src/common/options.h Option)."""

    name: str
    type: type  # int, float, str, bool
    default: Any
    doc: str = ""
    min: Any = None
    max: Any = None
    enum_allowed: tuple = ()
    runtime: bool = True  # may be changed after startup (flags: [runtime])

    def validate(self, value: Any) -> Any:
        if self.type is bool and isinstance(value, str):
            value = value.lower() in ("1", "true", "yes", "on")
        try:
            value = self.type(value)
        except (TypeError, ValueError) as e:
            raise ValueError(f"option {self.name}: cannot coerce {value!r} to "
                             f"{self.type.__name__}") from e
        if self.enum_allowed and value not in self.enum_allowed:
            raise ValueError(f"option {self.name}: {value!r} not in "
                             f"{self.enum_allowed}")
        if self.min is not None and value < self.min:
            raise ValueError(f"option {self.name}: {value!r} < min {self.min}")
        if self.max is not None and value > self.max:
            raise ValueError(f"option {self.name}: {value!r} > max {self.max}")
        return value


# The option schema. Names mirror Ceph's where analogous
# (ref: src/common/options/global.yaml.in, osd.yaml.in).
OPTIONS: dict[str, Option] = {o.name: o for o in [
    Option("erasure_code_dir", str, "",
           "directory for out-of-tree EC plugin shims (dlopen analog)"),
    Option("osd_pool_default_size", int, 3, "replica count", min=1),
    Option("osd_pool_default_min_size", int, 0, "min replicas to serve IO"),
    Option("osd_pool_default_pg_num", int, 32, "default pg_num", min=1),
    Option("osd_pool_default_crush_rule", int, -1, "default crush rule id"),
    Option("osd_pool_default_erasure_code_profile", str,
           "plugin=jax technique=reed_sol_van k=2 m=2",
           "default EC profile"),
    Option("mon_max_pg_per_osd", int, 250, "pg-per-osd health limit"),
    # pg-log / recovery / backfill (ref: osd.yaml.in osd_min_pg_log_entries,
    # osd_max_backfills, osd_recovery_max_active, osd_backfill_scan_*).
    Option("osd_min_pg_log_entries", int, 1000,
           "pg-log entries retained by trim; the log tail this leaves is "
           "the log-delta recovery horizon — peers older than it backfill",
           min=1),
    Option("osd_backfill", bool, True,
           "enable the backfill recovery mode (off reproduces the "
           "silent past-horizon under-replication the seed had)"),
    Option("osd_max_backfills", int, 1,
           "max concurrent backfills one OSD participates in, as "
           "primary (local reservations) or target (remote)", min=1),
    Option("osd_backfill_scan_max", int, 64,
           "objects per backfill scan batch", min=1),
    Option("osd_backfill_retry_interval", float, 0.5,
           "seconds between reservation retries (backfill_wait)"),
    Option("osd_recovery_max_active", int, 8,
           "max in-flight recovery/backfill pushes per OSD", min=1),
    Option("osd_recovery_max_bytes", int, 0,
           "recovery push budget in bytes/s (token bucket; 0 = "
           "unlimited) — deprioritizes recovery vs client I/O", min=0),
    Option("osd_backfill_full_ratio", float, 0.85,
           "refuse incoming backfills above this fraction of "
           "osd_capacity_bytes (backfill_toofull)"),
    Option("osd_capacity_bytes", int, 0,
           "advertised store capacity for fullness checks (0 = "
           "unlimited; the in-memory stores have no intrinsic size)",
           min=0),
    # overload protection (ref: global.yaml.in mon_osd_nearfull_ratio /
    # mon_osd_full_ratio, osd.yaml.in osd_failsafe_full_ratio,
    # osd_client_message_cap / osd_client_message_size_cap): the three
    # fullness lines of defense plus the client-op admission throttle.
    Option("mon_osd_nearfull_ratio", float, 0.85,
           "per-OSD used/capacity ratio raising OSD_NEARFULL health",
           min=0.0, max=1.0),
    Option("mon_osd_full_ratio", float, 0.95,
           "per-OSD ratio setting the cluster FULL flag: client "
           "writes park (or fail -ENOSPC with FULL_TRY)",
           min=0.0, max=1.0),
    Option("osd_failsafe_full_ratio", float, 0.97,
           "local statfs ratio above which the OSD rejects writes "
           "-ENOSPC at admission — the stale-map-proof last line of "
           "defense", min=0.0, max=1.0),
    Option("mon_osd_reporter_lifetime", float, 600.0,
           "seconds a failure reporter's accusation stays live; "
           "older reports expire on mon tick so stale accusations "
           "cannot sum to a markdown", min=0.0),
    Option("osd_pool_default_quota_max_bytes", int, 0,
           "default pool byte quota (0 = unlimited)", min=0),
    Option("osd_pool_default_quota_max_objects", int, 0,
           "default pool object quota (0 = unlimited)", min=0),
    Option("osd_client_message_cap", int, 256,
           "max in-flight client ops dispatched per OSD; excess ops "
           "queue at admission", min=0),
    Option("osd_client_message_size_cap", int, 500 << 20,
           "max aggregate in-flight client-op bytes per OSD", min=0),
    Option("osd_pg_op_queue_cap", int, 512,
           "per-PG op-queue depth past which the primary sends "
           "MOSDBackoff instead of queueing", min=1),
    # op QoS scheduler (round 11; ref: osd.yaml.in osd_op_queue +
    # osd_mclock_scheduler_client/background_* options): the
    # dmClock-analog admission scheduler and its per-class defaults.
    # Read LIVE by every OpScheduler, so a runtime flip applies to the
    # next dequeue decision.
    Option("osd_op_queue", str, "mclock",
           "op admission queue: mclock (dmClock-analog QoS tags) | "
           "fifo (the pre-scheduler baseline)",
           enum_allowed=("mclock", "fifo")),
    Option("osd_qos_default_reservation", float, 0.0,
           "default per-client reservation IOPS (0 = none) for "
           "queues without a client-profile or pool qos_* override",
           min=0.0),
    Option("osd_qos_default_weight", float, 1.0,
           "default per-client proportional weight", min=0.0),
    Option("osd_qos_default_limit", float, 0.0,
           "default per-client limit IOPS (0 = unlimited)", min=0.0),
    Option("osd_qos_recovery_reservation", float, 10.0,
           "recovery-class reservation IOPS — the floor that keeps "
           "recovery from starving under client load (PR 2's "
           "RecoveryThrottle folded into the scheduler)", min=0.0),
    Option("osd_qos_recovery_weight", float, 1.0,
           "recovery-class proportional weight", min=0.0),
    Option("osd_qos_recovery_limit", float, 0.0,
           "recovery-class limit IOPS (0 = unlimited)", min=0.0),
    Option("osd_qos_scrub_weight", float, 0.5,
           "scrub-class proportional weight (background best-effort)",
           min=0.0),
    Option("osd_qos_scrub_limit", float, 10.0,
           "scrub-class limit in scrub rounds/s (0 = unlimited)",
           min=0.0),
    Option("osd_qos_cost_per_io_bytes", int, 65536,
           "dmClock cost divisor: an op is charged "
           "max(1, bytes / this) tag units, so a 4 MiB writer pays "
           "its size honestly against 4 KiB writers instead of the "
           "flat per-op cost (doubly important once the EC "
           "aggregator makes many-small-writes cheap to encode)",
           min=1),
    # EC encode aggregator (round 13; the cross-op stripe-batch
    # coalescing layer in osd/ec_aggregator.py). Read LIVE per encode,
    # so osd_ec_agg=false flips a running OSD to the measured per-op
    # baseline path.
    Option("osd_ec_agg", bool, True,
           "coalesce concurrent EC stripe encodes from all PGs on "
           "this OSD into one padded batched kernel launch per flush "
           "window; false = the per-op-launch baseline path"),
    Option("osd_ec_agg_window_us", float, 500.0,
           "EC aggregator flush window in microseconds — the hard "
           "bound on how long a lone op's encode may wait for "
           "company", min=0.0),
    Option("osd_ec_agg_max_stripes", int, 4096,
           "stripes that force an immediate aggregator flush (the "
           "batch-size ceiling; also bounds the padded launch's "
           "memory)", min=1),
    # EC read/repair aggregator (round 19; the decode twin of the
    # round-13 encode aggregator, osd/ec_read_aggregator.py). Read
    # LIVE per decode, so osd_ec_read_agg=false flips a running OSD
    # to the measured per-op decode baseline.
    Option("osd_ec_read_agg", bool, True,
           "coalesce concurrent EC degraded-read / repair decodes "
           "from all PGs on this OSD into one padded batched decode "
           "launch per flush window; false = the per-op-launch "
           "baseline path"),
    Option("osd_ec_read_agg_window_us", float, 500.0,
           "EC read aggregator flush window in microseconds — the "
           "hard bound on how long a lone degraded read's decode may "
           "wait for company", min=0.0),
    Option("osd_ec_read_agg_max_stripes", int, 4096,
           "stripes that force an immediate read-aggregator flush "
           "(the decode batch-size ceiling; also bounds the padded "
           "launch's memory)", min=1),
    # hot-shard residency (round 19): bounded device-side cache of
    # gathered stripe batches so RMW and repeated degraded reads skip
    # the host gather + H2D leg; entries are version-keyed, so any
    # write to the object range makes the cached generation
    # unreachable (plus an explicit invalidate on apply).
    Option("osd_ec_resident_bytes", int, 64 << 20,
           "per-OSD byte budget for the device-resident hot-shard "
           "cache (LRU by PG/object range, version-keyed "
           "invalidation); 0 disables residency", min=0),
    Option("osd_qos_backlog_cap", int, 4096,
           "OSD-wide admission backlog bound across ALL tenants "
           "(per-tenant queues are capped by osd_pg_op_queue_cap; "
           "this bounds their sum so a many-tenant flood backs off "
           "instead of exhausting memory)", min=1),
    # gray-failure (slow-OSD) detection (round 11; ref: the
    # osd_network ping-time warnings mon_warn_on_slow_ping_time
    # gates): the mon's slow-score sweep over heartbeat-RTT reports.
    Option("mon_osd_slow_ratio", float, 3.0,
           "an OSD whose median reported heartbeat RTT exceeds the "
           "fleet median by this factor is slow-suspect", min=1.0),
    Option("mon_osd_slow_min_ms", float, 50.0,
           "absolute latency floor (ms) below which no OSD is ever "
           "marked slow — fast-cluster jitter must not trip OSD_SLOW",
           min=0.0),
    Option("mon_osd_slow_confirm", int, 2,
           "consecutive slow-score sweeps above threshold before "
           "OSD_SLOW trips (debounce)", min=1),
    Option("mon_osd_slow_primary_dampening", bool, False,
           "when an OSD trips OSD_SLOW, auto-dampen its primary "
           "affinity (the primary-avoidance hint); restored on heal. "
           "OFF by default"),
    Option("mon_osd_slow_primary_affinity", float, 0.0,
           "the affinity fraction a dampened slow OSD gets (0 = "
           "never primary while slow)", min=0.0, max=1.0),
    # MDS failover / metadata HA (ref: mds.yaml.in mds_beacon_interval,
    # mds_beacon_grace, mds_reconnect_timeout, mds_standby_replay,
    # mon_mds options in global.yaml.in): the MDSMonitor's beacon-grace
    # failover machinery and the daemon's ladder pacing.
    Option("mds_beacon_interval", float, 1.0,
           "seconds between MDSBeacons to the mon", min=0.01),
    Option("mds_beacon_grace", float, 5.0,
           "silent-daemon window before the MDSMonitor fails it "
           "(an active is blocklisted and a standby promoted)",
           min=0.1),
    Option("mds_reconnect_timeout", float, 2.0,
           "reconnect-window length: how long a promoted MDS waits "
           "for journaled sessions to re-claim their caps before "
           "dropping the stragglers", min=0.0),
    Option("mds_replay_interval", float, 0.25,
           "standby-replay journal/session-table tail poll period",
           min=0.01),
    Option("mds_standby_replay", bool, False,
           "keep one warm standby tailing the active's journal for "
           "faster takeover (costs a continuous poll)"),
    Option("mds_standby_count_wanted", int, 1,
           "standbys below which MDS_INSUFFICIENT_STANDBY warns",
           min=0),
    Option("mds_journal_max_entries", int, 64,
           "applied journal events kept resident before a batch trim "
           "(the segment-trim analog; gives standby-replay a real "
           "tail)", min=1),
    Option("mds_session_timeout", float, 10.0,
           "client cap-lease length advertised at session open",
           min=0.1),
    # snapshots (ref: osd.yaml.in osd_snap_trim_sleep / osd_pg_max_
    # concurrent_snap_trims, bluestore shared-blob machinery, mds
    # snapshot enablement): the snap subsystem's three layers.
    Option("bluestore_sharedblob_enabled", bool, True,
           "OP_CLONE shares the source's blobs (refcounted, zero data "
           "bytes move); false restores the seed's O(size) byte-copy "
           "clone"),
    Option("osd_snap_trim_batch", int, 16,
           "head objects trimmed per burst by the removed_snaps "
           "background trimmer before sleeping", min=1),
    Option("osd_snap_trim_sleep", float, 0.0,
           "seconds the background snap trimmer sleeps between "
           "bursts (0 = no pacing)", min=0.0),
    Option("mds_snap_enabled", bool, True,
           "serve .snap/<name> snapshot verbs (mksnap/rmsnap/readdir "
           "through a realm); false returns -EPERM like upstream's "
           "allow_new_snaps=false"),
    Option("mds_snap_max_per_realm", int, 100,
           "snapshots one directory may hold before mksnap -EMLINK",
           min=1),
    # multi-active metadata plane (round 7; ref: mds_bal_* options +
    # the Migrator's export sizing): the mon-side load rebalancer and
    # the two-phase subtree migration.
    Option("mds_bal_interval", float, 10.0,
           "seconds between rebalancer decisions on the mon tick "
           "(0 disables the load-based subtree rebalancer)", min=0.0),
    Option("mds_bal_ratio", float, 4.0,
           "hottest/coldest rank op-rate ratio past which a subtree "
           "migrates off the hot rank", min=1.0),
    Option("mds_bal_min_ops", float, 20.0,
           "op/s below which a rank is never considered overloaded "
           "(don't shuffle an idle filesystem)", min=0.0),
    Option("mds_migration_timeout", float, 10.0,
           "exporter-side pacing bound for one subtree handoff "
           "attempt", min=0.1),
    # elastic control plane (round 6; ref: mon.yaml.in mon options +
    # the pg_autoscaler module's threshold): runtime monmap
    # membership, AuthMonitor key lifecycle, LogMonitor retention and
    # the PG merge barrier.
    Option("mon_allow_pg_merge", bool, True,
           "accept pg_num decreases (two-phase merge through "
           "pg_num_pending); false reproduces the seed's "
           "grow-only autoscaler"),
    Option("autoscaler_shrink_threshold", int, 4,
           "pg_autoscaler proposes a merge when pg_num exceeds the "
           "recommendation by this factor (the over-split bar)",
           min=2),
    Option("mon_merge_ready_window", float, 2.0,
           "seconds a source PG's ready-to-merge report stays live; "
           "sources re-report every stats tick while ready, so a "
           "degraded source ages out of the barrier", min=0.5),
    Option("mon_log_max", int, 500,
           "cluster-log entries the LogMonitor retains (older are "
           "trimmed with each append)", min=10),
    Option("mon_auth_revoke_warn_s", float, 300.0,
           "seconds a revoked key stays in the AUTH_KEY_REVOKED "
           "health warning (the log keeps the permanent record)",
           min=0.0),
    Option("mon_election_timeout", float, 0.3,
           "election round length before victory/retry"),
    Option("mon_lease", float, 2.0,
           "peon lease length; expiry calls an election"),
    # CRUSH tunables defaults (jewel profile; ref: src/crush/CrushWrapper.h
    # set_tunables_jewel).
    Option("crush_choose_total_tries", int, 50, "descent retry budget"),
    Option("crush_choose_local_tries", int, 0, "local retries (legacy)"),
    Option("crush_choose_local_fallback_tries", int, 0,
           "local fallback retries (legacy)"),
    Option("crush_chooseleaf_descend_once", int, 1, "retry descent not leaf"),
    Option("crush_chooseleaf_vary_r", int, 1, "vary r on leaf recursion"),
    Option("crush_chooseleaf_stable", int, 1, "stable leaf mapping"),
    # op tracking + distributed tracing (ref: osd.yaml.in
    # osd_op_history_size / osd_op_complaint_time; the jaeger_tracing
    # options the reference gates src/common/tracer.cc behind). The
    # trace_* knobs are read live by every Tracer, so a runtime
    # override applies from the next op on.
    Option("osd_op_history_size", int, 20,
           "completed ops retained per OpTracker for "
           "dump_historic_ops", min=0),
    Option("osd_op_complaint_time", float, 30.0,
           "op age (monotonic seconds) past which an in-flight op "
           "counts as slow (SLOW_OPS)", min=0.0),
    Option("trace_sampling_rate", float, 0.0,
           "head-based sampling probability for distributed op "
           "traces: a sampled root's context propagates across every "
           "message hop of the op", min=0.0, max=1.0),
    Option("trace_slow_keep_s", float, 30.0,
           "tail-based retention: an UNSAMPLED op slower than this is "
           "kept anyway (local root span only), so SLOW_OPS stays "
           "drill-downable at sampling 0; <= 0 disables even the "
           "local timing (the fully-off path)"),
    Option("trace_buffer_size", int, 256,
           "completed spans retained per daemon for dump_tracing",
           min=8),
    # cluster telemetry plane (round 12; ref: mgr.yaml.in
    # mgr_stats_period + mon_mgr_beacon_grace): the daemon->mgr
    # perf-counter report sessions, the mgr's time-series retention,
    # and the MgrMap beacon/failover machinery. mgr_stats_period is
    # read LIVE by every reporter, so a runtime override applies from
    # the next period on.
    Option("mgr_stats_period", float, 0.5,
           "seconds between a daemon's MMgrReport value deltas to the "
           "active mgr (0 disables reporting entirely — the bench "
           "section's off leg)", min=0.0),
    Option("mgr_stats_retention", int, 120,
           "report samples retained per monotonic counter in the "
           "mgr's DaemonStateIndex ring (the rate-query window)",
           min=2),
    Option("mgr_stats_schema_refresh", int, 20,
           "reports between periodic schema re-sends — re-seeds a "
           "session the mgr's TTL cull dropped while the daemon's "
           "reports were merely delayed (the one-way-channel analog "
           "of reconnect-resends-schema)", min=1),
    Option("mgr_stats_stale_s", float, 10.0,
           "seconds without a report before a daemon is culled from "
           "the DaemonStateIndex (dead daemons unpin by TTL, not "
           "conn reset — a transparent TCP reconnect must not wipe "
           "live state)", min=0.5),
    Option("mgr_stats_singleton_fallback", bool, True,
           "render /metrics from the process-local "
           "PerfCountersCollection when NO daemon has a report "
           "session (the standalone/no-mgr fallback); false = "
           "reported state only"),
    Option("mgr_beacon_interval", float, 0.5,
           "seconds between MMgrBeacons to the mon", min=0.01),
    Option("mgr_beacon_grace", float, 4.0,
           "silent-mgr window before the MgrMonitor fails it (a "
           "silent active is dropped and a standby promoted in the "
           "same commit)", min=0.1),
    Option("mgr_progress_interval", float, 1.0,
           "ProgressModule tick period (event derivation + the "
           "monward digest)", min=0.05),
    Option("mgr_progress_max_events", int, 64,
           "recently-completed progress events retained for "
           "`ceph progress json`", min=1),
    # self-driving tuner (round 17; mgr/tuner.py TunerModule + the
    # mon's tune audit/ownership pool in mon/tune.py). The mgr_tuner_*
    # knobs are read LIVE every tick, so mode/threshold flips apply
    # to the next evaluation without a mgr restart.
    Option("mgr_tuner_interval", float, 1.0,
           "TunerModule tick period (sensor evaluation + guardrailed "
           "actuation)", min=0.05),
    Option("mgr_tuner_mode", str, "observe",
           "the tuner's mode ladder: 'off' evaluates nothing, "
           "'observe' (the safe default) logs would-be actions to "
           "`ceph tune log` without committing, 'drive' (opt-in) "
           "commits them through the mon command paths",
           enum_allowed=("off", "observe", "drive")),
    Option("mgr_tuner_act_ticks", int, 3,
           "hysteresis: consecutive breaching ticks before a policy's "
           "action becomes eligible (a flapping sensor commits "
           "nothing)", min=1),
    Option("mgr_tuner_revert_ticks", int, 5,
           "hysteresis: consecutive clean ticks before a policy's "
           "revert becomes eligible", min=1),
    Option("mgr_tuner_max_changes_per_tick", int, 2,
           "cluster-wide change budget per tick; eligible proposals "
           "past it DEFER to the next tick (streaks retained) rather "
           "than drop", min=1),
    Option("mgr_tuner_qos_floor_ms", float, 250.0,
           "the client p99 QoS floor (ms) the recovery governor "
           "protects: p99 above it scales recovery down, p99 under "
           "the headroom fraction of it lets pending backfill scale "
           "recovery up", min=1.0),
    Option("mgr_tuner_headroom_frac", float, 0.5,
           "fraction of the QoS floor p99 must stay UNDER to count "
           "as headroom for scaling recovery up", min=0.01, max=1.0),
    Option("mgr_tuner_recovery_max_active_cap", int, 32,
           "ceiling the recovery governor may scale "
           "osd_recovery_max_active up to", min=1),
    Option("mgr_tuner_hot_pool_ratio", float, 4.0,
           "hot-pool protector trip: a pool whose op rate exceeds "
           "this multiple of the busiest OTHER pool's is the "
           "aggressor", min=1.0),
    Option("mgr_tuner_hot_pool_min_ops", float, 50.0,
           "absolute op-rate floor (ops/s) below which no pool can "
           "trip the hot-pool protector (idle-cluster noise "
           "immunity)", min=0.0),
    Option("mgr_tuner_hot_limit_frac", float, 0.5,
           "the tightened client-profile qos_limit as a fraction of "
           "the aggressor's observed op rate", min=0.01, max=1.0),
    Option("mgr_tuner_hot_weight", float, 0.5,
           "the tightened client-profile dmClock weight committed on "
           "an aggressor entity", min=0.01),
    Option("mgr_tuner_affinity", float, 0.0,
           "the dampened primary affinity the gray-OSD responder and "
           "kernel-path watchdog commit (0 = never primary)",
           min=0.0, max=1.0),
    Option("mon_tune_audit_max", int, 256,
           "bounded length of the mon's tuner audit ring "
           "(`ceph tune log`)", min=8),
    Option("mon_tune_affinity_lease_s", float, 600.0,
           "how long a tuner-committed primary-affinity lease defers "
           "the mon's own slow-OSD dampening sweep; expired leases "
           "return the OSD to the sweep", min=1.0),
    # device-runtime observability plane (round 14; the devmon layer
    # in utils/devmon.py + the mon's KERNEL_PATH_DEGRADED sweep).
    # devmon_expected_engine is read LIVE per sweep check, the
    # mon_kernel_path_* knobs live per report.
    Option("devmon_expected_engine", str, "auto",
           "the kernel engine this daemon is EXPECTED to serve CRUSH "
           "sweeps with: 'auto' trusts the built plan (a mismatch "
           "then means a plan silently degraded mid-run); pinning "
           "'pallas' makes every non-kernel sweep a counted — and "
           "health-checked — mismatch (the deployment contract for "
           "production TPU daemons)",
           enum_allowed=("auto", "pallas", "xla", "scalar")),
    Option("mon_kernel_path_degraded_ratio", float, 0.1,
           "per-report mismatch/checks ratio at or above which a "
           "daemon's kernel path counts as degraded for the "
           "KERNEL_PATH_DEGRADED debounce",
           min=0.0, max=1.0),
    Option("mon_kernel_path_confirm", int, 2,
           "consecutive degraded device-health reports before "
           "KERNEL_PATH_DEGRADED trips for a daemon (and clean "
           "reports before it clears) — the OSD_SLOW debounce "
           "discipline", min=1),
    # device-fault resilience plane (round 16): the CRUSH kernel
    # quarantine/re-probe state machine (crush/mapper.py) and the EC
    # aggregator's degrade ladder (osd/ec_aggregator.py). All read
    # LIVE from cluster config — a running cluster can be retuned.
    Option("crush_kernel_reprobe_base", float, 0.5,
           "seconds before the FIRST re-probe after a kernel-path "
           "execution failure quarantines it; doubles per "
           "consecutive failure (capped by crush_kernel_reprobe_max)",
           min=0.0),
    Option("crush_kernel_reprobe_max", float, 30.0,
           "backoff ceiling for kernel quarantine re-probes",
           min=0.0),
    Option("crush_kernel_reprobe_disable_after", int, 5,
           "consecutive kernel failures (initial + failed probes) "
           "after which the quarantine goes PERMANENT — the kernel "
           "path stays retired until the daemon restarts", min=1),
    Option("osd_ec_fallback_retries", int, 1,
           "per-op device encode retries after a failed aggregator "
           "batch before the op is served from the host-only "
           "reference encoder", min=0),
    Option("osd_ec_fallback_quarantine_base", float, 1.0,
           "seconds the fused encode+CRC jit path rests after a "
           "failure before being retried; doubles per consecutive "
           "failure", min=0.0),
    Option("osd_ec_fallback_quarantine_max", float, 30.0,
           "backoff ceiling for the fused encode+CRC rest window",
           min=0.0),
    # mesh provenance (round 15, ROADMAP #1d first slice): where a
    # production daemon's device mesh comes from. Read once at OSD
    # boot — the tracked mapping table re-attaches the mesh on every
    # update, so the knob governs provenance, not per-sweep routing.
    Option("osd_crush_mesh", str, "off",
           "attach a device mesh to this OSD's tracked mapping table "
           "at boot so full-pool CRUSH sweeps run mesh-sharded "
           "without hand-wiring: 'auto' builds the local default "
           "mesh over all visible devices when more than one is "
           "visible (a single device keeps the plain path); 'off' "
           "never attaches one",
           enum_allowed=("off", "auto")),
    # multi-process cluster backend (round 18; cluster/proc.py
    # supervisor + the mon central config db in mon/service.py). The
    # proc_* knobs govern the parent-side supervisor and are read at
    # spawn/stop time; mon_config_strict is read LIVE per `config set`.
    Option("proc_restart_backoff_base", float, 0.3,
           "seconds before the FIRST respawn after a proc-backend "
           "daemon crashes (exits without being asked to stop); "
           "doubles per consecutive crash", min=0.0),
    Option("proc_restart_backoff_max", float, 5.0,
           "backoff ceiling for crash respawns", min=0.0),
    Option("proc_stop_timeout", float, 10.0,
           "seconds a graceful stop (SIGTERM -> stop(mark_down=True)) "
           "may take before the supervisor escalates to SIGKILL",
           min=0.1),
    Option("mon_config_strict", bool, False,
           "when true, `ceph config set` rejects names that are not "
           "registered Options instead of storing them as raw "
           "strings"),
    # TPU execution knobs (no Ceph analog).
    Option("tpu_ec_backend", str, "auto",
           "GF kernel: bitmatmul (MXU) | lut (VPU) | auto",
           enum_allowed=("bitmatmul", "lut", "auto")),
    Option("tpu_block_bytes", int, 1 << 20,
           "per-step chunk-bytes tile for streaming encodes", min=4096),
    Option("tpu_mesh_axes", str, "batch", "mesh axis names, comma-separated"),
    Option("debug_default_level", int, 0, "default log gate level"),
]}


class Config:
    """Layered option store with observer notification."""

    def __init__(self, options: dict[str, Option] | None = None):
        self._options = dict(options or OPTIONS)
        self._layers: dict[str, dict[str, Any]] = {name: {} for name in LAYERS}
        self._observers: list[Callable[[str, Any], None]] = []

    # -- declaration ------------------------------------------------------
    def declare(self, option: Option) -> None:
        self._options[option.name] = option

    # -- resolution -------------------------------------------------------
    def get(self, name: str) -> Any:
        opt = self._options[name]
        for layer in reversed(LAYERS):
            if name in self._layers[layer]:
                return self._layers[layer][name]
        return opt.default

    def __getitem__(self, name: str) -> Any:
        return self.get(name)

    def set(self, name: str, value: Any, layer: str = "override") -> None:
        if layer not in self._layers:
            raise KeyError(f"unknown config layer {layer!r}")
        opt = self._options.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name!r}")
        if layer == "override" and not opt.runtime:
            raise ValueError(f"option {name} is not runtime-changeable "
                             f"(flags: [runtime] absent)")
        value = opt.validate(value)
        old = self.get(name)
        self._layers[layer][name] = value
        if self.get(name) != old:
            for obs in self._observers:
                obs(name, self.get(name))

    def rm(self, name: str, layer: str) -> None:
        old = self.get(name)
        self._layers[layer].pop(name, None)
        new = self.get(name)
        if new != old:
            for obs in self._observers:
                obs(name, new)

    # -- bulk ingestion ---------------------------------------------------
    def load_file(self, path: str) -> None:
        """Load a JSON conf file into the 'file' layer."""
        with open(path) as f:
            for k, v in json.load(f).items():
                self.set(k, v, layer="file")

    def load_env(self, prefix: str = "CEPH_TPU_") -> None:
        for k, v in os.environ.items():
            if k.startswith(prefix):
                name = k[len(prefix):].lower()
                if name in self._options:
                    self.set(name, v, layer="env")

    def apply_cmdline(self, pairs: Iterable[str]) -> None:
        """Apply ``name=value`` strings (the benchmark CLI --parameter style)."""
        for pair in pairs:
            name, _, value = pair.partition("=")
            self.set(name.strip(), value.strip(), layer="cmdline")

    # -- observation ------------------------------------------------------
    def add_observer(self, fn: Callable[[str, Any], None]) -> None:
        self._observers.append(fn)

    def show(self) -> dict[str, Any]:
        return {name: self.get(name) for name in sorted(self._options)}


@dataclass
class ConfigProxy:
    """Process-wide config handle (ref: src/common/config_proxy.h)."""

    config: Config = field(default_factory=Config)

    def __getattr__(self, name):
        return getattr(self.config, name)


_global: Config | None = None


def global_config() -> Config:
    """The per-process config (ref: src/common/ceph_context.h CephContext)."""
    global _global
    if _global is None:
        cfg = Config()
        cfg.load_env()  # raises on malformed CEPH_TPU_* before caching
        _global = cfg
    return _global


_ABSENT = object()       # live.get sentinel: absent != stored None


def apply_mon_config(entity: str, cfgmap: dict, live: dict,
                     state: dict, mirror_global: bool = False) -> list[str]:
    """Apply a mon-published config map into a daemon's live config.

    ``cfgmap`` is ``{who: {name: raw-str}}`` with who = global |
    <type> | <type>.<id>; resolution is most-specific wins, the same
    mask walk as ConfigMonitor.resolve. ``live`` is the daemon's
    runtime config dict (shared cluster-wide on the in-process
    backend, private per child on the proc backend). ``state`` is a
    per-daemon dict remembering each applied key's pre-map baseline so
    a key that later leaves the map (`config rm`) restores what the
    daemon booted with instead of leaving the override stuck.

    Registered Options are validated/coerced to their declared type;
    unknown names apply as raw strings (same leniency as the mon-side
    live push). Invalid values are skipped, never raised — a bad
    central value must not kill a daemon. With ``mirror_global`` the
    registered names are also mirrored into the per-process
    :func:`global_config` "mon" layer (the proc-backend children's
    Config runtime layer). Returns the names whose live value changed.
    """
    dtype = entity.split(".", 1)[0]
    resolved: dict[str, str] = {}
    for scope in ("global", dtype, entity):
        for name, raw in (cfgmap.get(scope) or {}).items():
            resolved[name] = raw
    baselines: dict[str, tuple[bool, Any]] = state.setdefault(
        "baseline", {})
    changed: list[str] = []
    gcfg = global_config() if mirror_global else None
    for name in [n for n in baselines if n not in resolved]:
        had, old = baselines.pop(name)
        if had:
            if live.get(name) != old or name not in live:
                changed.append(name)
            live[name] = old
        else:
            if name in live:
                changed.append(name)
            live.pop(name, None)
        if gcfg is not None and name in gcfg._options:
            gcfg.rm(name, layer="mon")
    for name, raw in resolved.items():
        opt = OPTIONS.get(name)
        try:
            value = opt.validate(raw) if opt is not None else raw
        except (ValueError, TypeError):
            continue
        # record the pre-map baseline once — and only when this apply
        # actually changes the value. On the in-process backend every
        # daemon shares ONE live dict, so a later applier would
        # otherwise snapshot the already-mutated value as "previous"
        # and a config rm would restore the override instead of the
        # boot value.
        if name not in baselines and live.get(name, _ABSENT) != value:
            baselines[name] = (name in live, live.get(name))
        if name not in live or live.get(name) != value:
            live[name] = value
            changed.append(name)
        if gcfg is not None and name in gcfg._options:
            try:
                gcfg.set(name, value, layer="mon")
            except (ValueError, KeyError):
                pass
    return changed
