"""Physical performance bounds per device — the honesty guard for benchmarks.

Round 1's headline number (9,317 GiB/s) was physically impossible on the
v5e chip this environment provides; the timing loop measured dispatch, not
execution (this platform's ``block_until_ready`` returns before the device
runs). Every benchmark now (a) anchors timing with a device-side reduction
read back to host, and (b) passes its result through :func:`check`, which
refuses to report a rate above the device's roofline.

Bounds are deliberately *optimistic* (best-case fusion, minimum possible
HBM traffic): a measurement above them is certainly wrong; a measurement
below them is not thereby certified, just possible.

ref: the reference harness (src/test/erasure-code/ceph_erasure_code_benchmark.cc
ErasureCodeBench::run) has no such guard because wall-clock timing of a
synchronous C++ loop cannot overshoot; an async remote device can.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class DeviceSpec:
    name: str
    hbm_bytes_per_s: float      # peak HBM bandwidth
    int8_macs_per_s: float      # peak MXU int8 multiply-accumulates/s
    hbm_bytes: float            # capacity


# Known TPU generations (public figures). int8 MACs = OPS/2.
_SPECS = {
    "TPU v5 lite": DeviceSpec("TPU v5e", 819e9, 394e12 / 2, 16 * 2**30),
    "TPU v5e": DeviceSpec("TPU v5e", 819e9, 394e12 / 2, 16 * 2**30),
    "TPU v5": DeviceSpec("TPU v5p", 2765e9, 918e12 / 2, 95 * 2**30),
    "TPU v4": DeviceSpec("TPU v4", 1228e9, 275e12 / 2, 32 * 2**30),
    "TPU v6 lite": DeviceSpec("TPU v6e", 1640e9, 1836e12 / 2, 32 * 2**30),
}


def device_spec(device_kind: str | None = None) -> DeviceSpec | None:
    """Spec for the current (or named) device; None when unknown (e.g. CPU
    — no guard is applied there, wall-clock on CPU is synchronous)."""
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    for prefix, spec in _SPECS.items():
        if device_kind.startswith(prefix):
            return spec
    return None


def encode_bound(k: int, m: int, spec: DeviceSpec) -> float:
    """Upper bound on encode *input* bytes/s for the (8m)x(8k) bit-matmul.

    HBM: minimum traffic per input byte is 1 (read data) + m/k (write
    parity); everything else could in principle stay in VMEM.
    MXU: the bit-plane product does (8m)*(8k) MACs per k input bytes
    = 64*m MACs per input byte.
    """
    hbm = spec.hbm_bytes_per_s / (1.0 + m / k)
    mxu = spec.int8_macs_per_s / (64.0 * m)
    return min(hbm, mxu)


def decode_bound(n_erased: int, n_read: int, spec: DeviceSpec) -> float:
    """Upper bound on decode *read* bytes/s (the benchmark's headline
    decode unit: chunk bytes actually read).

    The decode kernel is an (8*n_erased) x (8*n_read) bit-matmul over the
    read planes: 64*n_erased MACs per read byte; minimum HBM traffic per
    read byte is 1 (read) + n_erased/n_read (write reconstructions).
    """
    n_erased = max(n_erased, 1)
    hbm = spec.hbm_bytes_per_s / (1.0 + n_erased / n_read)
    mxu = spec.int8_macs_per_s / (64.0 * n_erased)
    return min(hbm, mxu)


def mfu(k: int, m: int, input_bytes_per_s: float, spec: DeviceSpec) -> float:
    """Fraction of MXU int8 peak the measured encode rate implies."""
    macs = 64.0 * m * input_bytes_per_s
    return macs / spec.int8_macs_per_s


class RooflineViolation(RuntimeError):
    pass


def check(measured_bytes_per_s: float, bound_bytes_per_s: float | None,
          what: str = "throughput") -> None:
    """Refuse to report a physically impossible number."""
    if bound_bytes_per_s is None:
        return
    if measured_bytes_per_s > bound_bytes_per_s * 1.02:  # 2% timer slack
        raise RooflineViolation(
            f"measured {what} {measured_bytes_per_s / 2**30:.1f} GiB/s exceeds "
            f"the device roofline {bound_bytes_per_s / 2**30:.1f} GiB/s — the "
            f"timing loop is not measuring execution; refusing to report it")
