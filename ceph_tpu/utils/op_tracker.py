"""TrackedOp / OpTracker: per-op event timelines.

ref: src/common/TrackedOp.{h,cc} — every client op gets a tracked
record with timestamped lifecycle events; in-flight ops and a bounded
history are dumpable via the admin socket (``dump_ops_in_flight`` /
``dump_historic_ops``), and ops older than the warn threshold are
counted as slow (ref: OpTracker::check_ops_in_flight).

Ages and event offsets are measured on ``time.monotonic()`` — a
wall-clock jump (NTP step, suspend) must not age every in-flight op
into a SLOW_OPS storm or make durations run backwards. One wall-clock
``initiated_at`` stamp is kept for display only. Defaults for the
history depth and the complaint threshold come from the registered
``osd_op_history_size`` / ``osd_op_complaint_time`` config options.
"""

from __future__ import annotations

import time
from collections import deque


def _opt_default(name: str, fallback):
    try:
        from ceph_tpu.utils.config import global_config
        return global_config().get(name)
    except Exception:
        return fallback


class TrackedOp:
    def __init__(self, tracker: "OpTracker", desc: str):
        self._tracker = tracker
        self.desc = desc
        self.initiated_at = time.time()      # wall clock, display only
        self.start = time.monotonic()        # all durations hang off this
        self.events: list[tuple[float, str]] = [(self.start, "queued")]
        self.done = False

    def mark_event(self, name: str) -> None:
        self.events.append((time.monotonic(), name))

    def finish(self) -> None:
        if not self.done:
            self.done = True
            self.mark_event("done")
            self._tracker._finish(self)

    @property
    def duration(self) -> float:
        end = self.events[-1][0] if self.done else time.monotonic()
        return end - self.start

    def dump(self) -> dict:
        return {
            "description": self.desc,
            "initiated_at": self.initiated_at,
            "age": round(self.duration, 6),
            "events": [{"time": round(t - self.start, 6), "event": e}
                       for t, e in self.events],
        }


class OpTracker:
    """ref: OpTracker — per-daemon registry."""

    def __init__(self, history_size: int | None = None,
                 slow_op_warn_s: float | None = None):
        if history_size is None:
            history_size = int(_opt_default("osd_op_history_size", 20))
        if slow_op_warn_s is None:
            slow_op_warn_s = float(
                _opt_default("osd_op_complaint_time", 30.0))
        self.inflight: dict[int, TrackedOp] = {}
        self.history: deque[TrackedOp] = deque(maxlen=history_size)
        self.slow_op_warn_s = slow_op_warn_s
        self._seq = 0

    def create(self, desc: str) -> TrackedOp:
        self._seq += 1
        op = TrackedOp(self, desc)
        op._seq = self._seq
        self.inflight[self._seq] = op
        return op

    def _finish(self, op: TrackedOp) -> None:
        self.inflight.pop(getattr(op, "_seq", -1), None)
        self.history.append(op)

    def dump_ops_in_flight(self) -> dict:
        """ref: admin socket dump_ops_in_flight."""
        return {"num_ops": len(self.inflight),
                "ops": [op.dump() for op in self.inflight.values()]}

    def dump_historic_ops(self) -> dict:
        """ref: admin socket dump_historic_ops (slowest-last order)."""
        return {"num_ops": len(self.history),
                "ops": [op.dump() for op in self.history]}

    def slow_ops(self) -> list[TrackedOp]:
        return [op for op in self.inflight.values()
                if op.duration > self.slow_op_warn_s]

    def dump_slow_ops(self) -> dict:
        """ref: admin socket dump_slow_ops — the in-flight ops past
        the complaint threshold (what the SLOW_OPS health warning and
        the mon's slow-op count are built from)."""
        ops = sorted(self.slow_ops(), key=lambda o: o.start)
        return {"num_slow_ops": len(ops),
                "complaint_time": self.slow_op_warn_s,
                "ops": [op.dump() for op in ops]}
