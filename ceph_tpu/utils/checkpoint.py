"""Checkpoint/resume for long placement sweeps.

TPU-native analog of the reference's resumability machinery (SURVEY.md
§5.4: PG log / mon store let interrupted work resume): a 100M-PG sweep's
driver state is tiny — the crushmap (as compiler text), the sweep config,
the PG cursor, and the partial count vector — so a JSON+npz pair with
atomic rename gives crash-safe resume. Deterministic re-derivation does
the rest: CRUSH is a pure function, so resuming from the cursor
reproduces exactly the counts an uninterrupted run would have produced.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

import numpy as np


@dataclass
class SweepState:
    """Resumable aggregated-sweep progress."""

    crushmap_text: str
    rule: int
    num_rep: int
    n_total: int
    cursor: int = 0                      # PGs fully aggregated so far
    bad: int = 0
    counts: np.ndarray = field(default_factory=lambda: np.zeros(0))
    weights_digest: str = ""             # device reweights affect placement

    def save(self, path: str) -> None:
        """ONE file, one atomic rename: counts and cursor must move
        together or a crash between two renames double-counts a chunk
        on resume."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"crushmap_text": self.crushmap_text,
                       "rule": self.rule, "num_rep": self.num_rep,
                       "n_total": self.n_total, "cursor": self.cursor,
                       "bad": self.bad,
                       "weights_digest": self.weights_digest,
                       "counts": np.asarray(self.counts,
                                            dtype=np.int64).tolist()}, f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "SweepState | None":
        if not os.path.exists(path):
            return None
        with open(path) as f:
            d = json.load(f)
        return cls(crushmap_text=d["crushmap_text"], rule=d["rule"],
                   num_rep=d["num_rep"], n_total=d["n_total"],
                   cursor=d["cursor"], bad=d["bad"],
                   counts=np.asarray(d["counts"], dtype=np.int64),
                   weights_digest=d.get("weights_digest", ""))


def resumable_sweep(crush_map, rule: int, n: int, num_rep: int,
                    ckpt_path: str, chunk: int = 1 << 22,
                    mapper=None, max_chunks: int | None = None):
    """Aggregated sweep of n PGs with checkpoint-per-chunk.

    Restarting with the same ckpt_path resumes at the saved cursor; the
    crushmap text in the checkpoint must match (a changed map invalidates
    the partial counts — placement is a pure function of the map).
    max_chunks limits work per call (None = run to completion).
    Returns (state, done).
    """
    import hashlib

    from ceph_tpu.crush.compiler import decompile_crushmap
    from ceph_tpu.crush.mapper import Mapper

    text = decompile_crushmap(crush_map)
    if mapper is None:
        mapper = Mapper(crush_map)
    # reweights (is_out vector) change placement without changing the
    # crushmap text — they are part of the sweep's identity
    digest = hashlib.sha256(
        np.asarray(mapper.arrays["device_weights"]).tobytes()).hexdigest()
    state = SweepState.load(ckpt_path)
    if state is not None:
        if (state.crushmap_text != text or state.rule != rule or
                state.num_rep != num_rep or state.n_total != n or
                state.weights_digest != digest):
            raise ValueError(
                f"checkpoint {ckpt_path} belongs to a different sweep "
                f"(map/rule/num_rep/n/reweights changed); delete it to "
                f"restart")
    else:
        state = SweepState(crushmap_text=text, rule=rule,
                           num_rep=num_rep, n_total=n,
                           weights_digest=digest)
    if state.counts.size == 0:
        state.counts = np.zeros(mapper.packed.max_devices, dtype=np.int64)
    chunks_run = 0
    while state.cursor < n:
        if max_chunks is not None and chunks_run >= max_chunks:
            break
        step = min(chunk, n - state.cursor)
        counts, bad = mapper.sweep(rule, state.cursor, step, num_rep)
        state.counts = state.counts + np.asarray(counts)
        state.bad += int(bad)
        state.cursor += step
        state.save(ckpt_path)
        chunks_run += 1
    return state, state.cursor >= n
