"""In-process typed perf counters.

TPU-native analog of Ceph's PerfCounters (ref: src/common/perf_counters.h
PerfCountersBuilder / PerfCounters). Same counter taxonomy — u64 counters,
time sums, and (count, sum) averages — registered through a builder and dumped
as JSON, standing in for ``ceph daemon <id> perf dump`` over the admin socket
(ref: src/common/admin_socket.cc). Histograms use fixed log2 buckets.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict

TYPE_U64 = "u64"          # PERFCOUNTER_U64
TYPE_TIME = "time"        # PERFCOUNTER_TIME
TYPE_LONGRUNAVG = "avg"   # PERFCOUNTER_LONGRUNAVG
TYPE_HISTOGRAM = "hist"   # PERFCOUNTER_HISTOGRAM


@dataclass
class _Counter:
    type: str
    doc: str = ""
    value: float = 0
    count: int = 0
    sum: float = 0.0
    monotonic: bool = False  # add_u64_counter (inc-only) vs add_u64 (gauge)
    buckets: list = field(default_factory=lambda: [0] * 64)


class PerfCounters:
    def __init__(self, name: str):
        self.name = name
        self._counters: Dict[str, _Counter] = {}
        self._lock = threading.Lock()

    def _get(self, key: str, expected: str) -> _Counter:
        c = self._counters[key]
        if c.type != expected:
            raise TypeError(f"counter {key} is {c.type}, not {expected}")
        return c

    def inc(self, key: str, amount: int = 1) -> None:
        with self._lock:
            self._get(key, TYPE_U64).value += amount

    def set(self, key: str, value: float) -> None:
        with self._lock:
            c = self._get(key, TYPE_U64)
            if c.monotonic:
                raise TypeError(f"counter {key} is monotonic (add_u64_"
                                f"counter); use inc(), not set()")
            c.value = value

    def tinc(self, key: str, seconds: float) -> None:
        with self._lock:
            self._get(key, TYPE_TIME).value += seconds

    def avg_add(self, key: str, value: float) -> None:
        with self._lock:
            c = self._get(key, TYPE_LONGRUNAVG)
            c.count += 1
            c.sum += value

    def hist_add(self, key: str, value: float) -> None:
        with self._lock:
            c = self._get(key, TYPE_HISTOGRAM)
            bucket = min(63, max(0, int(value).bit_length()))
            c.buckets[bucket] += 1
            c.count += 1
            c.sum += value

    class _Timer:
        def __init__(self, pc: "PerfCounters", key: str):
            self.pc, self.key = pc, key

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.pc.tinc(self.key, time.perf_counter() - self.t0)

    def timer(self, key: str) -> "_Timer":
        return self._Timer(self, key)

    def dump(self) -> dict:
        """``perf dump`` analog."""
        with self._lock:
            out = {}
            for key, c in self._counters.items():
                if c.type == TYPE_U64:
                    out[key] = int(c.value)
                elif c.type == TYPE_TIME:
                    out[key] = c.value
                elif c.type == TYPE_LONGRUNAVG:
                    out[key] = {"avgcount": c.count, "sum": c.sum}
                else:
                    out[key] = {"count": c.count, "sum": c.sum,
                                "log2_buckets": [b for b in c.buckets]}
            return out

    def dump_json(self) -> str:
        return json.dumps({self.name: self.dump()}, indent=2)


def hist_cumulative(buckets: list) -> list[tuple[float, int]]:
    """Render log2 buckets as cumulative prometheus-style ``le``
    pairs: bucket i counts values v with int(v).bit_length() == i,
    i.e. v < 2**i — so the cumulative count through bucket i is the
    count of observations <= (2**i - 1), and 2**i is a valid inclusive
    upper bound. Returns [(le, cumulative_count), ...] up to the
    highest non-empty bucket (always at least one pair), monotone by
    construction."""
    top = 0
    for i, b in enumerate(buckets):
        if b:
            top = i
    out: list[tuple[float, int]] = []
    run = 0
    for i in range(top + 1):
        run += int(buckets[i])
        out.append((float(2 ** i), run))
    return out


class PerfCountersCollection:
    """Process-wide registry of PerfCounters instances, the analog of
    CephContext's collection behind ``perf dump``
    (ref: src/common/perf_counters_collection.h PerfCountersCollection)."""

    _instance: "PerfCountersCollection | None" = None

    def __init__(self) -> None:
        self._loggers: Dict[str, PerfCounters] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "PerfCountersCollection":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def add(self, pc: PerfCounters) -> None:
        with self._lock:
            self._loggers[pc.name] = pc

    def remove(self, pc: PerfCounters) -> None:
        with self._lock:
            self._loggers.pop(pc.name, None)

    def get(self, name: str) -> PerfCounters | None:
        with self._lock:
            return self._loggers.get(name)

    def dump(self) -> dict:
        """Cluster-of-one ``perf dump``: {logger_name: {counter: value}}."""
        with self._lock:
            return {name: pc.dump() for name, pc in self._loggers.items()}

    def dump_json(self) -> str:
        return json.dumps(self.dump(), indent=2, sort_keys=True)


class PerfCountersBuilder:
    """ref: src/common/perf_counters.h PerfCountersBuilder."""

    def __init__(self, name: str):
        self._pc = PerfCounters(name)

    def add_u64_counter(self, key: str, doc: str = "") -> "PerfCountersBuilder":
        """Monotonic counter (inc-only), PERFCOUNTER_COUNTER analog."""
        self._pc._counters[key] = _Counter(TYPE_U64, doc, monotonic=True)
        return self

    def add_u64(self, key: str, doc: str = "") -> "PerfCountersBuilder":
        """Gauge (set allowed), plain PERFCOUNTER_U64 analog."""
        self._pc._counters[key] = _Counter(TYPE_U64, doc)
        return self

    def add_time(self, key: str, doc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[key] = _Counter(TYPE_TIME, doc)
        return self

    def add_time_avg(self, key: str, doc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[key] = _Counter(TYPE_LONGRUNAVG, doc)
        return self

    def add_histogram(self, key: str, doc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[key] = _Counter(TYPE_HISTOGRAM, doc)
        return self

    def create_perf_counters(self, register: bool = True) -> PerfCounters:
        """Finalize; registers with the process collection by default, the
        way daemons hand their counters to the CephContext collection."""
        if register:
            PerfCountersCollection.instance().add(self._pc)
        return self._pc
