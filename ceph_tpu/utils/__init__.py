"""Common runtime: config, logging, perf counters.

TPU-native analog of Ceph's common runtime layer (ref: src/common/config.h
ConfigProxy, src/common/perf_counters.h, src/log/Log.cc) — one typed, layered
config schema instead of ~2000 YAML options, subsystem-gated structured
logging instead of dout(), and in-process counters dumped as JSON instead of
an admin socket.
"""

from ceph_tpu.utils.config import Config, ConfigProxy, Option, OPTIONS
from ceph_tpu.utils.logging import get_logger, set_subsys_level
from ceph_tpu.utils.perf_counters import PerfCounters, PerfCountersBuilder
