"""AdminSocket: the per-daemon out-of-band command endpoint.

ref: src/common/admin_socket.{h,cc} — each daemon listens on a unix
socket; ``ceph daemon <sock> <command>`` connects, sends the command,
reads one json reply. Commands register with a handler; every daemon
gets the stock set (perf dump, config show, dump_ops_in_flight,
dump_historic_ops, log dump, help).

Client side: ``daemon_command(path, cmd)`` — the `ceph daemon` verb.
"""

from __future__ import annotations

import asyncio
import inspect
import json
from typing import Callable

from ceph_tpu.utils.logging import dump_recent, get_logger
from ceph_tpu.utils.perf_counters import PerfCountersCollection

log = get_logger("asok")


class AdminSocket:
    def __init__(self, path: str):
        self.path = path
        self._commands: dict[str, tuple[Callable, str]] = {}
        self._server: asyncio.AbstractServer | None = None
        self.register("help", self._help, "list registered commands")
        self.register("perf dump",
                      lambda: PerfCountersCollection.instance().dump(),
                      "dump perf counters")
        self.register("perf histogram dump", _histogram_dump,
                      "TYPE_HISTOGRAM counters as cumulative "
                      "le-bucketed series (count/sum/buckets)")
        self.register("log dump", lambda: {"recent": dump_recent()},
                      "dump the in-memory log ring")

    def register(self, prefix: str, fn: Callable,
                 desc: str = "") -> None:
        """ref: AdminSocket::register_command. ``desc`` is REQUIRED:
        the dump surface is big enough to rot silently, and `help` is
        its only index — an undocumented verb fails registration (the
        test_meta guard enforces the same statically)."""
        if not desc:
            raise ValueError(
                f"admin socket command {prefix!r} registered without "
                f"a description (help would list it blank)")
        self._commands[prefix] = (fn, desc)

    def _help(self) -> dict:
        return {name: desc for name, (_, desc) in
                sorted(self._commands.items())}

    async def start(self) -> None:
        import os
        try:
            os.unlink(self.path)       # stale socket from a SIGKILL
        except OSError:
            pass
        self._server = await asyncio.start_unix_server(
            self._serve, path=self.path)

    async def stop(self) -> None:
        if self._server:
            self._server.close()
        import os
        try:
            os.unlink(self.path)
        except OSError:
            pass

    async def _serve(self, reader, writer) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(),
                                          timeout=5.0)
            try:
                cmd = json.loads(line)
            except json.JSONDecodeError:
                cmd = {"prefix": line.decode(errors="replace").strip()}
            prefix = cmd.get("prefix", "")
            ent = self._commands.get(prefix)
            if ent is None:
                out = {"error": f"unknown command {prefix!r}",
                       "commands": sorted(self._commands)}
            else:
                fn, _ = ent
                result = fn(cmd) if _wants_arg(fn) else fn()
                if inspect.isawaitable(result):
                    result = await result
                out = result
            payload = json.dumps(out, default=str).encode()
            writer.write(len(payload).to_bytes(4, "little") + payload)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, OSError) as e:
            log.dout(5, f"admin socket client error: {e}")
        finally:
            writer.close()


def _histogram_dump() -> dict:
    """Every TYPE_HISTOGRAM counter in the process collection as
    {logger: {counter: {count, sum, buckets: [[le, cumulative]...]}}}
    (ref: `ceph daemon ... perf histogram dump`)."""
    from ceph_tpu.utils.perf_counters import hist_cumulative
    out: dict = {}
    for name, counters in PerfCountersCollection.instance() \
            .dump().items():
        for key, val in counters.items():
            if isinstance(val, dict) and "log2_buckets" in val:
                out.setdefault(name, {})[key] = {
                    "count": val["count"], "sum": val["sum"],
                    "buckets": hist_cumulative(val["log2_buckets"]),
                }
    return out


def _wants_arg(fn: Callable) -> bool:
    try:
        return len(inspect.signature(fn).parameters) >= 1
    except (TypeError, ValueError):
        return False


async def daemon_command(path: str, cmd: dict | str) -> dict:
    """The `ceph daemon <sock> <cmd>` client verb."""
    reader, writer = await asyncio.open_unix_connection(path)
    try:
        payload = json.dumps(cmd if isinstance(cmd, dict)
                             else {"prefix": cmd})
        writer.write(payload.encode() + b"\n")
        await writer.drain()
        ln = int.from_bytes(await reader.readexactly(4), "little")
        return json.loads(await reader.readexactly(ln))
    finally:
        writer.close()
