"""Refcounted per-key asyncio locks.

Several single-process daemons serialize mutations per resource path —
the MDS's per-path open lock (Locker's file-lock role) and the RGW
gateway's per-(bucket,key) bucket-index lock (the bucket-index OSD
class ops' role). Both need the same idiom: an ``asyncio.Lock`` per
live key, dropped when the last holder leaves so the table does not
grow with every key ever touched. One implementation, shared, so a
future fix (e.g. cancellation-safety of the refcount) cannot miss a
copy.
"""

from __future__ import annotations

import asyncio
import contextlib


class KeyedLocks:
    """``async with locks.hold(key):`` — serialize per hashable key."""

    def __init__(self) -> None:
        self._locks: dict = {}
        self._users: dict = {}

    @contextlib.asynccontextmanager
    async def hold(self, key):
        lock = self._locks.setdefault(key, asyncio.Lock())
        # refcount BEFORE awaiting the lock: the count covers waiters,
        # so the dict entry cannot be dropped (and a second Lock object
        # created) while someone is still queued on the first one
        self._users[key] = self._users.get(key, 0) + 1
        try:
            async with lock:
                yield
        finally:
            self._users[key] -= 1
            if self._users[key] <= 0:
                self._users.pop(key, None)
                self._locks.pop(key, None)
