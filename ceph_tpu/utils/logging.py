"""Subsystem-gated structured logging.

TPU-native analog of Ceph's dout() machinery (ref: src/common/debug.h dout,
src/common/subsys.h subsystem table, src/log/Log.cc async writer). Each
subsystem has a gate level; a record is emitted only if its level <= gate,
mirroring ``debug_<subsys> = N`` config. We keep a bounded in-memory ring of
recent records (ref: src/log/Log.cc m_recent) dumpable on failure, and lean on
Python's logging for the writer instead of a custom async thread.
"""

from __future__ import annotations

import collections
import logging
import sys
import threading
import time

# Mirrors the reference subsystem list where analogous
# (ref: src/common/subsys.h). Default gate level 1 (errors/milestones only).
SUBSYS = {
    "crush": 1,
    "osd": 1,
    "ec": 1,
    "bench": 1,
    "mon": 1,
    "sim": 1,
    "tpu": 1,
    "interop": 1,
}

_RING_SIZE = 4096
_ring: collections.deque = collections.deque(maxlen=_RING_SIZE)
_lock = threading.Lock()
_levels = dict(SUBSYS)

_root = logging.getLogger("ceph_tpu")
if not _root.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(levelname).1s %(message)s"))
    _root.addHandler(_h)
    _root.setLevel(logging.DEBUG)
    _root.propagate = False


def set_subsys_level(subsys: str, level: int) -> None:
    """``debug_<subsys> = level`` analog."""
    _levels[subsys] = level


def get_subsys_level(subsys: str) -> int:
    return _levels.get(subsys, 0)


def dump_recent() -> list[str]:
    """Recent-record ring, dumped on crash (ref: src/log/Log.cc dump_recent)."""
    with _lock:
        return list(_ring)


class SubsysLogger:
    """``dout(level) << msg`` analog: ``log.dout(level, msg, **fields)``."""

    def __init__(self, subsys: str):
        if subsys not in SUBSYS:
            SUBSYS[subsys] = 1
            _levels.setdefault(subsys, 1)
        self.subsys = subsys
        self._logger = _root.getChild(subsys)

    def dout(self, level: int, msg: str, **fields) -> None:
        record = f"[{self.subsys}:{level}] {msg}" + (
            " " + " ".join(f"{k}={v}" for k, v in fields.items())
            if fields else "")
        with _lock:
            _ring.append(f"{time.time():.6f} {record}")
        if level <= _levels.get(self.subsys, 0):
            self._logger.info(record)

    def error(self, msg: str, **fields) -> None:
        record = f"[{self.subsys}:-1] {msg}" + (
            " " + " ".join(f"{k}={v}" for k, v in fields.items())
            if fields else "")
        with _lock:
            _ring.append(f"{time.time():.6f} {record}")
        self._logger.error(record)


_loggers: dict[str, SubsysLogger] = {}


def get_logger(subsys: str) -> SubsysLogger:
    if subsys not in _loggers:
        _loggers[subsys] = SubsysLogger(subsys)
    return _loggers[subsys]
