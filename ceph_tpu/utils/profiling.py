"""Profiler harness: jax.profiler traces + MFU reporting.

TPU analog of the reference's tracing stack (SURVEY.md §5.1: PerfCounters
+ LTTng/Blkin spans): ``trace()`` wraps ``jax.profiler.trace`` (Perfetto/
TensorBoard-readable) around a benchmark region, degrading to a no-op on
platforms where the profiler backend is unavailable (the remote-TPU
tunnel in this sandbox does not export a profiler endpoint). MFU numbers
come from ceph_tpu.utils.roofline and are embedded in every benchmark
record, not here.
"""

from __future__ import annotations

import contextlib

from ceph_tpu.utils.logging import get_logger

log = get_logger("prof")


@contextlib.contextmanager
def trace(log_dir: str | None):
    """Profile the enclosed region into log_dir (None = no-op).

    View with TensorBoard or ui.perfetto.dev. Failures to start the
    profiler (unsupported backend) log and continue — profiling must
    never break a benchmark run.
    """
    if not log_dir:
        yield
        return
    import jax

    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # pragma: no cover - platform dependent
        log.dout(1, "profiler unavailable", error=str(e))
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                log.dout(1, "profile written", dir=log_dir)
            except Exception as e:  # pragma: no cover
                log.dout(1, "profiler stop failed", error=str(e))


def annotate(name: str):
    """Named sub-region (TraceAnnotation) for kernel attribution."""
    import jax

    return jax.profiler.TraceAnnotation(name)
